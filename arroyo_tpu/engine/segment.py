"""Whole-segment XLA compilation: one jitted call per micro-batch.

A chained run of shuffle-free operators (optimizer.chain_graph) still costs
N Python hook dispatches per micro-batch, each bailing to numpy — the
profiler (obs/profile.py) can attribute that overhead per operator but
nothing removes it. This module traces the chain's data path — ValueOperator
projections/filters, KeyOperator key calculation + routing hash, the
WatermarkGenerator's per-batch max, and the window operators' insert prep
(bins + accumulator inputs) — into ONE ``jax.jit`` batch-in/batch-out
function, compiled once per (segment, input schema) and cached process-wide.

Design rules (correctness first — compilation must never be a risk):

  - **Masked, padded execution.** Filters cannot change array shapes under
    XLA, so the trace threads a validity mask instead of compacting; inputs
    pad to the next power of two so varying batch sizes reuse a handful of
    compiled shapes instead of retracing per batch (the LR111 bug class).
    The host compacts once, after the traced call — the same single filter
    pass the interpreted path pays.
  - **State stays where it was.** Member mutable state (watermark state
    machine, window aggregator tables, late-data boundaries) is NOT moved
    into the trace: the traced function is pure, and per-member host
    finishers feed its outputs into the members' existing state-mutation
    methods (``WatermarkGenerator.observe_batch_max``, the window
    operators' ``insert_arrays``). Checkpoint/restore therefore runs the
    exact interpreted code, byte for byte — the LR2xx state audit's class
    model is the carry contract, enforced by reuse instead of by a
    parallel implementation.
  - **Verify-then-trust.** The first batch of every freshly compiled
    (segment, schema) entry runs BOTH ways: the traced function and a pure
    numpy reference that mirrors the interpreted members exactly. Any
    difference — values or dtypes, bit for bit — falls the segment back to
    the interpreted path permanently (structured ``SEGMENT_FALLBACK``
    WARN), as does any trace failure. A fallback is never a job failure.
  - **Signals stay interpreted.** Watermarks, barriers, stop, and EOF take
    the existing ChainCollector path, so barrier alignment, coalescing
    flush rules, and checkpoint recovery are untouched.

Cache keys include the serialized member configs, the input column
(name, dtype) signature, and the node parallelism, so a schema or
parallelism change recompiles rather than mis-executes
(``segment.compile.cache-max`` bounds the LRU).

jax/XLA imports happen at trace time, not module import time: plan-time
marking (optimizer.chain_graph) must stay cheap enough for control-plane
processes that never run a batch.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..config import config
from ..expr import (BinOp, Case, Cast, Col, Expr, Func, Lit, Neg, Not,
                    eval_expr)
from ..graph import OpName

# scalar functions whose jnp evaluation is bit-identical to the numpy path
# (elementwise, IEEE-exact or pure integer). Transcendentals (exp/ln/log10/
# power) and decimal-scaled round() are NOT listed: libm and XLA may round
# differently, which would break byte-exact goldens.
_TRACEABLE_FUNCS = {"abs", "floor", "ceil", "sqrt", "extract_epoch",
                    "date_trunc_micros", "to_timestamp_micros"}

_TRACEABLE_BINOPS = {"+", "-", "*", "/", "%", "==", "!=", "<", "<=", ">",
                     ">=", "and", "or"}

# ops implemented with BOTH numpy and jnp twins in expr.py yet deliberately
# kept out of the allowlist: their two implementations are not bit-exact
# (libm vs XLA rounding for the transcendentals; decimal-scaled round).
# Trace-safety rule LR303 audits the three sets against expr.py — an
# allowlisted op with no trace builder is an ERROR, a dual-implemented op
# in neither this set nor the allowlist is a WARN (silently uncompiled),
# and an op in both sets is a contradiction. The allowlisted set itself is
# proven bit-exact across the dtype matrix by the runtime parity oracle
# (tests/test_trace_audit.py).
_KNOWN_DIVERGENT_FUNCS = {"ln", "log10", "exp", "power", "round"}

_KNOWN_DIVERGENT_BINOPS: set[str] = set()


def expr_traceable(e: Expr) -> Optional[str]:
    """None if ``e`` evaluates identically under eval_jnp, else the reason
    it cannot (used both for plan-time marking and the runtime gate)."""
    if isinstance(e, Col):
        return None
    if isinstance(e, Lit):
        if isinstance(e.value, (bool, int, float)):
            return None
        return f"non-numeric literal {e.value!r}"
    if isinstance(e, BinOp):
        if e.op not in _TRACEABLE_BINOPS:
            return f"operator {e.op!r}"
        return expr_traceable(e.left) or expr_traceable(e.right)
    if isinstance(e, (Not, Neg)):
        return expr_traceable(e.inner)
    if isinstance(e, Cast):
        if e.dtype == "string":
            return "cast to string"
        return expr_traceable(e.inner)
    if isinstance(e, Case):
        if e.otherwise is None:
            # numpy leaves unmatched rows holding the first branch's value,
            # jnp would yield NaN — don't trace the divergent shape
            return "CASE without ELSE"
        for c, v in e.branches:
            r = expr_traceable(c) or expr_traceable(v)
            if r:
                return r
        return expr_traceable(e.otherwise)
    if isinstance(e, Func):
        if e.name not in _TRACEABLE_FUNCS:
            return f"function {e.name}()"
        for a in e.args:
            r = expr_traceable(a)
            if r:
                return r
        return None
    return f"expression {type(e).__name__}"  # UdfExpr and anything unknown


def _referenced(exprs) -> set[str]:
    out: set[str] = set()
    for e in exprs:
        if e is not None:
            out |= e.columns()
    return out


# ------------------------------------------------------- plan-time marking

_WINDOW_OPS = (OpName.TUMBLING_AGGREGATE.value, OpName.SLIDING_AGGREGATE.value)


def _scan_members(members: list[tuple[str, dict]]) -> tuple[int, bool, str]:
    """(traceable prefix length, ends in a window insert, stop reason)."""
    k = 0
    insert = False
    stop = "end of chain"
    for op, cfg in members:
        reason = _member_traceable(op, cfg, first=k == 0)
        if reason is not None:
            stop = reason
            break
        k += 1
        if op in _WINDOW_OPS:
            insert = True
            stop = "window insert terminates the traced prefix"
            break
    return k, insert, stop


def segment_marking(members: list[tuple[str, dict]]) -> Optional[dict]:
    """Static compilability of a chained run: the maximal traceable PREFIX
    of the member list, judged by op kind and expression shape (runtime
    still gates on actual column dtypes and verifies the first batch).
    Returns ``{"prefix": k, "insert": bool, "stop": reason, "mesh": bool}``
    when the prefix is worth compiling (>= 2 members), else None."""
    k, insert, stop = _scan_members(members)
    if k < 2:
        return None
    return {"prefix": k, "insert": insert, "stop": stop,
            "mesh": insert and _mesh_markable(members, k)}


def _mesh_markable(members: list[tuple[str, dict]], k: int) -> bool:
    """Static half of the mesh-fusion gate: can this insert-terminated
    prefix run as ONE shard_map'd program feeding the sharded aggregate
    in-program? In-trace filters ban it — the fused step commits rows on
    device, so the host prologue (late split, open-bin bookkeeping) must
    see exactly the rows the program inserts. The LEADING member's filter
    is fine (the mesh path force-hoists it to the host); any later
    member's filter has nowhere to go."""
    for op, cfg in members[1:k]:
        if op == OpName.VALUE.value and cfg.get("filter") is not None:
            return False
    return True


def segment_reject_reason(members: list[tuple[str, dict]]) -> Optional[str]:
    """Human-readable ``not compilable: <reason>`` for a chained run that
    ``segment_marking`` declined to mark, or None when it IS marked.

    Attached to the chained node's config at plan time (optimizer.
    chain_graph) and surfaced by ``check`` (AR009 INFO), ``explain``,
    ``top``, and the executed-graph view — so an uncompiled segment is a
    plan-time explained fact, not an unexplained runtime fallback."""
    k, _insert, stop = _scan_members(members)
    if k >= 2:
        return None
    # the stop reason leads: narrow renderers (`top` truncates) must show
    # the actionable part, not a boilerplate prefix
    return f"not compilable: {stop} (traceable prefix {k} < 2)"


def _member_traceable(op: str, cfg: dict, first: bool = False) -> Optional[str]:
    if op == OpName.VALUE.value:
        # a FIRST member's filter is hoisted to the host (evaluated exactly
        # as interpreted, object columns and all), so only its projections
        # must trace
        exprs = ([] if first else [cfg.get("filter")]) + \
            [e for _n, e in (cfg.get("projections") or [])]
        for e in exprs:
            if e is None:
                continue
            r = expr_traceable(e)
            if r:
                return f"value: {r}"
        return None
    if op == OpName.KEY.value:
        for _n, e in cfg.get("keys", []):
            r = expr_traceable(e)
            if r:
                return f"key: {r}"
        return None
    if op == OpName.WATERMARK.value:
        r = expr_traceable(cfg["expr"])
        return f"watermark: {r}" if r else None
    if op in _WINDOW_OPS:
        for _n, kind, e in cfg.get("aggregates", []):
            if kind.startswith("udaf:") or kind in ("collect", "count_distinct"):
                return f"window: {kind} accumulator is host-resident"
            if e is not None:
                r = expr_traceable(e)
                if r:
                    return f"window: {r}"
        return None
    return f"operator {op} is not traceable"


# ------------------------------------------------------------ jnp helpers


def _splitmix64_jnp(x):
    import jax.numpy as jnp

    c1 = jnp.uint64(0x9E3779B97F4A7C15)
    c2 = jnp.uint64(0xBF58476D1CE4E5B9)
    c3 = jnp.uint64(0x94D049BB133111EB)
    z = x + c1
    z = (z ^ (z >> jnp.uint64(30))) * c2
    z = (z ^ (z >> jnp.uint64(27))) * c3
    return z ^ (z >> jnp.uint64(31))


def _hash_column_jnp(col):
    """Traced twin of hashing.hash_column for numeric/bool columns
    (differentially covered by the first-batch verification against the
    host path, which itself cross-checks the C++ kernel)."""
    import jax.numpy as jnp
    from jax import lax

    if col.dtype.kind == "f":
        col = jnp.where(col == 0.0, 0.0, col)  # canonicalize -0.0
        bits = lax.bitcast_convert_type(col.astype(jnp.float64), jnp.uint64)
        return _splitmix64_jnp(bits)
    if col.dtype == np.bool_:
        return _splitmix64_jnp(col.astype(jnp.uint64))
    bits = lax.bitcast_convert_type(col.astype(jnp.int64), jnp.uint64)
    return _splitmix64_jnp(bits)


def _hash_columns_jnp(cols):
    import jax.numpy as jnp

    h = _hash_column_jnp(cols[0])
    for c in cols[1:]:
        h2 = _hash_column_jnp(c)
        h = _splitmix64_jnp(h ^ (h2 + jnp.uint64(0x9E3779B97F4A7C15)))
    return h


def _as_full(v, p):
    """Broadcast a traced scalar to a full column the way eval_expr's
    np.full does (weak-typed python scalars promote identically under
    jax x64)."""
    import jax.numpy as jnp

    v = jnp.asarray(v)
    if v.ndim == 0:
        return jnp.broadcast_to(v, (p,))
    return v


def _dtype_floor(dt: np.dtype):
    """Identity element for a masked max of dtype ``dt``."""
    if np.issubdtype(dt, np.floating):
        return np.array(-np.inf, dtype=dt)
    return np.iinfo(dt).min


# ------------------------------------------------------------- stage plans
#
# A bound segment is a list of small stage records; ``_trace_fn`` folds them
# into one traced function and ``_reference`` executes the interpreted
# members' exact numpy logic for the first-batch verification. Both read the
# SAME records, so a drift between them is a verification failure, not a
# silent divergence.


class _Stage:
    __slots__ = ("kind", "member_index", "member")

    def __init__(self, kind: str, member_index: int, member):
        self.kind = kind  # "value" | "key" | "wm" | "insert"
        self.member_index = member_index
        self.member = member


class _SegmentPlan:
    """Static description of what the traced function consumes/produces."""

    def __init__(self):
        self.stages: list[_Stage] = []
        self.prefix = 0  # members covered (including an insert member)
        self.insert: Optional[_Stage] = None
        self.traced_in: list[str] = []  # input columns fed to the trace
        self.traced_out: list[str] = []  # traced output names, fixed order
        self.insert_has_key = False
        # final batch assembly: ordered (name, "host" | "traced")
        self.out_plan: list[tuple[str, str]] = []
        self.emits_batch = True  # False in insert mode
        self.wm_stages: list[_Stage] = []
        # leading-filter hoist: the FIRST member's filter evaluates on the
        # host (eval_expr, exactly the interpreted path — object columns
        # allowed) and the traced inputs compact BEFORE the trace. A
        # selective leading filter otherwise forces the whole trace to
        # compute on mostly-dead padded rows — measurably slower than
        # interpreted's compact-then-compute on e.g. q8's rare-event
        # branches. Filters in LATER members still trace as mask narrowing.
        self.prefilter: Optional[Expr] = None


class SegmentUntraceable(Exception):
    """Raised during binding when the actual batch makes the marked
    segment untraceable (object columns, host accumulators, ...)."""


# a leading filter keeping less than this fraction of rows is hoisted to
# the host: tracing a mostly-dead padded batch costs more than interpreted's
# compact-then-compute, while a high-survival filter fuses profitably
_HOIST_SELECTIVITY = 0.5


def _bind(members, prefix: int, batch: Batch, probe: bool = False,
          hoist: bool = False) -> _SegmentPlan:
    """Resolve the plan against the first batch's real columns: decide
    which inputs the trace consumes, the output assembly order, and gate
    every referenced column on a numeric/bool dtype. ``probe`` builds a
    plan only for a one-off ``_reference`` run (the insert member's
    key-transport setup), skipping the trace-only gates; ``hoist`` moves
    the leading member's filter out of the trace (see _HOIST_SELECTIVITY
    and SegmentRunner._should_hoist)."""
    from ..operators.builtin import (KeyOperator, ValueOperator,
                                     WatermarkGenerator)
    from ..windows.sliding import SlidingAggregate
    from ..windows.tumbling import TumblingAggregate

    plan = _SegmentPlan()
    plan.prefix = prefix
    # provenance: name -> None (verbatim input column) | "computed";
    # ``order`` mirrors the dict insertion order the interpreted members
    # produce, so the emitted Batch's column order is byte-identical
    prov: dict[str, Optional[str]] = {n: None for n in batch.columns}
    order: list[str] = list(batch.columns)
    referenced: set[str] = set()

    def ref(exprs):
        for name in _referenced(exprs):
            if name not in prov:
                raise SegmentUntraceable(
                    f"expression references unknown column {name!r}")
            if prov[name] is None:
                referenced.add(name)

    for i in range(prefix):
        m = members[i]
        if isinstance(m, ValueOperator):
            st = _Stage("value", i, m)
            if i == 0 and m.filter is not None and hoist:
                # hoisted: evaluated host-side pre-trace, never in-trace
                plan.prefilter = m.filter
                for name in m.filter.columns():
                    if name not in prov:
                        raise SegmentUntraceable(
                            f"filter references unknown column {name!r}")
                ref([e for _n, e in (m.projections or [])])
            else:
                ref([m.filter] + [e for _n, e in (m.projections or [])])
            if m.projections is not None:
                new_order: list[str] = []
                new_prov: dict[str, Optional[str]] = {}
                for name, _e in m.projections:
                    if name not in new_prov:
                        new_order.append(name)
                    new_prov[name] = "computed"
                if TIMESTAMP_FIELD not in new_prov:
                    if TIMESTAMP_FIELD not in prov:
                        raise SegmentUntraceable("batch has no _timestamp")
                    new_order.append(TIMESTAMP_FIELD)
                    new_prov[TIMESTAMP_FIELD] = prov[TIMESTAMP_FIELD]
                for carried in (KEY_FIELD, "_is_retract"):
                    if carried in prov and carried not in new_prov:
                        new_order.append(carried)
                        new_prov[carried] = prov[carried]
                order, prov = new_order, new_prov
        elif isinstance(m, KeyOperator):
            st = _Stage("key", i, m)
            ref([e for _n, e in m.keys])
            for name, _e in m.keys:
                if name not in prov:
                    order.append(name)
                prov[name] = "computed"
            if KEY_FIELD not in prov:
                order.append(KEY_FIELD)
            prov[KEY_FIELD] = "computed"
        elif isinstance(m, WatermarkGenerator):
            st = _Stage("wm", i, m)
            ref([m.expr])
            plan.wm_stages.append(st)
        elif isinstance(m, (TumblingAggregate, SlidingAggregate)):
            st = _Stage("insert", i, m)
            if m.lane_key_fields is None:
                raise SegmentUntraceable("window key transport unresolved")
            if m.dict_key_fields:
                raise SegmentUntraceable(
                    f"window group-by columns {m.dict_key_fields} are "
                    f"non-numeric (host key dictionary)")
            if "collect" in m.acc_kinds:
                raise SegmentUntraceable("collect accumulator is host-resident")
            ref([e for e in m.acc_inputs if e is not None])
            if TIMESTAMP_FIELD not in prov:
                raise SegmentUntraceable("window input has no _timestamp")
            if prov[TIMESTAMP_FIELD] is None:
                referenced.add(TIMESTAMP_FIELD)
            if KEY_FIELD in prov:
                plan.insert_has_key = True
                if prov[KEY_FIELD] is None:
                    referenced.add(KEY_FIELD)
            plan.insert = st
            plan.emits_batch = False
        else:
            raise SegmentUntraceable(f"member {m.name()} is not traceable")
        plan.stages.append(st)

    if not probe:
        # dtype gate: every input column the trace consumes must be numeric
        for name in sorted(referenced):
            dt = np.asarray(batch.columns[name]).dtype
            if dt.kind not in "biuf":
                raise SegmentUntraceable(f"column {name!r} has dtype {dt} "
                                         f"(only numeric/bool columns trace)")
        if not referenced:
            raise SegmentUntraceable("segment computes nothing traceable")
    plan.traced_in = sorted(referenced)
    if plan.emits_batch:
        for name in order:
            plan.out_plan.append(
                (name, "host" if prov.get(name) is None else "traced"))
        plan.traced_out = [n for n, src in plan.out_plan if src == "traced"]
    else:
        m = plan.insert.member
        plan.traced_out = ["__bins"]
        if plan.insert_has_key:
            plan.traced_out.append("__hash")
        plan.traced_out += [f"__val{i}" for i, inp in enumerate(m.acc_inputs)
                            if inp is not None]
    return plan


def _insert_step(member) -> int:
    """Bin width of a window insert: tumbling bins by the window width,
    sliding by the slide."""
    from ..windows.tumbling import TumblingAggregate

    return member.width if isinstance(member, TumblingAggregate) else member.slide


# ----------------------------------------------------------------- tracing


def _trace_fn(plan: _SegmentPlan) -> Callable:
    """Build the single traced function for a bound plan.

    Traced signature: ``fn(n, *in_arrays)``, every array padded to one
    static length P; returns ``(outs, mask, aux)`` where ``outs`` follow
    ``plan.traced_out`` order, ``mask`` selects valid rows (None when no
    member filters — the padding tail is then dropped by slicing), and
    ``aux`` carries one ``(batch_max, valid_count)`` pair per watermark
    stage."""
    import jax
    import jax.numpy as jnp

    # pin 64-bit jax semantics BEFORE the first trace: without it a chain
    # that never touches a device kernel (value/key/wm-only — nothing has
    # imported arroyo_tpu.ops) traces under default 32-bit jax, int64
    # inputs downcast, and every first-batch verification fails into a
    # permanent unexplained fallback (trace-safety rule LR304)
    from ..ops import require_x64

    require_x64()

    def fn(n, *arrays):
        p = arrays[0].shape[0]
        cols: dict[str, Any] = dict(zip(plan.traced_in, arrays))
        # dtype pinned: bare arange would follow the jax_enable_x64 flag
        # (int32 by default) while the numpy twin is fixed 64-bit (LR304)
        base = jnp.arange(p, dtype=jnp.int64) < n  # padding-tail invalidity
        valid = None  # narrows at each filter; None = all real rows valid
        aux: list[Any] = []
        outs: dict[str, Any] = {}
        for si, st in enumerate(plan.stages):
            m = st.member
            if st.kind == "value":
                hoisted = si == 0 and plan.prefilter is not None
                if m.filter is not None and not hoisted:
                    f = jnp.broadcast_to(
                        jnp.asarray(m.filter.eval_jnp(cols), dtype=bool), (p,))
                    valid = (base & f) if valid is None else (valid & f)
                if m.projections is not None:
                    new = {}
                    for name, e in m.projections:
                        new[name] = _as_full(e.eval_jnp(cols), p)
                    for carried in (TIMESTAMP_FIELD, KEY_FIELD, "_is_retract"):
                        if carried not in new and carried in cols:
                            new[carried] = cols[carried]
                    cols = new
            elif st.kind == "key":
                key_cols = []
                for name, e in m.keys:
                    c = _as_full(e.eval_jnp(cols), p)
                    cols[name] = c
                    key_cols.append(c)
                cols[KEY_FIELD] = _hash_columns_jnp(key_cols)
            elif st.kind == "wm":
                vals = _as_full(m.expr.eval_jnp(cols), p)
                eff = base if valid is None else valid
                floor = _dtype_floor(np.dtype(vals.dtype))
                aux.extend([jnp.max(jnp.where(eff, vals, floor)),
                            jnp.sum(eff)])
            else:  # insert
                outs["__bins"] = cols[TIMESTAMP_FIELD] // _insert_step(m)
                if plan.insert_has_key:
                    outs["__hash"] = cols[KEY_FIELD].astype(jnp.uint64)
                for i, (inp, dt) in enumerate(zip(m.acc_inputs, m.acc_dtypes)):
                    if inp is not None:
                        outs[f"__val{i}"] = _as_full(
                            inp.eval_jnp(cols), p).astype(dt)
        if plan.emits_batch:
            for name in plan.traced_out:
                outs[name] = cols[name]
        return tuple(outs[k] for k in plan.traced_out), valid, tuple(aux)

    jitted = jax.jit(fn)

    def run(n: int, arrays: list[np.ndarray]):
        out_tuple, mask, aux = jitted(np.int64(n), *arrays)
        return dict(zip(plan.traced_out, out_tuple)), mask, aux

    return run


# --------------------------------------------------------------- reference


def _reference(plan: _SegmentPlan, batch: Batch) -> dict:
    """Pure-numpy twin of the interpreted member hooks, mutating nothing:
    the oracle the compiled outputs must match bit for bit. Structure
    mirrors ValueOperator/KeyOperator/WatermarkGenerator and the window
    operators' process_batch exactly (compaction at each filter, eval_expr
    per expression, hash_columns for routing keys)."""
    from ..hashing import hash_columns

    cols = dict(batch.columns)
    n = batch.num_rows
    aux: list[tuple[Optional[int], int]] = []
    res: dict[str, Any] = {}
    for st in plan.stages:
        m = st.member
        if st.kind == "value":
            if m.filter is not None:
                fmask = np.asarray(eval_expr(m.filter, cols, n), dtype=bool)
                if not fmask.all():
                    cols = {k: v[fmask] for k, v in cols.items()}
                    n = int(fmask.sum())
            if m.projections is not None:
                new = {}
                for name, e in m.projections:
                    new[name] = eval_expr(e, cols, n)
                if TIMESTAMP_FIELD not in new:
                    new[TIMESTAMP_FIELD] = cols[TIMESTAMP_FIELD]
                if KEY_FIELD in cols and KEY_FIELD not in new:
                    new[KEY_FIELD] = cols[KEY_FIELD]
                if "_is_retract" in cols and "_is_retract" not in new:
                    new["_is_retract"] = cols["_is_retract"]
                cols = new
        elif st.kind == "key":
            key_cols = []
            for name, e in m.keys:
                c = eval_expr(e, cols, n)
                cols[name] = c
                key_cols.append(np.asarray(c))
            cols[KEY_FIELD] = (hash_columns(key_cols) if n
                               else np.zeros(0, dtype=np.uint64))
        elif st.kind == "wm":
            if n:
                vals = np.asarray(eval_expr(m.expr, cols, n))
                aux.append((int(vals.max()), n))
            else:
                aux.append((None, 0))
        else:  # insert
            res["__bins"] = np.asarray(cols[TIMESTAMP_FIELD]) // _insert_step(m)
            if plan.insert_has_key:
                res["__hash"] = np.asarray(cols[KEY_FIELD]).astype(np.uint64)
            for i, (inp, dt) in enumerate(zip(m.acc_inputs, m.acc_dtypes)):
                if inp is not None:
                    res[f"__val{i}"] = np.asarray(
                        eval_expr(inp, cols, n)).astype(dt)
    if plan.emits_batch:
        for name, _src in plan.out_plan:
            res[name] = np.asarray(cols[name])
    return {"cols": res, "aux": aux, "n": n}


# ----------------------------------------------------------- compiled entry


_PAD_QUANTUM = 4096


def _padded_size(n: int) -> int:
    """Static trace length for an n-row batch: next power of two below the
    quantum, then quantum multiples. Bounds the number of distinct compiled
    shapes (the retrace-per-batch bug) at ~log2(quantum) + max_rows/quantum
    while capping padding waste at one quantum (~12% worst case) — a pure
    pow2 schedule wasted up to 2x on just-over-a-power batch sizes, which
    showed up directly as compiled-vs-interpreted regression on the A/B."""
    if n <= 16:
        return 16
    if n < _PAD_QUANTUM:
        return 1 << (n - 1).bit_length()
    return -(-n // _PAD_QUANTUM) * _PAD_QUANTUM


class CompiledSegment:
    """One (segment, schema) cache entry: the bound plan + traced fn,
    shared by every subtask (and post-restore incarnation) of the node."""

    def __init__(self, plan: _SegmentPlan, fn: Callable, sig: tuple):
        self.plan = plan
        self.fn = fn
        self.sig = sig
        self._shapes: set[int] = set()
        self._lock = threading.Lock()

    def execute(self, batch: Batch, job_id: str, observe: bool = True,
                min_rows: int = 0) -> Optional[dict]:
        """Run the traced function on one batch; returns the same structure
        ``_reference`` produces (compacted numpy arrays + aux pairs), or
        None when fewer than ``min_rows`` rows survive the hoisted filter
        (too small to pay the jit dispatch — caller runs interpreted)."""
        fmask = None
        n = batch.num_rows
        if self.plan.prefilter is not None:
            fm = np.asarray(
                eval_expr(self.plan.prefilter, batch.columns, n), dtype=bool)
            if not fm.any():
                # the interpreted leading member emits nothing: downstream
                # stages never see this batch
                return {"cols": {}, "n": 0,
                        "aux": [(None, 0)] * len(self.plan.wm_stages)}
            if not fm.all():
                survivors = int(fm.sum())
                if survivors < min_rows:
                    # a selective filter left too few rows for the jit call
                    # to pay for itself: hand the batch back (the caller
                    # runs it interpreted; nothing was mutated here)
                    return None
                fmask = fm
                n = survivors
        p = _padded_size(n)
        arrays = []
        for name in self.plan.traced_in:
            a = np.asarray(batch.columns[name])
            if fmask is not None:
                # fused compact+pad: one pass per column (the same single
                # filter pass the interpreted member pays — a separate
                # compact-then-pad double copy showed up on the A/B)
                buf = np.zeros(p, dtype=a.dtype)
                np.compress(fmask, a, out=buf[:n])
                a = buf
            elif p > n:
                padded = np.zeros(p, dtype=a.dtype)
                padded[:n] = a
                a = padded
            arrays.append(a)
        with self._lock:
            new_shape = p not in self._shapes
            self._shapes.add(p)
        if new_shape and observe:
            # per-shape XLA compile (bucketed by the pow2 padding): timed
            # into arroyo_segment_compile_seconds so retraces stay visible
            t0 = time.perf_counter()
            outs, mask, aux = self.fn(n, arrays)
            from ..metrics import registry

            registry.observe_segment_compile(job_id, time.perf_counter() - t0)
        else:
            outs, mask, aux = self.fn(n, arrays)
        def host_col(name):
            # passthrough columns never enter the trace; they only pay the
            # hoisted filter's compaction, exactly like interpreted
            col = batch.columns[name]
            return col[fmask] if fmask is not None else col

        if mask is not None:
            idx = np.flatnonzero(np.asarray(mask))
            k = len(idx)
            res = {name: np.asarray(a)[idx] for name, a in outs.items()}
            if self.plan.emits_batch:
                for name, src in self.plan.out_plan:
                    if src == "host":
                        res[name] = host_col(name)[idx]
        else:
            k = n
            res = {name: np.asarray(a)[:n] for name, a in outs.items()}
            if self.plan.emits_batch:
                for name, src in self.plan.out_plan:
                    if src == "host":
                        res[name] = host_col(name)
        pairs = []
        it = iter(aux)
        for mx in it:
            cnt = int(next(it))
            pairs.append((int(mx) if cnt else None, cnt))
        return {"cols": res, "aux": pairs, "n": k}


def _outputs_equal(got: dict, want: dict) -> Optional[str]:
    """Bitwise comparison of an execute() result against the reference;
    returns a mismatch description or None."""
    if got["n"] != want["n"]:
        return f"row count {got['n']} != {want['n']}"
    if got["aux"] != want["aux"]:
        return f"watermark aux {got['aux']} != {want['aux']}"
    if got["n"] == 0 and not got["cols"]:
        return None  # hoisted filter killed the whole batch: nothing flows
    gc, wc = got["cols"], want["cols"]
    if set(gc) != set(wc):
        return f"column set {sorted(gc)} != {sorted(wc)}"
    for name in wc:
        g, w = np.asarray(gc[name]), np.asarray(wc[name])
        if g.dtype != w.dtype:
            return f"{name}: dtype {g.dtype} != {w.dtype}"
        if g.dtype == object:
            if len(g) != len(w) or any(
                    not (a is None and b is None) and a != b
                    for a, b in zip(g, w)):
                return f"{name}: object values differ"
        elif g.tobytes() != w.tobytes():
            return f"{name}: values differ"
    return None


# ------------------------------------------------------------ global cache


class _SegmentCache:
    """Process-wide LRU of compiled (and known-untraceable) segments, so
    the N subtasks of a node — and post-restore incarnations — share one
    compile."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Any]" = OrderedDict()

    def _max(self) -> int:
        return int(config().get("segment.compile.cache-max", 32) or 32)

    def lookup(self, key: tuple) -> tuple[bool, Any]:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True, self._entries[key]
            return False, None

    def store(self, key: tuple, entry) -> None:
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self._max():
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


segment_cache = _SegmentCache()


class _Fallback:
    """Negative cache entry: this (segment, schema) is untraceable."""

    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason


# ----------------------------------------------------------------- runner


# per-process micro-batch commit counts for mesh-armed runners: "fused" =
# committed through the ONE shard_map'd program, "host" = committed through
# the per-batch host path (first-batch verification, small batches, post-
# failure recovery). bench.py --mesh-ab embeds these so "one jitted call
# per step" is provable from the artifact, and the mesh tests assert the
# fused path actually engaged (a silently-host run would still be correct).
_MESH_DISPATCH = {"fused": 0, "host": 0}


def mesh_dispatch_counts() -> dict:
    return dict(_MESH_DISPATCH)


def reset_mesh_dispatch_counts() -> None:
    for k in _MESH_DISPATCH:
        _MESH_DISPATCH[k] = 0


class SegmentRunner:
    """Per-task driver: owns the compile/fallback decision for one chained
    operator and runs the compiled function per batch. The task run loop
    invokes ``process_batch`` in place of the chain's member hook loop."""

    def __init__(self, chain, ctx, metrics, marking: dict):
        self.chain = chain
        self.ctx = ctx
        self.metrics = metrics
        self.marking = marking
        self._entry: Optional[CompiledSegment] = None
        self._sig: Optional[tuple] = None
        self._fallback = False
        self._min_rows = int(config().get("segment.compile.min-rows", 8192))
        # cost demotion (not a fallback): a run of consecutive batches
        # whose hoisted-filter survivors stayed under min-rows proves the
        # stream too selective for the jit to pay; latch to interpreted so
        # later batches stop paying a throwaway filter evaluation
        self._small_streak = 0
        # mesh fusion (device.mesh-devices > 1 + a mesh-markable insert
        # prefix): the traced prefix runs per-shard inside the sharded
        # aggregate's ONE shard_map'd program instead of as a host jit
        # followed by a device exchange step. _mesh_n > 1 also forces the
        # leading-filter hoist (_should_hoist) — the fused program has no
        # mask output.
        mesh_n = int(config().get("device.mesh-devices", 0) or 0)
        self._mesh_n = (
            mesh_n if mesh_n > 1 and marking.get("mesh")
            and bool(config().get("segment.compile.mesh-fuse", True)) else 0)
        self._mesh_prog = None  # jitted shard_map step (armed by _setup_mesh)
        self._mesh_agg = None
        self._mesh_member = None
        self._mesh_off = False  # latched: fusion declined/failed, host path only
        self._mesh_shapes: set[int] = set()
        # cache identity: the traced prefix's configs (tail members never
        # enter the trace — their configs may hold run-local objects) plus
        # the node's parallelism, so a rescale recompiles rather than
        # reusing a trace whose key semantics could differ. The mesh width
        # keys too: a resize changes the forced-hoist decision and the
        # owner-range layout the fused program bakes in.
        cfgs = [(op, _cfg_fingerprint(c))
                for op, c in chain.cfg_members[: int(marking["prefix"])]]
        self._seg_key = hashlib.sha1(json.dumps(
            [cfgs, ctx.task_info.parallelism, self._mesh_n], default=repr,
        ).encode()).hexdigest()[:16]

    # -- events ---------------------------------------------------------

    def _event(self, level: str, code: str, message: str, **data) -> None:
        from ..obs.events import recorder as _events

        ti = self.ctx.task_info
        _events.record(ti.job_id, level, code, message=message,
                       node=ti.node_id, subtask=ti.subtask_index,
                       data={"segment": self.chain.name(), **data})

    # -- per-batch entry point -----------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0) -> None:
        # segment.compile.min-rows: batches too small to amortize the jit
        # dispatch (sub-threshold coalescing flushes, selective-filter
        # survivors) run interpreted — the two paths are verified
        # interchangeable per batch, so mixing them is free
        if (self._fallback or batch.num_rows < max(1, self._min_rows)):
            self.chain.process_batch(batch, ctx, collector,
                                     input_index=input_index)
            return
        if self._entry is None or self._sig != _schema_sig(batch):
            verified = self._prepare(batch)
            if self._fallback:
                self.chain.process_batch(batch, ctx, collector,
                                         input_index=input_index)
                return
            if verified is not None:
                # fresh compile: the verification pass already executed
                # this batch — commit its (proven-equal) outputs instead
                # of paying a second jit dispatch
                self._commit(verified, collector)
                return
            if self._entry is None:
                # vacuous first batch (hoisted filter left no survivors):
                # a no-op on both paths; compile retries on the next batch
                return
        if self._mesh_prog is not None and self._mesh_execute(batch, collector):
            return
        try:
            # pure: a trace/XLA failure here (e.g. a new padded shape
            # compiling under memory pressure) has mutated nothing, so it
            # degrades like any other — never a job failure
            res = self._entry.execute(batch, ctx.task_info.job_id,
                                      min_rows=self._min_rows)
        except Exception as e:  # noqa: BLE001 - fallback, never a panic
            self._mark_fallback(f"{type(e).__name__}: {e}")
            self.chain.process_batch(batch, ctx, collector,
                                     input_index=input_index)
            return
        if res is None:
            self._small_streak += 1
            if self._small_streak >= 8:
                self._fallback = True  # cost latch; state paths unaffected
                self.metrics.segment_compiled = False
                self.metrics.segment_reason = (
                    "hoisted-filter survivors stayed under "
                    "segment.compile.min-rows (cost latch)")
            self.chain.process_batch(batch, ctx, collector,
                                     input_index=input_index)
            return
        self._small_streak = 0
        self._commit(res, collector)

    # -- compile --------------------------------------------------------

    def _prepare(self, batch: Batch) -> Optional[dict]:
        """Resolve/compile the entry for this batch's schema; on a FRESH
        compile, returns the verification pass's execute() result for this
        batch (proven bit-equal to the reference) so the caller can commit
        it without re-running; None on cache hit or fallback."""
        sig = _schema_sig(batch)
        key = (self._seg_key, sig)
        members = self.chain.members[: int(self.marking["prefix"])]
        # the insert member's key-transport split must exist before binding
        # (acc lanes extend acc_inputs); dtype-only, so deriving it from the
        # first batch matches what the first surviving batch would do
        err = self._setup_insert(members, batch)
        if err is not None:
            segment_cache.store(key, _Fallback(err))
            self._mark_fallback(err)
            return None
        from ..metrics import registry

        hit, entry = segment_cache.lookup(key)
        if hit:
            if isinstance(entry, _Fallback):
                # negative-cache reuse deliberately does NOT count as a
                # cache hit: the metric means "reused a COMPILED entry"
                self._mark_fallback(entry.reason)
                return None
            registry.add_segment_cache_hit(self.ctx.task_info.job_id)
            self._entry, self._sig = entry, sig
            self.metrics.segment_compiled = True
            self._setup_mesh(entry)
            # the event feed is per-job: a job served from the process-wide
            # cache must still be diagnosable as compiled from `logs` alone
            self._event(
                "INFO", "SEGMENT_COMPILED",
                f"segment {self.chain.name()} running compiled "
                f"({entry.plan.prefix}/{len(self.chain.members)} members, "
                f"cache hit)",
                members=entry.plan.prefix, cached=True,
                schema=[list(pair) for pair in sig])
            return None
        t0 = time.perf_counter()
        try:
            plan = _bind(members, len(members), batch,
                         hoist=self._should_hoist(members[0], batch))
            entry = CompiledSegment(plan, _trace_fn(plan), sig)
            # observe=False: the bind+trace+verify total below covers this
            # first shape's compile; later shapes self-report from execute
            got = entry.execute(batch, self.ctx.task_info.job_id,
                                observe=False)
            if got["n"] == 0 and not got["cols"]:
                # the hoisted filter killed the entire first batch: the
                # traced function never ran, so "verification" would be
                # vacuous. The batch is a no-op on both paths — do NOT
                # cache or adopt the unproven entry; retry the compile on
                # the next batch that has survivors
                return None
            want = _reference(plan, batch)
            mismatch = _outputs_equal(got, want)
            if mismatch is not None:
                raise SegmentUntraceable(f"verification failed: {mismatch}")
        except SegmentUntraceable as e:
            segment_cache.store(key, _Fallback(str(e)))
            self._mark_fallback(str(e))
            return None
        except Exception as e:  # noqa: BLE001 - tracing must never kill a job
            reason = f"{type(e).__name__}: {e}"
            segment_cache.store(key, _Fallback(reason))
            self._mark_fallback(reason)
            return None
        elapsed = time.perf_counter() - t0
        segment_cache.store(key, entry)
        registry.observe_segment_compile(self.ctx.task_info.job_id, elapsed)
        self._entry, self._sig = entry, sig
        self.metrics.segment_compiled = True
        self._setup_mesh(entry)
        self._event(
            "INFO", "SEGMENT_COMPILED",
            f"segment {self.chain.name()} compiled to one jitted call "
            f"({plan.prefix}/{len(self.chain.members)} members, "
            f"{elapsed * 1e3:.1f}ms, first batch verified)",
            members=plan.prefix, compile_ms=round(elapsed * 1e3, 2),
            schema=[list(pair) for pair in sig])
        return got

    def _should_hoist(self, m0, batch: Batch) -> bool:
        """Hoist the leading filter out of the trace when it must be (the
        expression or its columns cannot trace) or when the first batch
        shows it selective enough that compact-then-compute beats masked
        full-length tracing. Either choice is correct — the first-batch
        verification covers both shapes — so a wrong guess only costs
        performance."""
        from ..operators.builtin import ValueOperator

        if not isinstance(m0, ValueOperator) or m0.filter is None:
            return False
        if self._mesh_n > 1:
            # mesh fusion: the fused shard_map program has no mask output,
            # so a leading filter MUST run on the host. Cache keys include
            # the mesh width, so entries never cross hoist decisions.
            return True
        if expr_traceable(m0.filter) is not None:
            return True
        for name in m0.filter.columns():
            col = batch.columns.get(name)
            if col is None or np.asarray(col).dtype.kind not in "biuf":
                return True
        fm = np.asarray(
            eval_expr(m0.filter, batch.columns, batch.num_rows), dtype=bool)
        return bool(fm.mean() < _HOIST_SELECTIVITY)

    def _setup_insert(self, members, batch: Batch) -> Optional[str]:
        if not self.marking.get("insert"):
            return None
        m = members[-1]
        if m.lane_key_fields is not None:
            return None
        # the split must be derived from the member's OWN input — exactly
        # what process_batch would see — so run the prefix as a one-off
        # pure reference. (The chain input is NOT a substitute: a group-by
        # column name can shadow a differently-typed source column.)
        try:
            probe = _bind(members[:-1], len(members) - 1, batch, probe=True)
        except SegmentUntraceable as e:
            return str(e)
        inter = _reference(probe, batch)["cols"]
        missing = [f for f in m.key_fields if f not in inter]
        if missing:
            return (f"window group-by columns {missing} not produced by "
                    f"the traced prefix")
        m._setup_key_transport(Batch(inter))
        return None

    def _mark_fallback(self, reason: str) -> None:
        self._fallback = True
        self.metrics.segment_compiled = False
        self.metrics.segment_reason = reason
        self._event(
            "WARN", "SEGMENT_FALLBACK",
            f"segment {self.chain.name()} fell back to the interpreted "
            f"path: {reason}", reason=reason)

    # -- mesh fusion ----------------------------------------------------

    def _setup_mesh(self, entry: CompiledSegment) -> None:
        """Arm the fused mesh path for a freshly adopted entry: ONE
        shard_map'd jitted program that runs the traced prefix per shard
        and feeds the sharded aggregate's owner bucketing → all_to_all →
        sort_reduce/probe_merge directly in-program, so rows never
        round-trip to host between projection and state update. Fusion is
        an optimization on top of the verified per-batch path, not a mode
        switch: any gate failure quietly stays on the host path (no
        SEGMENT_FALLBACK — the segment is still compiled)."""
        self._mesh_prog = None
        self._mesh_agg = None
        self._mesh_member = None
        if self._mesh_n <= 1 or self._mesh_off:
            return
        plan = entry.plan
        if plan.insert is None:
            self._mesh_off = True
            return
        # the member resolves BY INDEX against THIS chain (same rule as
        # _commit): a cache-hit entry was bound by another incarnation
        member = self.chain.members[plan.insert.member_index]
        from ..parallel.sharded_agg import ShardedAggregator

        # the window operators build their store lazily on first insert;
        # setup runs before the verified first batch commits, so force the
        # construction (same path an insert would take) to see its type
        agg_fn = getattr(member, "_aggregator", None)
        agg = agg_fn() if agg_fn is not None else getattr(member, "_agg", None)
        if not isinstance(agg, ShardedAggregator):
            # mesh-devices was toggled after the operator built its store,
            # or the backend fell back — the host path still works
            self._mesh_off = True
            return
        for si, st in enumerate(plan.stages):
            if (st.kind == "value" and st.member.filter is not None
                    and (si != 0 or plan.prefilter is None)):
                # an in-trace filter would desync the host prologue (late
                # split, open-bin bookkeeping) from the rows the program
                # inserts; _mesh_markable bans this statically, but a
                # cache entry bound under different config could disagree
                self._mesh_off = True
                return
        # the host prologue derives bins from the VERBATIM event time, so
        # the insert-time _timestamp must be the input column untouched: a
        # projection that redefines it (prov walk in _bind) cannot fuse —
        # and "in traced_in" alone doesn't prove it (an earlier stage may
        # have consumed the verbatim column before a projection shadowed it)
        ts_verbatim = TIMESTAMP_FIELD in plan.traced_in
        for st in plan.stages:
            if (st.kind == "value" and st.member.projections is not None
                    and any(name == TIMESTAMP_FIELD
                            for name, _e in st.member.projections)):
                ts_verbatim = False
        if not ts_verbatim:
            self._mesh_off = True
            return
        if getattr(member, "mesh_insert_begin", None) is None:
            self._mesh_off = True
            return
        try:
            prefix_fn = self._build_mesh_prefix(plan, member)
            self._mesh_prog = agg.fused_step(
                prefix_fn, len(plan.traced_in), 2 * len(plan.wm_stages))
            self._mesh_agg = agg
            self._mesh_member = member
            self._mesh_shapes = set()
        except Exception as e:  # noqa: BLE001 - fusion is best-effort
            self._mesh_off = True
            self._event(
                "WARN", "SEGMENT_FALLBACK",
                f"segment {self.chain.name()} mesh fusion disabled "
                f"(compiled host path continues): {type(e).__name__}: {e}",
                reason=str(e), mesh=True)

    def _build_mesh_prefix(self, plan: _SegmentPlan, member) -> Callable:
        """The traced prefix re-expressed as the sharded step's in-program
        prologue: a per-shard twin of ``_trace_fn`` minus filter stages
        (banned by the mesh gate), producing the insert columns the
        exchange+merge consumes.

        Contract (parallel.sharded_agg.ShardedAggregator.fused_step):
        ``prefix_fn(arrays, valid, base_bin, ontime) -> (key_i64,
        bins_i32, insert_valid, vals, aux)`` where ``valid`` masks this
        shard's padding rows, ``ontime`` masks host-detected late rows
        (insert only — the watermark observes PRE-late rows, matching the
        interpreted order where the generator sits upstream of the
        window), and ``aux`` is one (masked max, valid count) pair per
        watermark stage."""
        import jax.numpy as jnp
        from jax import lax

        from ..ops import require_x64

        require_x64()
        step_us = _insert_step(member)
        stages = list(plan.stages)
        traced_in = list(plan.traced_in)
        insert_has_key = plan.insert_has_key
        acc = list(zip(member.acc_inputs, member.acc_dtypes))

        def prefix_fn(arrays, valid, base_bin, ontime):
            p = arrays[0].shape[0]
            cols: dict[str, Any] = dict(zip(traced_in, arrays))
            aux: list[Any] = []
            key_i64 = None
            bins = None
            vals: list[Any] = []
            for st in stages:
                m = st.member
                if st.kind == "value":
                    if m.projections is not None:
                        new = {}
                        for name, e in m.projections:
                            new[name] = _as_full(e.eval_jnp(cols), p)
                        for carried in (TIMESTAMP_FIELD, KEY_FIELD,
                                        "_is_retract"):
                            if carried not in new and carried in cols:
                                new[carried] = cols[carried]
                        cols = new
                elif st.kind == "key":
                    key_cols = []
                    for name, e in m.keys:
                        c = _as_full(e.eval_jnp(cols), p)
                        cols[name] = c
                        key_cols.append(c)
                    cols[KEY_FIELD] = _hash_columns_jnp(key_cols)
                elif st.kind == "wm":
                    wvals = _as_full(m.expr.eval_jnp(cols), p)
                    floor = _dtype_floor(np.dtype(wvals.dtype))
                    aux.extend([jnp.max(jnp.where(valid, wvals, floor)),
                                jnp.sum(valid)])
                else:  # insert: rel bins in int32, like the host twins
                    bins = (cols[TIMESTAMP_FIELD] // step_us
                            - base_bin).astype(jnp.int32)
                    if insert_has_key:
                        # signed transport twin of the host .view(np.int64)
                        key_i64 = lax.bitcast_convert_type(
                            cols[KEY_FIELD].astype(jnp.uint64), jnp.int64)
                    for inp, dt in acc:
                        if inp is None:
                            vals.append(jnp.ones(p, dtype=dt))
                        else:
                            vals.append(
                                _as_full(inp.eval_jnp(cols), p).astype(dt))
            if key_i64 is None:
                key_i64 = jnp.zeros(p, dtype=jnp.int64)
            return key_i64, bins, valid & ontime, tuple(vals), tuple(aux)

        return prefix_fn

    def _mesh_execute(self, batch: Batch, collector) -> bool:
        """One fused micro-batch: host prologue (hoisted filter, late
        split, open-bin bookkeeping via the member's mesh_insert_begin),
        then ONE jitted shard_map dispatch running projection → key hash →
        owner exchange → merge entirely on device. Returns False to hand
        the batch to the per-batch host path, which recovers it exactly:
        a failed fused call never committed aggregate state, and the
        member prologue's bookkeeping (late counter, open-bin set) is
        idempotent under the host re-run."""
        plan = self._entry.plan
        member = self._mesh_member
        agg = self._mesh_agg
        n = batch.num_rows
        fmask = None
        if plan.prefilter is not None:
            fm = np.asarray(
                eval_expr(plan.prefilter, batch.columns, n), dtype=bool)
            if not fm.any():
                self._small_streak = 0
                return True  # nothing flows on either path
            if not fm.all():
                survivors = int(fm.sum())
                if survivors < max(1, self._min_rows):
                    return False  # host path owns the small-batch latch
                fmask = fm
                n = survivors
        try:
            ts = np.asarray(batch.columns[TIMESTAMP_FIELD])
            if fmask is not None:
                ts = ts[fmask]
            bins_abs = ts // _insert_step(member)
            mcols = self.chain._chain_cols(collector)
            ontime = member.mesh_insert_begin(
                bins_abs, mcols[plan.insert.member_index])
            p = _padded_size(n)
            if p % agg.n_dev:
                p = -(-p // agg.n_dev) * agg.n_dev
            shard = p // agg.n_dev
            arrays = []
            for name in plan.traced_in:
                a = np.asarray(batch.columns[name])
                buf = np.zeros(p, dtype=a.dtype)
                if fmask is not None:
                    np.compress(fmask, a, out=buf[:n])
                else:
                    buf[:n] = a
                arrays.append(buf.reshape(agg.n_dev, shard))
            ot = np.zeros(p, dtype=bool)
            ot[:n] = True if ontime is None else ontime
            ot = ot.reshape(agg.n_dev, shard)
            with self._entry._lock:
                new_shape = p not in self._mesh_shapes
                self._mesh_shapes.add(p)
            t0 = time.perf_counter()
            aux = agg.update_fused(
                self._mesh_prog, n,
                0 if member.base_bin is None else int(member.base_bin),
                ot, arrays)
            if new_shape:
                # per-shape XLA compile of the fused program, same series
                # as the host entry's retraces
                from ..metrics import registry

                registry.observe_segment_compile(
                    self.ctx.task_info.job_id, time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - fusion is best-effort
            self._mesh_prog = None
            self._mesh_off = True
            self._event(
                "WARN", "SEGMENT_FALLBACK",
                f"segment {self.chain.name()} fused mesh step failed; "
                f"batches continue on the compiled host path: "
                f"{type(e).__name__}: {e}", reason=str(e), mesh=True)
            return False
        pairs = []
        it = iter(aux)
        for mx in it:
            cnt = np.asarray(next(it))
            total = int(cnt.sum())
            # exact across shards: empty shards report the dtype floor,
            # which never exceeds a real value
            pairs.append((int(np.asarray(mx).max()) if total else None, total))
        for st, (mx, cnt) in zip(reversed(plan.wm_stages), reversed(pairs)):
            if cnt:
                self.chain.members[st.member_index].observe_batch_max(
                    mx, mcols[st.member_index])
        self._small_streak = 0
        self.metrics.segment_mesh = True
        _MESH_DISPATCH["fused"] += 1
        return True

    # -- host finish ----------------------------------------------------

    def _commit(self, res: dict, collector) -> None:
        """Feed verified traced outputs into the members' own state
        mutation/emission methods, in the interpreted path's order: data
        first (terminal collect or window insert), then the watermark
        state machines innermost-first (a downstream generator's broadcast
        happens inside the upstream one's collect call).

        Members resolve BY INDEX against this runner's chain, never via
        the cached plan's stage objects: a cache-hit entry was bound by a
        different operator incarnation (another subtask, another run, a
        restore), and committing into ITS members would mutate dead state
        while this chain's operators — the ones that checkpoint — see
        nothing. The traced function itself is pure, so reusing it across
        incarnations is safe; only the state sinks must be re-resolved."""
        if self._mesh_n > 1:
            _MESH_DISPATCH["host"] += 1
        chain = self.chain
        cols = chain._chain_cols(collector)
        plan = self._entry.plan
        k = res["n"]
        if plan.insert is not None:
            if k:
                m = chain.members[plan.insert.member_index]
                vals = []
                for i, (inp, dt) in enumerate(zip(m.acc_inputs, m.acc_dtypes)):
                    vals.append(np.ones(k, dtype=dt) if inp is None
                                else res["cols"][f"__val{i}"])
                hashes = (res["cols"]["__hash"] if plan.insert_has_key
                          else np.zeros(k, dtype=np.uint64))
                m.insert_arrays(hashes, res["cols"]["__bins"], vals,
                                cols[plan.insert.member_index])
        elif k:
            out = {name: res["cols"][name] for name, _src in plan.out_plan}
            cols[plan.prefix - 1].collect(Batch(out))
        for st, (mx, cnt) in zip(reversed(plan.wm_stages),
                                 reversed(res["aux"])):
            if cnt:
                chain.members[st.member_index].observe_batch_max(
                    mx, cols[st.member_index])


def _schema_sig(batch: Batch) -> tuple:
    return tuple((name, np.asarray(c).dtype.str)
                 for name, c in batch.columns.items())


def _cfg_fingerprint(cfg: dict):
    """JSON-stable view of a member config (exprs as tagged trees; live
    callables dropped the way graph serialization drops them)."""
    from ..graph import _jsonable

    return _jsonable(cfg)


def runner_for(operator, ctx, metrics) -> Optional[SegmentRunner]:
    """The task run loop's hook: a SegmentRunner when ``operator`` is a
    chained run marked compilable at plan time and ``segment.compile.
    enabled`` is on; None means run the interpreted hook loop."""
    if not config().get("segment.compile.enabled", True):
        return None
    from ..operators.chained import ChainedOperator

    if not isinstance(operator, ChainedOperator):
        return None
    marking = operator.compile_marking
    if not marking:
        # plan-time reject (optimizer.chain_graph): record the reason so
        # `top`/`explain` show "not compiled: ..." instead of nothing
        reason = getattr(operator, "compile_reject", None)
        if reason:
            metrics.segment_reason = reason
        return None
    return SegmentRunner(operator, ctx, metrics, marking)
