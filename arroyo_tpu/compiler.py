"""Native UDF compile service + dylib host.

Equivalent of the reference's two native-UDF components, re-targeted at the
C++ toolchain this framework's host runtime uses:

- crates/arroyo-compiler-service (lib.rs:57 CompileService, :89
  write_udf_crate): builds user UDF source into a shared library with the
  system toolchain and pushes the artifact to object storage so every
  worker can fetch it. Here: g++ -shared over a C++ translation unit,
  artifact published through arroyo_tpu.state.storage (local or s3://).
- crates/arroyo-udf-host (lib.rs:97 UdfDylibInterface / :168 UdfDylib,
  dlopen2 + C ABI): loads the dylib on the worker and exposes the symbol
  as a SQL scalar function. Here: ctypes over a columnar C ABI, registered
  into the same UDF registry the planner consults, so native UDFs are
  vectorized batch calls (one FFI hop per batch, not per row).

C ABI contract (vectorized, columnar — the TPU-native analog of the
reference's per-batch Arrow FFI):

    extern "C" void NAME(int64_t n, const A0* a0, ..., R* out);

with A*/R drawn from {int64_t, double}. The host allocates ``out``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

_CTYPE = {
    "int64": ctypes.POINTER(ctypes.c_int64),
    "float64": ctypes.POINTER(ctypes.c_double),
}
_NPDTYPE = {"int64": np.int64, "float64": np.float64}


class CompileError(RuntimeError):
    pass


@dataclass
class NativeUdfSpec:
    name: str
    arg_dtypes: tuple[str, ...]
    return_dtype: str
    artifact_url: str  # storage path of the built .so


class CompileService:
    """Builds C++ UDF sources into shared libraries and publishes them.

    artifacts_url: storage prefix (local dir or s3://...) the built dylibs
    are pushed to; workers fetch from the same prefix (reference pushes UDF
    dylibs to object storage the same way)."""

    def __init__(self, artifacts_url: Optional[str] = None):
        from .config import config

        self.artifacts_url = artifacts_url or config().get(
            "compiler.artifacts-url",
            os.path.join(
                config().get("checkpoint.storage-url", "/tmp/arroyo-tpu"), "udf-artifacts"
            ),
        )

    def build_udf(self, name: str, source: str, arg_dtypes: list[str],
                  return_dtype: str) -> NativeUdfSpec:
        """Compile ``source`` (a C++ translation unit defining the
        extern-C symbol ``name``) and publish the dylib. Idempotent per
        (name, source) — the artifact key is content-addressed."""
        from .state import storage

        for d in list(arg_dtypes) + [return_dtype]:
            if d not in _CTYPE:
                raise CompileError(f"unsupported UDF dtype {d!r} (int64/float64)")
        digest = hashlib.sha256(source.encode()).hexdigest()[:16]
        artifact = os.path.join(self.artifacts_url, f"{name}-{digest}.so")
        if not storage.exists(artifact):
            with tempfile.TemporaryDirectory(prefix="arroyo-udf-") as d:
                src = os.path.join(d, f"{name}.cc")
                out = os.path.join(d, f"{name}.so")
                with open(src, "w") as f:
                    f.write(source)
                r = subprocess.run(
                    ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out, src],
                    capture_output=True, text=True, timeout=120,
                )
                if r.returncode != 0:
                    raise CompileError(f"g++ failed for UDF {name!r}:\n{r.stderr}")
                with open(out, "rb") as f:
                    data = f.read()
            storage.makedirs(self.artifacts_url)
            storage.write_bytes(artifact, data)
        return NativeUdfSpec(name, tuple(arg_dtypes), return_dtype, artifact)


class CompileServer:
    """Standalone compile service (reference arroyo-compiler-service
    lib.rs:57 runs CompileService as its own deployable; here a JSON/HTTP
    daemon): POST /compile {name, source, arg_dtypes, return_dtype} ->
    {artifact_url}; GET /status. The API server delegates cpp UDF builds
    here when ``compiler.endpoint`` is configured, keeping g++ and
    untrusted source compilation off the control-plane process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 artifacts_url: Optional[str] = None):
        import json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        svc = CompileService(artifacts_url)
        self.service = svc

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/status":
                    self._json(200, {"ok": True,
                                     "artifacts_url": svc.artifacts_url})
                else:
                    self._json(404, {"error": "no route"})

            def do_POST(self):
                if self.path != "/compile":
                    self._json(404, {"error": "no route"})
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    spec = svc.build_udf(
                        body["name"], body["source"],
                        list(body.get("arg_dtypes", [])),
                        body.get("return_dtype", "float64"))
                except (CompileError, KeyError, TypeError, ValueError) as e:
                    # bad JSON / bad shape / bad source: the submitter's fault
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - g++ missing, timeout
                    # service-side failure: still answer, or the API wraps
                    # the dropped connection as "unreachable" and the real
                    # diagnostic is lost
                    self._json(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                self._json(200, {
                    "name": spec.name, "artifact_url": spec.artifact_url,
                    "arg_dtypes": list(spec.arg_dtypes),
                    "return_dtype": spec.return_dtype,
                })

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CompileServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"compile-service-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def compile_udf(name: str, source: str, arg_dtypes: list[str],
                return_dtype: str) -> NativeUdfSpec:
    """Build via the remote compile service when ``compiler.endpoint`` is
    configured, else in-process (reference: the API calls the compiler
    service over gRPC when deployed, builds locally in dev)."""
    from .config import config

    endpoint = config().get("compiler.endpoint")
    if not endpoint:
        return CompileService().build_udf(name, source, arg_dtypes, return_dtype)
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        str(endpoint).rstrip("/") + "/compile",
        data=_json.dumps({
            "name": name, "source": source, "arg_dtypes": arg_dtypes,
            "return_dtype": return_dtype}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=180) as r:
            out = _json.loads(r.read())
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace")
        try:
            detail = _json.loads(detail).get("error", detail)
        except ValueError:
            pass
        raise CompileError(detail) from e
    except urllib.error.URLError as e:
        raise CompileError(f"compile service unreachable: {e.reason}") from e
    return NativeUdfSpec(out["name"], tuple(out["arg_dtypes"]),
                         out["return_dtype"], out["artifact_url"])


# --------------------------------------------------------------- dylib host

_loaded: dict[str, ctypes.CDLL] = {}
_load_lock = threading.Lock()


def _fetch_local(artifact_url: str) -> str:
    """Materialize the artifact on the local filesystem (workers pull from
    object storage into a content-keyed cache; local paths pass through)."""
    from .state import storage

    if not artifact_url.startswith("s3://"):
        return artifact_url
    cache = os.path.join(tempfile.gettempdir(), "arroyo-udf-cache")
    os.makedirs(cache, exist_ok=True)
    local = os.path.join(cache, os.path.basename(artifact_url))
    if not os.path.exists(local):
        data = storage.read_bytes(artifact_url)
        tmp = local + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, local)
    return local


def load_native_udf(spec: NativeUdfSpec) -> None:
    """dlopen the artifact and register the symbol as a vectorized SQL UDF
    (shares the planner-visible registry with Python UDFs)."""
    from .udf import register_udf

    path = _fetch_local(spec.artifact_url)
    with _load_lock:
        lib = _loaded.get(path)
        if lib is None:
            lib = ctypes.CDLL(path)
            _loaded[path] = lib
    fn = getattr(lib, spec.name)  # AttributeError = bad artifact, surfaced
    fn.argtypes = [ctypes.c_int64] + [_CTYPE[d] for d in spec.arg_dtypes] + [
        _CTYPE[spec.return_dtype]
    ]
    fn.restype = None
    arg_np = [_NPDTYPE[d] for d in spec.arg_dtypes]
    out_np = _NPDTYPE[spec.return_dtype]

    def call(*cols):
        n = len(cols[0]) if cols else 0
        ins = [np.ascontiguousarray(c, dtype=t) for c, t in zip(cols, arg_np)]
        out = np.empty(n, dtype=out_np)
        fn(n, *[c.ctypes.data_as(_CTYPE[d]) for c, d in zip(ins, spec.arg_dtypes)],
           out.ctypes.data_as(_CTYPE[spec.return_dtype]))
        return out

    register_udf(spec.name, call, return_dtype=spec.return_dtype, vectorized=True)


def activate_udf_specs(specs: list[dict]) -> None:
    """Register persisted UDF records (controller DB rows / --udfs-file
    payload) into this process's planner-visible registry. cpp specs load
    their built artifact; python specs execute their source, which is
    expected to call register_udf/register_udaf (the reference's Python
    UDFs run user code in-process the same way)."""
    for rec in specs:
        if rec["language"] == "cpp":
            load_native_udf(NativeUdfSpec(
                rec["name"], tuple(rec["arg_dtypes"]), rec["return_dtype"],
                rec["artifact_url"],
            ))
        elif rec["language"] == "python":
            ns: dict = {}
            exec(rec["source"], ns)  # noqa: S102 - user-supplied UDF, by design
        else:
            raise CompileError(f"unknown UDF language {rec['language']!r}")
