"""Trace-safety auditor: prove segment-compiled and device code is pure,
shape-stable, and numerically parity-safe.

The whole-segment compiler (engine/segment.py) and the device kernels
(ops/) rest on conventions jax cannot check for us: code that runs under
``jax.jit`` must be PURE (no host syncs, no Python control flow on traced
values, no member-state reads/writes), SHAPE-STABLE (no data-dependent
output shapes), and — because every traced path here has a bit-exact
numpy twin — NUMERICALLY PARITY-SAFE (the allowlist in segment.py, the
twin implementations in expr.py, and the dtype semantics of both paths
must agree). PR 12 discovered violations at runtime: the first-batch
verification caught them one (segment, schema) at a time and degraded to
the interpreted path. This module proves the same invariants statically,
repo-wide, at lint time (the LR3xx series — fourth engine on the shared
Diagnostic model) and at plan time (AR009).

**The trace-reachability model.** Trace roots are (a) every function
passed to ``jax.jit`` / ``pjit`` (including through wrappers:
``jax.jit(_shard_map(local_step, ...))`` roots ``local_step``) or to a
``jax.lax`` control-flow combinator (``fori_loop``/``scan``/...), and
(b) every ``eval_jnp`` method (the expression twins are only ever called
from inside a trace). The audited set is the call closure over those
roots, resolved through sweep-known functions by name (nested defs and
methods included) — the same closure-resolution idea as the LR2xx state
audit. Within the closure a per-function TAINT analysis marks traced
values: parameters (per-callsite), ``jnp.``/``jax.lax.`` results, and
calls into closure functions whose returns are traced. Static metadata
(``.dtype``/``.shape``/``.ndim``, ``jnp.issubdtype``, ``np.dtype``,
``is None`` identity tests) is explicitly NOT traced — branching on it
is ordinary trace-time specialization. A call into a function the sweep
cannot resolve launders taint by design: the callee is audited on its
own if it is trace-reachable, and a host helper that merely receives a
traced value is the callee's problem, not the callsite's.

Rule catalog:

    LR301 trace-impurity       host sync or impurity in trace-reachable
                               code: ``.item()``/``.tolist()``/
                               ``.block_until_ready()``, ``int()/float()/
                               bool()`` on traced values, ``np.*`` calls
                               on traced values, ``if``/``while`` on
                               traced booleans, and reads/writes of
                               mutable ``self`` state
    LR302 trace-shape-unstable data-dependent output shape in traced
                               code: ``jnp.nonzero``/``unique``/
                               ``flatnonzero``/``argwhere``/``compress``
                               without ``size=``, single-argument
                               ``jnp.where``, boolean-mask indexing
    LR303 allowlist-drift      segment.py's ``_TRACEABLE_FUNCS``/
                               ``_TRACEABLE_BINOPS`` vs expr.py's twin
                               implementations: an allowlisted op with no
                               trace builder raises at compile time and
                               silently falls back (ERROR); an op with
                               bit-exact-capable twins in neither the
                               allowlist nor ``_KNOWN_DIVERGENT_*`` is a
                               silently-uncompiled segment (WARN)
    LR304 dual-path-dtype      dtype divergence risks between the numpy
                               and traced paths: jnp constructors whose
                               default dtype follows ``jax_enable_x64``
                               (``arange``/``zeros``/... without
                               ``dtype=``), ``.astype(int/float/bool)``
                               with Python builtins, and jit-root modules
                               that never pin x64 before tracing (the
                               32-bit default silently downcasts every
                               int64 input)
    LR305 trace-time-side-effect print/logging/event/metric/clock calls
                               inside trace-reachable code: they execute
                               ONCE at trace time and never again — the
                               jitted replay silently drops them

Waivers: the repo-lint grammar, ``# lint: waive LR3xx — justification``
on the flagged line or the line above.

**AR009 (plan pass).** For every chained run the optimizer marked
compilable, propagate the input edge schema's dtypes through each traced-
prefix expression TWICE — empirically through the numpy evaluators, and
through a static model of jax-x64 semantics (weak Python scalars, the
int⊕float32 lattice divergence, the float-function dtype rules) — and
REJECT the pipeline at plan time when the traced program would compute
in a different dtype than the interpreted path (the same divergence the
first-batch verification would catch per batch, promoted to a plan
error). Chains the optimizer declined to mark carry their
``not compilable: <reason>`` string as an INFO diagnostic, so
``check``/``explain`` stop reporting fallback as an unexplained runtime
event. The jnp dtype model is pinned against real jitted dtypes by
tests/test_trace_audit.py, and the allowlist itself is proven bit-exact
across the dtype matrix by the runtime parity oracle in the same file.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from .diagnostics import Diagnostic, Severity, finish
from .repo_lint import ModuleInfo, _call_name, _dotted, _parse

RULES = ("LR301", "LR302", "LR303", "LR304", "LR305")

# attribute loads that yield static (trace-time) metadata, not traced data
_STATIC_ATTRS = frozenset({
    "dtype", "shape", "ndim", "size", "kind", "itemsize", "names", "aval",
})

# library calls that return static metadata even when fed traced values
_METADATA_FNS = frozenset({
    "dtype", "issubdtype", "promote_types", "result_type", "can_cast",
    "iinfo", "finfo", "isdtype",
})

# builtins that pass taint through from their arguments
_PROPAGATING_BUILTINS = frozenset({
    "zip", "enumerate", "reversed", "sorted", "list", "tuple", "iter",
    "map", "filter", "next", "sum", "min", "max", "abs",
})

# jax.lax control-flow combinators whose function arguments run traced
_LAX_COMBINATORS = frozenset({
    "fori_loop", "scan", "while_loop", "cond", "switch", "map",
    "associative_scan", "custom_root",
})

_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit",
              "jax.experimental.pjit.pjit")

# jnp/lax calls with data-dependent output shapes unless size= pins them
_SHAPE_UNSTABLE = frozenset({
    "nonzero", "flatnonzero", "argwhere", "unique", "unique_values",
    "unique_counts", "unique_inverse", "compress", "extract",
})

# jnp constructors whose default dtype follows the jax_enable_x64 flag
# while the numpy twin is fixed 64-bit: name -> index of the positional
# dtype argument (arange's sits after start/stop/step)
_DTYPE_DEFAULT_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                        "arange": 3, "linspace": 5}

_CLOCK_FNS = frozenset({
    "time", "monotonic", "perf_counter", "thread_time", "process_time",
    "monotonic_ns", "perf_counter_ns", "thread_time_ns", "process_time_ns",
    "sleep",
})

_LOG_METHODS = frozenset({"debug", "info", "warning", "error", "exception",
                          "critical"})

_MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "push",
    "extend", "extendleft", "update", "insert", "remove", "discard",
    "clear", "setdefault", "sort", "reverse", "rotate",
})


def _canon(mod: ModuleInfo, expr: ast.expr) -> str:
    return mod.canonical(_dotted(expr))


def _is_jnp(canon: str) -> bool:
    return canon.startswith(("jax.numpy.", "jnp.", "jax.lax.", "lax.")) \
        or canon.startswith("jax.")


# ----------------------------------------------------------- function index


@dataclass
class FnInfo:
    name: str
    relpath: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    mod: ModuleInfo
    cls: Optional[str] = None  # owning class, for method self-state checks
    # taint state (fixpoint): which params are traced, does it return taint
    param_taint: set[str] = field(default_factory=set)
    all_params_tainted: bool = False
    returns_traced: bool = False
    taint: set[str] = field(default_factory=set)

    def key(self):
        return (self.relpath, id(self.node))

    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


class _Index:
    """Every function/method (nested included) in the sweep, by bare name."""

    def __init__(self):
        self.by_name: dict[str, list[FnInfo]] = {}
        self.fns: list[FnInfo] = []
        # (relpath, class) -> attrs mutated outside __init__ (mutable state)
        self.class_mutable: dict[tuple[str, str], set[str]] = {}

    def add_module(self, mod: ModuleInfo) -> None:
        def walk(node: ast.AST, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self._mine_class(child, mod)
                    walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FnInfo(child.name, mod.relpath, child, mod, cls)
                    self.fns.append(fi)
                    self.by_name.setdefault(child.name, []).append(fi)
                    walk(child, None)  # nested defs are not methods
                else:
                    walk(child, cls)

        walk(mod.tree, None)

    def _mine_class(self, cd: ast.ClassDef, mod: ModuleInfo) -> None:
        mutable: set[str] = set()
        for st in cd.body:
            if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or st.name == "__init__":
                continue
            for n in ast.walk(st):
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        a = _self_attr(t)
                        if a:
                            mutable.add(a)
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS:
                    a = _self_attr(n.func.value)
                    if a:
                        mutable.add(a)
        self.class_mutable[(mod.relpath, cd.name)] = mutable

    def resolve(self, name: str, relpath: str) -> list[FnInfo]:
        cands = self.by_name.get(name, [])
        local = [c for c in cands if c.relpath == relpath]
        return local or cands


def _self_attr(t: ast.expr) -> Optional[str]:
    """'x' for a target/receiver rooted at ``self`` (``self.x``,
    ``self.x.y``, ``self.x[i]``)."""
    while isinstance(t, (ast.Subscript, ast.Attribute)):
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr
        t = t.value
    return None


def _walk_own(root: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/class
    definitions: nested defs are separate closure entries with their own
    taint environment, so scanning them here would double-report findings
    under the wrong context."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


# ------------------------------------------------------------ root discovery


def _fn_args_of_call(call: ast.Call) -> list[str]:
    """Names passed as arguments (candidate traced callbacks/roots)."""
    return [a.id for a in call.args if isinstance(a, ast.Name)]


def _is_shard_map(canon: str) -> bool:
    """shard_map wraps its function argument for per-shard tracing, so a
    shard_map call site is a jit root exactly like jit()/pjit() — whether
    spelled jax.experimental.shard_map.shard_map, jax.shard_map, a bare
    import, or a leading-underscore version-compat alias (the repo's own
    parallel/sharded_agg.py ``_shard_map``). Without this the fused mesh
    step's per-shard body would escape LR301-LR305 entirely."""
    return canon.rsplit(".", 1)[-1].lstrip("_") == "shard_map"


def _find_roots(index: _Index, mods: list[ModuleInfo]
                ) -> tuple[list[FnInfo], set[str]]:
    """Trace roots + the set of relpaths containing a JIT call site (the
    modules LR304's x64-pin check applies to)."""
    roots: list[FnInfo] = []
    jit_modules: set[str] = set()

    def root_by_name(name: str, relpath: str):
        for fi in index.resolve(name, relpath):
            roots.append(fi)

    for mod in mods:
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    dc = _canon(mod, d)
                    if dc in _JIT_NAMES or _is_shard_map(dc):
                        root_by_name(n.name, mod.relpath)
                        jit_modules.add(mod.relpath)
            if not isinstance(n, ast.Call):
                continue
            canon = _canon(mod, n.func)
            if canon in _JIT_NAMES or canon.endswith((".jit", ".pjit")) \
                    or _is_shard_map(canon):
                jit_modules.add(mod.relpath)
                for a in n.args:
                    if isinstance(a, ast.Name):
                        root_by_name(a.id, mod.relpath)
                    elif isinstance(a, ast.Call):
                        # jit(wrapper(fn, ...)): the wrapped fn is traced
                        for name in _fn_args_of_call(a):
                            root_by_name(name, mod.relpath)
    for fi in index.by_name.get("eval_jnp", []):
        roots.append(fi)
    return roots, jit_modules


# ------------------------------------------------------------- taint engine


class _Taint:
    """Per-function forward taint over local names (flat scope)."""

    def __init__(self, fi: FnInfo, index: _Index, closure: dict):
        self.fi = fi
        self.index = index
        self.closure = closure  # key -> FnInfo for closure membership
        self.taint = set(fi.param_taint)
        if fi.all_params_tainted:
            self.taint |= {p for p in fi.params() if p not in ("self", "cls")}
        # (callee FnInfo, [tainted positional args]) observed at callsites
        self.callee_args: list[tuple[FnInfo, list[int], bool]] = []

    def tainted(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.taint
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.tainted(e.value)
        if isinstance(e, ast.Subscript):
            return self.tainted(e.value)
        if isinstance(e, ast.Call):
            return self.call_tainted(e)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False  # trace-time identity (x is None)
            return self.tainted(e.left) or any(self.tainted(c)
                                               for c in e.comparators)
        if isinstance(e, ast.BoolOp):
            return any(self.tainted(v) for v in e.values)
        if isinstance(e, ast.BinOp):
            return self.tainted(e.left) or self.tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.tainted(e.operand)
        if isinstance(e, ast.IfExp):
            return self.tainted(e.body) or self.tainted(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(self.tainted(v) for v in e.values if v is not None)
        if isinstance(e, ast.Starred):
            return self.tainted(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.tainted(e.elt) or any(self.tainted(g.iter)
                                              for g in e.generators)
        if isinstance(e, ast.DictComp):
            return self.tainted(e.value) or any(self.tainted(g.iter)
                                                for g in e.generators)
        return False

    def call_tainted(self, call: ast.Call) -> bool:
        canon = _canon(self.fi.mod, call.func)
        name = _call_name(call)
        args_tainted = any(self.tainted(a) for a in call.args) or \
            any(self.tainted(k.value) for k in call.keywords)
        if name in _METADATA_FNS:
            return False
        if _is_jnp(canon):
            return True
        if name == "eval_jnp":
            return True
        if isinstance(call.func, ast.Name) and \
                name in _PROPAGATING_BUILTINS:
            return args_tainted
        # sweep-resolved callee: taint iff its returns are traced
        for fi in self._resolved(call):
            if fi.returns_traced:
                return True
        return False

    def _resolved(self, call: ast.Call) -> list[FnInfo]:
        name = _call_name(call)
        if isinstance(call.func, ast.Name):
            return [fi for fi in self.index.resolve(name, self.fi.relpath)
                    if fi.key() in self.closure]
        return []

    # -- statement walk -------------------------------------------------

    def run(self) -> None:
        for _ in range(4):  # small fixpoint: loops rarely nest deeper
            before = set(self.taint)
            self._walk(self.fi.node.body)
            if self.taint == before:
                break

    def _assign_target(self, t: ast.expr, tainted: bool) -> None:
        if isinstance(t, ast.Name):
            if tainted:
                self.taint.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign_target(e, tainted)
        elif isinstance(t, ast.Starred):
            self._assign_target(t.value, tainted)

    def _record_callsites(self, node: ast.AST) -> None:
        for n in _walk_own(node):
            if not isinstance(n, ast.Call):
                continue
            canon = _canon(self.fi.mod, n.func)
            # jax.lax combinators run their function args traced with
            # traced parameters — mark those callbacks fully tainted
            if canon.rsplit(".", 1)[-1] in _LAX_COMBINATORS and \
                    _is_jnp(canon):
                for an in _fn_args_of_call(n):
                    for fi in self.index.resolve(an, self.fi.relpath):
                        self.callee_args.append((fi, [], True))
                continue
            for fi in self._resolved(n):
                pos = [i for i, a in enumerate(n.args) if self.tainted(a)]
                kw = any(self.tainted(k.value) for k in n.keywords)
                self.callee_args.append((fi, pos, kw))

    def _walk(self, stmts: Iterable[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs audited separately (if reachable)
            if isinstance(st, ast.Assign):
                t = self.tainted(st.value)
                for tgt in st.targets:
                    self._assign_target(tgt, t)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                self._assign_target(st.target, self.tainted(st.value))
            elif isinstance(st, ast.AugAssign):
                if self.tainted(st.value) or self.tainted(st.target):
                    self._assign_target(st.target, True)
            elif isinstance(st, ast.For):
                it = st.iter
                # per-position taint through zip()/enumerate() so static
                # config zipped with traced state doesn't over-taint
                if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                        and it.func.id in ("zip", "enumerate") \
                        and isinstance(st.target, ast.Tuple) and it.args:
                    srcs = list(it.args)
                    if it.func.id == "enumerate":
                        srcs = [None] + srcs
                    for tgt, src in zip(st.target.elts, srcs):
                        self._assign_target(
                            tgt, src is not None and self.tainted(src))
                else:
                    self._assign_target(st.target, self.tainted(it))
                self._walk(st.body)
                self._walk(st.orelse)
                continue
            elif isinstance(st, (ast.If, ast.While)):
                self._walk(st.body)
                self._walk(st.orelse)
                continue
            elif isinstance(st, ast.With):
                self._walk(st.body)
                continue
            elif isinstance(st, ast.Try):
                self._walk(st.body)
                for h in st.handlers:
                    self._walk(h.body)
                self._walk(st.orelse)
                self._walk(st.finalbody)
                continue
            elif isinstance(st, ast.Return) and st.value is not None:
                if self.tainted(st.value):
                    self.fi.returns_traced = True


def _build_closure(index: _Index, roots: list[FnInfo]
                   ) -> dict[tuple, FnInfo]:
    """BFS over sweep-resolvable calls from the roots."""
    closure: dict[tuple, FnInfo] = {}
    todo = list(roots)
    for fi in roots:
        fi.all_params_tainted = True
    while todo:
        fi = todo.pop()
        if fi.key() in closure:
            continue
        closure[fi.key()] = fi
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            canon = _canon(fi.mod, n.func)
            names: list[str] = []
            if isinstance(n.func, ast.Name):
                names.append(n.func.id)
            if canon.rsplit(".", 1)[-1] in _LAX_COMBINATORS and _is_jnp(canon):
                names.extend(_fn_args_of_call(n))
            for name in names:
                for cand in index.resolve(name, fi.relpath):
                    if cand.key() not in closure:
                        todo.append(cand)
    return closure


def _taint_fixpoint(index: _Index, closure: dict[tuple, FnInfo]
                    ) -> dict[tuple, _Taint]:
    """Iterate per-function taint until param/return verdicts stabilize."""
    analyses: dict[tuple, _Taint] = {}
    for _ in range(6):
        changed = False
        for key, fi in closure.items():
            t = _Taint(fi, index, closure)
            t.run()
            t._record_callsites(fi.node)
            analyses[key] = t
            for callee, pos, kw_tainted in t.callee_args:
                if callee.key() not in closure:
                    continue
                params = [p for p in callee.params() if p not in ("self",)]
                if kw_tainted and not pos:
                    new = set(params)
                else:
                    new = {params[i] for i in pos if i < len(params)}
                    if kw_tainted:
                        new |= set(params)
                if not new <= callee.param_taint:
                    callee.param_taint |= new
                    changed = True
        if not changed:
            break
    return analyses


# ----------------------------------------------------------------- findings

# rule, relpath, line, msg, hint [, Severity] — severity defaults to ERROR
Finding = tuple


def _scan_closure(analyses: dict[tuple, _Taint]) -> list[Finding]:
    out: list[Finding] = []
    for key in sorted(analyses, key=lambda k: (k[0], analyses[k].fi.node.lineno)):
        t = analyses[key]
        fi = t.fi
        rel = fi.relpath
        ctx = f"trace-reachable {'method' if fi.cls else 'function'} " \
              f"{(fi.cls + '.') if fi.cls else ''}{fi.name}"
        mutable = _mutable_for(t, fi)
        for n in _walk_own(fi.node):
            # ---- LR301: host sync / impurity --------------------------
            if isinstance(n, ast.Call):
                name = _call_name(n)
                canon = _canon(fi.mod, n.func)
                if name in ("item", "tolist", "block_until_ready") and \
                        isinstance(n.func, ast.Attribute):
                    out.append((
                        "LR301", rel, n.lineno,
                        f".{name}() in {ctx}: forces a device->host sync — "
                        "under jit it either fails to trace or silently "
                        "degrades the whole segment to the interpreted path",
                        "keep the value traced; sync on the host side of "
                        "the jitted call"))
                elif isinstance(n.func, ast.Name) and \
                        n.func.id in ("int", "float", "bool") and \
                        any(t.tainted(a) for a in n.args):
                    out.append((
                        "LR301", rel, n.lineno,
                        f"{n.func.id}() on a traced value in {ctx}: "
                        "concretizes the tracer (TracerConversionError) or "
                        "freezes a trace-time constant into every batch",
                        "keep the computation in jnp; convert on the host "
                        "after the jitted call returns"))
                elif canon.startswith(("numpy.", "np.")) and \
                        canon.rsplit(".", 1)[-1] not in _METADATA_FNS and \
                        any(t.tainted(a) for a in n.args):
                    out.append((
                        "LR301", rel, n.lineno,
                        f"{canon}() on a traced value in {ctx}: numpy "
                        "evaluates eagerly on the host, so this either "
                        "fails to trace or silently pins a trace-time "
                        "constant",
                        "use the jnp twin of this call inside traced code"))
            if isinstance(n, (ast.If, ast.While)) and t.tainted(n.test):
                out.append((
                    "LR301", rel, n.lineno,
                    f"Python {'if' if isinstance(n, ast.If) else 'while'} "
                    f"on a traced value in {ctx}: trace-time control flow "
                    "cannot branch on batch data "
                    "(TracerBoolConversionError)",
                    "use jnp.where / lax.cond / a mask instead"))
            # self-state writes & mutable reads
            if fi.cls is not None:
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for tgt in targets:
                        a = _self_attr(tgt)
                        if a:
                            out.append((
                                "LR301", rel, n.lineno,
                                f"write to self.{a} in {ctx}: traced code "
                                "must be pure — the store happens once at "
                                "trace time, then never again, so member "
                                "state silently diverges from the "
                                "interpreted path",
                                "return the value from the traced function "
                                "and commit it in a host finisher (the "
                                "segment runner's carry contract)"))
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS:
                    a = _self_attr(n.func.value)
                    if a:
                        out.append((
                            "LR301", rel, n.lineno,
                            f"self.{a}.{n.func.attr}() in {ctx}: in-place "
                            "member mutation under trace runs once at "
                            "trace time only",
                            "thread the value through the traced return "
                            "and mutate on the host"))
                elif isinstance(n, ast.Attribute) and \
                        isinstance(n.ctx, ast.Load) and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id == "self" and n.attr in (mutable or ()):
                    out.append((
                        "LR301", rel, n.lineno,
                        f"read of mutable member state self.{n.attr} in "
                        f"{ctx}: the value is frozen into the trace at "
                        "compile time, so later mutations never reach the "
                        "compiled segment",
                        "pass the value in as a traced argument, or keep "
                        "this expression out of the traced prefix"))
            # ---- LR302: shape instability -----------------------------
            if isinstance(n, ast.Call):
                canon = _canon(fi.mod, n.func)
                tail = canon.rsplit(".", 1)[-1]
                if _is_jnp(canon) and tail in _SHAPE_UNSTABLE and \
                        not any(k.arg == "size" for k in n.keywords):
                    out.append((
                        "LR302", rel, n.lineno,
                        f"{canon}() without size= in {ctx}: the output "
                        "shape depends on batch VALUES, which XLA cannot "
                        "compile — the trace fails or retraces per batch",
                        "pass size= (pad to a static bound) or move the "
                        "compaction to the host after the jitted call"))
                if _is_jnp(canon) and tail == "where" and \
                        len(n.args) == 1 and not n.keywords:
                    out.append((
                        "LR302", rel, n.lineno,
                        f"single-argument jnp.where() in {ctx} is "
                        "nonzero() in disguise: data-dependent output "
                        "shape",
                        "use the three-argument form, or size= via "
                        "jnp.nonzero"))
            if isinstance(n, ast.Subscript) and t.tainted(n.value):
                sl = n.slice
                if isinstance(sl, (ast.Compare, ast.BoolOp)) or \
                        (isinstance(sl, ast.UnaryOp) and
                         isinstance(sl.op, ast.Not)):
                    out.append((
                        "LR302", rel, n.lineno,
                        f"boolean-mask indexing in {ctx}: the result "
                        "length depends on how many rows match, which "
                        "XLA cannot compile",
                        "thread a validity mask (jnp.where) and compact "
                        "on the host, as the segment trace does"))
            # ---- LR304: dtype-defaulting construction -----------------
            if isinstance(n, ast.Call):
                canon = _canon(fi.mod, n.func)
                tail = canon.rsplit(".", 1)[-1]
                if _is_jnp(canon) and tail in _DTYPE_DEFAULT_CTORS:
                    pos = _DTYPE_DEFAULT_CTORS[tail]
                    has_dtype = any(k.arg == "dtype" for k in n.keywords) \
                        or (pos is not None and len(n.args) > pos)
                    if not has_dtype:
                        out.append((
                            "LR304", rel, n.lineno,
                            f"{canon}() without an explicit dtype in "
                            f"{ctx}: the default follows jax_enable_x64 "
                            "(int32/float32 when unset) while the numpy "
                            "twin is fixed 64-bit — the dual paths "
                            "silently diverge",
                            "pass dtype= explicitly (jnp.int64/"
                            "jnp.float64)"))
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "astype" and n.args and \
                        isinstance(n.args[0], ast.Name) and \
                        n.args[0].id in ("int", "float", "bool"):
                    out.append((
                        "LR304", rel, n.lineno,
                        f".astype({n.args[0].id}) in {ctx}: the Python "
                        "builtin maps to a platform/flag-dependent width "
                        "under jax while numpy pins 64-bit",
                        "name the dtype exactly (jnp.int64, jnp.float64, "
                        "jnp.bool_)"))
            # ---- LR305: trace-time-only side effects ------------------
            if isinstance(n, ast.Call):
                canon = _canon(fi.mod, n.func)
                recv = ""
                if isinstance(n.func, ast.Attribute):
                    v = n.func.value
                    recv = getattr(v, "id", getattr(v, "attr", "")) or ""
                effect = None
                if isinstance(n.func, ast.Name) and n.func.id == "print":
                    effect = "print()"
                elif isinstance(n.func, ast.Name) and n.func.id == "open":
                    effect = "open()"
                elif canon.startswith("logging."):
                    effect = canon + "()"
                elif isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _LOG_METHODS and "log" in recv.lower():
                    effect = f"{recv}.{n.func.attr}()"
                elif canon.startswith("time.") and \
                        canon.rsplit(".", 1)[-1] in _CLOCK_FNS:
                    effect = canon + "()"
                elif isinstance(n.func, ast.Attribute) and (
                        (n.func.attr == "record"
                         and ("record" in recv.lower()
                              or "event" in recv.lower()))
                        or n.func.attr in ("_event", "_emit")):
                    effect = f"{recv}.{n.func.attr}()"
                if effect is not None:
                    out.append((
                        "LR305", rel, n.lineno,
                        f"{effect} in {ctx}: side effects under jit "
                        "execute ONCE at trace time and never again — "
                        "the compiled replay silently drops this call "
                        "on every subsequent batch",
                        "move it to the host wrapper around the jitted "
                        "call (events/metrics/logging belong outside the "
                        "trace)"))
    return out


def _mutable_for(t: _Taint, fi: FnInfo) -> set[str]:
    if fi.cls is None:
        return set()
    return t.index.class_mutable.get((fi.relpath, fi.cls), set())


# ------------------------------------------------------- LR304: the x64 pin


def _module_pins_x64(mod: ModuleInfo) -> bool:
    if "/ops/" in f"/{mod.relpath}" or mod.relpath.startswith("ops/"):
        return True  # arroyo_tpu.ops pins x64 at import
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call):
            if _call_name(n) == "require_x64":
                return True
            for a in n.args:
                if isinstance(a, ast.Constant) and a.value == "jax_enable_x64":
                    return True
        elif isinstance(n, ast.ImportFrom):
            if n.module and ("ops" == n.module or n.module.startswith("ops.")
                             or n.module.endswith(".ops")
                             or ".ops." in n.module):
                return True
            # `from arroyo_tpu import ops` / `from .. import ops` bind the
            # pinning package by name rather than through n.module
            if any(a.name == "ops" for a in n.names):
                return True
        elif isinstance(n, ast.Import):
            if any("ops" in a.name.split(".") for a in n.names):
                return True
    return False


def _check_x64_pins(mods: dict[str, ModuleInfo], jit_modules: set[str]
                    ) -> list[Finding]:
    out: list[Finding] = []
    for rel in sorted(jit_modules):
        mod = mods[rel]
        if _module_pins_x64(mod):
            continue
        line = 1
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and \
                    _canon(mod, n.func) in _JIT_NAMES:
                line = n.lineno
                break
        out.append((
            "LR304", rel, line,
            "module jits traced code without pinning jax_enable_x64 "
            "first: under the 32-bit default every int64 input silently "
            "downcasts and the uint64 routing hash truncates, so the "
            "first-batch verification fails into a permanent unexplained "
            "fallback",
            "call arroyo_tpu.ops.require_x64() (or import arroyo_tpu.ops) "
            "before building the jitted callable"))
    return out


# --------------------------------------------------- LR303: allowlist drift


def _set_literal(tree: ast.AST, varname: str) -> Optional[tuple[set, int]]:
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                n.targets[0].id == varname:
            value = n.value
        elif isinstance(n, ast.AnnAssign) and \
                isinstance(n.target, ast.Name) and n.target.id == varname \
                and n.value is not None:
            value = n.value  # `X: set[str] = {...}` parses like the bare form
        else:
            continue
        vals = set()
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            for e in value.elts:
                if isinstance(e, ast.Constant):
                    vals.add(e.value)
        elif isinstance(value, ast.Call):  # set(...) / frozenset(...)
            for a in value.args:
                if isinstance(a, (ast.Set, ast.Tuple, ast.List)):
                    for e in a.elts:
                        if isinstance(e, ast.Constant):
                            vals.add(e.value)
        else:
            continue
        return vals, n.lineno
    return None


def _dict_keys(tree: ast.AST, varname: str) -> set:
    out: set = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign):
            targets, value = n.targets, n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        else:
            continue
        if any(isinstance(t, ast.Name) and t.id == varname
               for t in targets) and isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant):
                    out.add(k.value)
    return out


def _method_impl_names(cls_node: ast.ClassDef, method: str) -> set:
    """String constants a method dispatches on: ``name == "x"``,
    ``name in ("x", "y")``, plus every dict-literal key inside it."""
    out: set = set()
    for st in cls_node.body:
        if not (isinstance(st, ast.FunctionDef) and st.name == method):
            continue
        for n in ast.walk(st):
            if isinstance(n, ast.Compare) and \
                    isinstance(n.left, ast.Name) and n.left.id == "name":
                for comp in n.comparators:
                    if isinstance(comp, ast.Constant):
                        out.add(comp.value)
                    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        out |= {e.value for e in comp.elts
                                if isinstance(e, ast.Constant)}
            elif isinstance(n, ast.Dict):
                out |= {k.value for k in n.keys
                        if isinstance(k, ast.Constant)}
    return out


def _class_node(tree: ast.AST, name: str) -> Optional[ast.ClassDef]:
    for n in ast.walk(tree):
        if isinstance(n, ast.ClassDef) and n.name == name:
            return n
    return None


def _check_allowlist_drift(mods: dict[str, ModuleInfo]) -> list[Finding]:
    seg = next((m for m in mods.values()
                if _set_literal(m.tree, "_TRACEABLE_FUNCS") is not None
                and m.relpath.endswith("segment.py")), None)
    ex = next((m for m in mods.values()
               if _class_node(m.tree, "Func") is not None
               and m.relpath.endswith("expr.py")), None)
    if seg is None or ex is None:
        return []
    out: list[Finding] = []
    funcs, fline = _set_literal(seg.tree, "_TRACEABLE_FUNCS")
    binops, bline = _set_literal(seg.tree, "_TRACEABLE_BINOPS")
    divergent = (_set_literal(seg.tree, "_KNOWN_DIVERGENT_FUNCS")
                 or (set(), fline))[0]
    divergent_b = (_set_literal(seg.tree, "_KNOWN_DIVERGENT_BINOPS")
                   or (set(), bline))[0]

    func_cls = _class_node(ex.tree, "Func")
    np_impl = _method_impl_names(func_cls, "eval_np")
    jnp_impl = _method_impl_names(func_cls, "eval_jnp")
    np_bin = _dict_keys(ex.tree, "_NP_BINOPS")
    bin_cls = _class_node(ex.tree, "BinOp")
    jnp_bin = _method_impl_names(bin_cls, "eval_jnp") if bin_cls else set()

    for f in sorted(funcs - jnp_impl):
        out.append((
            "LR303", seg.relpath, fline,
            f"allowlisted func {f!r} (_TRACEABLE_FUNCS) has no jnp trace "
            "builder in expr.Func.eval_jnp: every segment using it "
            "compiles, raises NotImplementedError at trace time, and "
            "silently falls back to the interpreted path",
            "implement the eval_jnp twin (and prove it bit-exact in the "
            "parity oracle) or remove the op from the allowlist"))
    for f in sorted(funcs - np_impl):
        out.append((
            "LR303", seg.relpath, fline,
            f"allowlisted func {f!r} has no numpy implementation in "
            "expr.Func.eval_np: the interpreted path (and the first-batch "
            "verification reference) cannot evaluate it",
            "implement eval_np or remove the op from the allowlist"))
    for f in sorted((np_impl & jnp_impl) - funcs - divergent):
        out.append((
            "LR303", seg.relpath, fline,
            f"func {f!r} has BOTH numpy and jnp implementations but is in "
            "neither _TRACEABLE_FUNCS nor _KNOWN_DIVERGENT_FUNCS: segments "
            "using it silently never compile",
            "allowlist it if the twins are bit-exact (prove with the "
            "parity oracle) or declare it in _KNOWN_DIVERGENT_FUNCS with "
            "the reason", Severity.WARNING))
    for f in sorted(funcs & divergent):
        out.append((
            "LR303", seg.relpath, fline,
            f"func {f!r} is in both _TRACEABLE_FUNCS and "
            "_KNOWN_DIVERGENT_FUNCS: the allowlist claims bit-exactness "
            "the divergence set denies",
            "keep it in exactly one of the two sets"))
    for op in sorted(binops - jnp_bin):
        out.append((
            "LR303", seg.relpath, bline,
            f"allowlisted operator {op!r} (_TRACEABLE_BINOPS) has no jnp "
            "dispatch entry in expr.BinOp.eval_jnp",
            "add the jnp twin or remove the operator from the allowlist"))
    for op in sorted(binops - np_bin):
        out.append((
            "LR303", seg.relpath, bline,
            f"allowlisted operator {op!r} has no _NP_BINOPS entry",
            "add the numpy twin or remove the operator from the allowlist"))
    for op in sorted((np_bin & jnp_bin) - binops - divergent_b):
        out.append((
            "LR303", seg.relpath, bline,
            f"operator {op!r} has both numpy and jnp implementations but "
            "is in neither _TRACEABLE_BINOPS nor _KNOWN_DIVERGENT_BINOPS: "
            "segments using it silently never compile",
            "allowlist it if bit-exact, else declare it known-divergent",
            Severity.WARNING))
    return out


# -------------------------------------------------------------- entry points


def audit_trace_modules(mods: list[ModuleInfo]) -> list[Diagnostic]:
    """LR3xx over already-parsed modules (the lint sweep hands its own)."""
    index = _Index()
    by_rel: dict[str, ModuleInfo] = {}
    for mod in mods:
        by_rel.setdefault(mod.relpath, mod)
        index.add_module(mod)
    roots, jit_modules = _find_roots(index, mods)
    closure = _build_closure(index, roots)
    analyses = _taint_fixpoint(index, closure)

    findings: list[Finding] = []
    findings += _scan_closure(analyses)
    findings += _check_x64_pins(by_rel, jit_modules)
    findings += _check_allowlist_drift(by_rel)

    diags: list[Diagnostic] = []
    seen: set[tuple] = set()
    for rule, rel, line, msg, hint, *rest in findings:
        sev = rest[0] if rest else Severity.ERROR
        mod = by_rel.get(rel)
        if mod is not None and mod.waiver(line, rule):
            continue
        key = (rule, rel, line, msg)
        if key in seen:
            continue
        seen.add(key)
        diags.append(Diagnostic(rule, sev, f"{rel}:{line}", msg, hint))
    return finish(diags)


def audit_trace_source(source: str, relpath: str = "engine/fixture.py"
                       ) -> list[Diagnostic]:
    """Audit one file's text (test surface)."""
    return audit_trace_modules([_parse(source, relpath)])


def audit_trace_sources(named: list[tuple[str, str]]) -> list[Diagnostic]:
    """Audit several (source, relpath) files as one sweep (test surface
    for the cross-module rules, e.g. LR303's segment/expr pairing)."""
    return audit_trace_modules([_parse(src, rel) for src, rel in named])


# =========================================================================
# AR009 — plan-time dual-path dtype propagation
# =========================================================================


class _Weak:
    """A weak-typed Python scalar inside the jax dtype model."""

    __slots__ = ("kind",)

    def __init__(self, kind: str):  # "i" | "f" | "b"
        self.kind = kind


class _Unmodeled(Exception):
    """The dtype model does not cover this expression shape; AR009 skips
    it (the runtime first-batch verification still covers it)."""


def _jnp_promote(a, b):
    """jax-x64 binary promotion. Identical to numpy except the lattice's
    famous corner: integer x float32 stays float32 under jax where numpy
    widens to float64 (the divergence AR009 exists to reject)."""
    if isinstance(a, _Weak) and isinstance(b, _Weak):
        if "f" in (a.kind, b.kind):
            return _Weak("f")
        if "i" in (a.kind, b.kind):
            return _Weak("i")
        return _Weak("b")
    if isinstance(a, _Weak):
        a, b = b, a
    if isinstance(b, _Weak):
        if b.kind == "f":
            return a if a.kind == "f" else np.dtype(np.float64)
        if b.kind == "i":
            return np.dtype(np.int64) if a.kind == "b" else a
        return np.dtype(np.int64) if a.kind == "b" else a
    if (a.kind in "iu" and b == np.float32) or \
            (b.kind in "iu" and a == np.float32):
        return np.dtype(np.float32)
    return np.promote_types(a, b)


def _resolve_weak(d):
    if isinstance(d, _Weak):
        return np.dtype({"i": np.int64, "f": np.float64, "b": np.bool_}[d.kind])
    return d


def _jnp_dtype(expr, env: dict):
    """Static model of the dtype ``expr.eval_jnp`` computes under jax with
    x64 enabled. Pinned against real jitted dtypes by the model-fidelity
    test in tests/test_trace_audit.py — extend both together."""
    from ..expr import BinOp, Case, Cast, Col, Expr, Func, Lit, Neg, Not

    e = expr
    if isinstance(e, Col):
        d = env.get(e.name)
        if d is None or d == np.dtype(object):
            raise _Unmodeled(e.name)
        return d
    if isinstance(e, Lit):
        if isinstance(e.value, bool):
            return np.dtype(np.bool_)
        if isinstance(e.value, int):
            return _Weak("i")
        if isinstance(e.value, float):
            return _Weak("f")
        raise _Unmodeled(repr(e.value))
    if isinstance(e, BinOp):
        if e.op in ("==", "!=", "<", "<=", ">", ">=", "and", "or"):
            _jnp_dtype(e.left, env), _jnp_dtype(e.right, env)
            return np.dtype(np.bool_)
        l = _jnp_dtype(e.left, env)
        r = _jnp_dtype(e.right, env)
        out = _jnp_promote(l, r)
        if e.op == "/":
            li, ri = (isinstance(x, _Weak) and x.kind == "i"
                      or (not isinstance(x, _Weak) and x.kind in "iu")
                      for x in (l, r))
            if not (li and ri):
                # true division: float result
                rf = _resolve_weak(out)
                if rf.kind in "iub":
                    # int/int handled above; mixed int-float promoted
                    out = np.dtype(np.float64)
        return out
    if isinstance(e, Not):
        return np.dtype(np.bool_)
    if isinstance(e, Neg):
        return _jnp_dtype(e.inner, env)
    if isinstance(e, Cast):
        try:
            from ..batch import Field

            return np.dtype(Field("_", e.dtype).numpy_dtype())
        except Exception as err:
            raise _Unmodeled(e.dtype) from err
    if isinstance(e, Case):
        if e.otherwise is None:
            raise _Unmodeled("CASE without ELSE")
        out = _jnp_dtype(e.otherwise, env)
        for c, v in e.branches:
            _jnp_dtype(c, env)
            out = _jnp_promote(out, _jnp_dtype(v, env))
        return out
    if isinstance(e, Func):
        args = [_jnp_dtype(a, env) for a in e.args]
        if e.name == "abs":
            return args[0]
        if e.name in ("floor", "ceil", "sqrt"):
            a = args[0]
            if isinstance(a, _Weak):
                return np.dtype(np.float64)
            if a.kind == "f":
                return a
            if a.kind in "iu":
                # expr.py promotes integer inputs to float64 explicitly
                return np.dtype(np.float64)
            # bool: numpy computes float16, jnp has no exact twin —
            # model the jnp results so the comparison flags the mismatch
            return np.dtype(np.bool_) if e.name != "sqrt" \
                else np.dtype(np.float32)
        if e.name == "extract_epoch":
            return _jnp_promote(args[0], _Weak("i"))
        if e.name == "date_trunc_micros":
            return _jnp_promote(args[1], args[0])
        if e.name == "to_timestamp_micros":
            return np.dtype(np.int64)
        raise _Unmodeled(e.name)
    if isinstance(e, Expr):
        raise _Unmodeled(type(e).__name__)
    raise _Unmodeled(repr(e))


def _np_dtype_of(expr, env: dict):
    """The dtype the interpreted path actually computes — measured, not
    modeled: evaluate on zero-row columns through the real eval_np."""
    from ..expr import eval_expr

    cols = {name: np.empty(0, dtype=dt) for name, dt in env.items()}
    return np.asarray(eval_expr(expr, cols, 0)).dtype


def pass_segment_compile(ctx) -> None:
    """AR009: dual-path dtype parity of plan-marked-compilable segments,
    plus the ``not compilable: <reason>`` surfacing for chains the
    optimizer declined to mark.

    Deliberately ignores ``pipeline.chaining.enabled``: chaining is a
    deploy-time flag that can flip on a pipeline AFTER it was accepted
    (restores re-plan under the then-current config), so a plan accepted
    today must stay byte-exact under tomorrow's chained execution — the
    same reasoning that makes AR004 warn about unbounded state regardless
    of today's memory. ``segment.compile.enabled`` is the explicit
    opt-out: with compilation off, segments can never trace and the
    divergence cannot materialize, so the pass stands down entirely."""
    from ..batch import KEY_FIELD, TIMESTAMP_FIELD
    from ..config import config
    from ..graph import OpName
    from ..optimizer import chain_graph

    if not config().get("segment.compile.enabled", True):
        return  # segments never compile: the divergence cannot materialize
    try:
        g2 = chain_graph(ctx.graph)
    except Exception:
        return  # a malformed graph fails other passes; nothing to add here
    for nid in sorted(g2.nodes):
        node = g2.nodes[nid]
        if node.op != OpName.CHAINED:
            continue
        reject = node.config.get("compile_reject")
        if reject:
            ctx.add("AR009", Severity.INFO, node.node_id,
                    f"chained run is {reject}; it will execute interpreted",
                    "expected for chains ending at a sink or using "
                    "host-only expressions — see README \"why is my "
                    "segment not compiled\"")
            continue
        marking = node.config.get("compile")
        if not marking:
            continue
        env: dict = {}
        for e in g2.in_edges(node.node_id):
            for f in e.schema.fields:
                try:
                    env[f.name] = np.dtype(f.numpy_dtype())
                except Exception:
                    continue
        env.setdefault(TIMESTAMP_FIELD, np.dtype(np.int64))
        members = list(node.config.get("members", []))[: int(marking["prefix"])]

        def compare(label: str, expr, mi: int, op: str) -> None:
            refs = expr.columns()
            strings = sorted(r for r in refs
                             if env.get(r) == np.dtype(object))
            if strings:
                ctx.add(
                    "AR009", Severity.INFO, node.node_id,
                    f"compile-marked segment member {mi} references "
                    f"non-numeric column(s) {strings}: the segment will "
                    "fall back to the interpreted path at runtime (only "
                    "numeric/bool columns trace)",
                    "expected when projections carry strings; the "
                    "fallback is safe and permanent")
                return
            try:
                want = _np_dtype_of(expr, env)
                got = _resolve_weak(_jnp_dtype(expr, env))
            except Exception:
                return  # unmodeled shape: the first-batch verify covers it
            if np.dtype(got) != np.dtype(want):
                ctx.add(
                    "AR009", Severity.ERROR, node.node_id,
                    f"dual-path dtype divergence in {label} (chain member "
                    f"{mi}, {op}): the interpreted path computes {want} "
                    f"but the traced program would compute {np.dtype(got)}"
                    " — the byte-exactness contract cannot hold, so the "
                    "pipeline is rejected at plan time instead of failing "
                    "verification on the first batch",
                    "make the dtype explicit (e.g. CAST both operands to "
                    "DOUBLE) so both paths agree, or rewrite the "
                    "expression out of the compile-marked chain")

        for mi, (op, cfg) in enumerate(members):
            if op == OpName.VALUE.value:
                projections = cfg.get("projections")
                for n, e in projections or []:
                    compare(f"projection {n!r}", e, mi, op)
                if projections is not None:
                    nenv: dict = {}
                    for n, e in projections:
                        try:
                            nenv[n] = _np_dtype_of(e, env)
                        except Exception:
                            nenv[n] = np.dtype(object)  # host-only value
                    for carried in (TIMESTAMP_FIELD, KEY_FIELD,
                                    "_is_retract"):
                        if carried in env and carried not in nenv:
                            nenv[carried] = env[carried]
                    env = nenv
            elif op == OpName.KEY.value:
                for n, e in cfg.get("keys") or []:
                    compare(f"key {n!r}", e, mi, op)
                    try:
                        env[n] = _np_dtype_of(e, env)
                    except Exception:
                        env[n] = np.dtype(object)
                env[KEY_FIELD] = np.dtype(np.uint64)
            elif op == OpName.WATERMARK.value:
                if cfg.get("expr") is not None:
                    compare("watermark expression", cfg["expr"], mi, op)
            else:  # window insert: accumulator input expressions
                for n, _k, e in cfg.get("aggregates") or []:
                    if e is not None:
                        compare(f"aggregate input {n!r}", e, mi, op)
