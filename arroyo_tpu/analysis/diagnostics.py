"""Shared diagnostic model for both analysis engines.

One shape serves the plan analyzer (sites are graph node/edge ids) and the
repo lint engine (sites are file:line): rule id, severity, site, message,
fix hint. Diagnostics order deterministically (same input -> identical
ordered output) so CI diffs and golden assertions are stable.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable, Optional


class Severity(enum.IntEnum):
    """Ordered so max() picks the worst finding."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR"
        return self.name.lower()


@dataclass(frozen=True)
class Diagnostic:
    rule_id: str  # e.g. "AR002" (plan) / "LR105" (repo lint)
    severity: Severity
    site: str  # node id / "src -> dst" edge / "path:line"
    message: str
    hint: str = ""  # actionable fix suggestion, may be empty

    def render(self) -> str:
        out = f"{self.severity}[{self.rule_id}] {self.site}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self) -> dict:
        """Machine-readable shape for ``--json`` output / CI annotation."""
        return {"rule": self.rule_id, "severity": str(self.severity),
                "site": self.site, "message": self.message, "hint": self.hint}

    def sort_key(self):
        # worst first, then stable by site/rule/message so equal inputs
        # always produce byte-identical reports
        return (-int(self.severity), self.site, self.rule_id, self.message)


from ..sql.lexer import SqlError as _SqlError


class AnalysisError(_SqlError):
    """Raised when plan analysis finds ERROR-severity diagnostics.

    A SqlError subclass so every existing plan-failure surface (API 400s,
    CLI run, tests) rejects analyzer findings the same way it rejects parse
    errors. Carries the full diagnostic list; str() is the rendered report
    so the rule id reaches CLI/API users unchanged.
    """

    def __init__(self, diagnostics: list[Diagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(render_report(self.diagnostics))


def finish(diags: Iterable[Diagnostic]) -> list[Diagnostic]:
    """Deterministic final ordering + exact-duplicate removal."""
    seen = set()
    out = []
    for d in sorted(diags, key=Diagnostic.sort_key):
        if d not in seen:
            seen.add(d)
            out.append(d)
    return out


def worst(diags: Iterable[Diagnostic]) -> Optional[Severity]:
    sevs = [d.severity for d in diags]
    return max(sevs) if sevs else None


def render_json(diags: list[Diagnostic]) -> str:
    """Deterministic JSON array of diagnostics (one object per finding:
    rule, severity, site, message, hint) for ``lint --json``/``check
    --json`` — CI annotates from this without scraping the text report."""
    import json

    return json.dumps([d.to_dict() for d in diags], indent=2)


_SITE_RE = None  # compiled lazily; module stays import-light


def render_sarif(diags: list[Diagnostic]) -> str:
    """Deterministic SARIF 2.1.0 document for ``lint --sarif`` / ``check
    --sarif``: CI annotates findings inline on PRs from this without any
    site-string scraping. ``path:line`` sites become physical locations;
    plan-graph sites (node ids, ``src -> dst`` edges) become logical
    locations. Severity maps ERROR->error, WARNING->warning, INFO->note;
    exit codes are owned by the CLI and unchanged by the format."""
    import json
    import re

    global _SITE_RE
    if _SITE_RE is None:
        _SITE_RE = re.compile(r"^(?P<path>[^\s:]+\.(?:py|sql)):(?P<line>\d+)$")
    level = {Severity.ERROR: "error", Severity.WARNING: "warning",
             Severity.INFO: "note"}
    results = []
    for d in diags:
        res = {
            "ruleId": d.rule_id,
            "level": level[d.severity],
            "message": {"text": d.message + (f"\nhint: {d.hint}" if d.hint
                                             else "")},
        }
        m = _SITE_RE.match(d.site)
        if m:
            res["locations"] = [{"physicalLocation": {
                "artifactLocation": {"uri": m.group("path")},
                "region": {"startLine": int(m.group("line"))},
            }}]
        else:
            res["locations"] = [{"logicalLocations": [
                {"fullyQualifiedName": d.site}]}]
        results.append(res)
    rules = [{"id": rid} for rid in sorted({d.rule_id for d in diags})]
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                    "master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "arroyo-tpu-analysis",
                                "rules": rules}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def render_report(diags: list[Diagnostic]) -> str:
    if not diags:
        return "no findings"
    lines = [d.render() for d in diags]
    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    n_warn = sum(1 for d in diags if d.severity == Severity.WARNING)
    lines.append(f"{len(diags)} finding(s): {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)
