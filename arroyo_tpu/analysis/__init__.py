"""Static analysis: plan-time dataflow validation + repo lint.

Two engines share one diagnostic model (``diagnostics.Diagnostic``):

- **Plan analyzer** (``plan_passes``): passes over the logical dataflow
  graph, run automatically at the end of SQL planning and exposed as
  ``python -m arroyo_tpu check <pipeline.sql>``. ERROR findings reject the
  pipeline at plan time — before state allocation or device compilation —
  matching the reference planner's ``--fail`` SQL tests.
- **Plan-diff pass** (``plan_diff``, AR010-012): live-evolution safety —
  matches operators across an old and new plan by stable state identity
  (node lineage + declared TableSpecs + key/window/aggregate config) and
  classifies each as carried, rebuilt-by-replay, or incompatible-reject;
  also derives the plan fingerprint stamped into checkpoint metadata.
  ``diff_plans`` / ``plan_fingerprint``; driven by the ``evolve`` API.
- **Repo lint** (``repo_lint``): AST checks over this codebase encoding
  invariants earlier PRs paid to learn (shared retry layer, no swallowed
  exceptions, determinism, no host-sync in hot paths, lock discipline,
  fault-site coverage). ``python -m arroyo_tpu lint`` / ``tools/lint.sh``;
  CI keeps it at zero unwaived findings.
- **Replay-soundness auditor** (``state_audit``, LR2xx): a whole-program
  class-model pass over every Operator/Source subclass proving hot-path
  mutable state is checkpoint-covered, side effects are commit-gated,
  checkpoint/restore table sets agree, and emission never follows raw
  set/dict order. Runs inside the same ``lint`` sweep; its static
  coverage verdict is cross-checked at runtime by
  tests/test_state_audit.py.
- **Trace-safety auditor** (``trace_audit``, LR3xx + plan pass AR009): a
  call-closure walk from every ``jax.jit`` root and ``eval_jnp`` twin
  proving trace-reachable code is pure (no host syncs, no Python control
  flow on traced values, no member-state access), shape-stable, and
  numerically parity-safe (segment.py's allowlist vs expr.py's twin
  implementations, dual-path dtype semantics, the x64 pin). AR009
  propagates schema dtypes through every compile-marked segment at plan
  time and rejects dtype-divergent pipelines before they run; its static
  jnp dtype model and the allowlist's bit-exactness are cross-checked at
  runtime by tests/test_trace_audit.py.
- **Concurrency auditor** (``concurrency_audit``, LR4xx): a whole-program
  pass over the threaded control plane (engine/state/controller) building
  a per-class thread-role model (``threading.Thread(target=...)`` seeds,
  ``# thread: <role>`` annotations, implicit caller role) and a
  lock-attribution map (``with self.<lock>:`` regions resolved through
  same-class helper closures and entry contexts). Emits LR401
  unlocked-shared-attr, LR402 lock-order cycles (SCC over the global
  acquires-while-holding graph), LR403 interprocedural
  lock-across-blocking (subsumes LR105, whose id stays a waiver alias),
  and LR404 non-atomic check-then-act. The static LR402 graph is
  cross-checked at runtime by the lock-order witness (obs/lockorder.py)
  in tests/test_concurrency_audit.py.

``lint --json`` / ``check --json`` emit the diagnostics as a JSON array
(rule, severity, site, message, fix hint) with unchanged exit codes.

See the README "Static analysis" section for the rule catalog, example
diagnostics, and how to add a pass or waive a finding.
"""

from __future__ import annotations

from typing import Optional

from .diagnostics import (  # noqa: F401
    AnalysisError,
    Diagnostic,
    Severity,
    finish,
    render_json,
    render_report,
    render_sarif,
    worst,
)
from .plan_diff import (  # noqa: F401
    NodeClassification,
    PlanDiff,
    diff_plans,
    plan_fingerprint,
)
from .plan_passes import PLAN_PASSES, PassContext, analyze_graph  # noqa: F401
from .repo_lint import RULES as LINT_RULES  # noqa: F401
from .repo_lint import lint_paths, lint_source  # noqa: F401
from .state_audit import RULES as AUDIT_RULES  # noqa: F401
from .state_audit import (  # noqa: F401
    audit_modules,
    audit_package,
    audit_source,
    coverage_for_class,
)
from .trace_audit import RULES as TRACE_RULES  # noqa: F401
from .trace_audit import (  # noqa: F401
    audit_trace_modules,
    audit_trace_source,
    audit_trace_sources,
)
from .concurrency_audit import RULES as CONCURRENCY_RULES  # noqa: F401
from .concurrency_audit import (  # noqa: F401
    audit_concurrency_modules,
    audit_concurrency_source,
    static_lock_graph,
    static_lock_graph_package,
)


def check_sql(sql: str, parallelism: int = 1):
    """Plan ``sql`` and run every analyzer pass, collecting ALL diagnostics
    instead of raising on the first error (the ``check`` CLI surface).

    Returns ``(planned_pipeline_or_None, diagnostics)``; the pipeline is
    None when planning itself fails (those failures surface as an AR000
    diagnostic so check output always speaks rule ids).
    """
    from ..sql.lexer import SqlError
    from ..sql.planner import plan_query

    try:
        pp = plan_query(sql, parallelism=parallelism, analyze=False)
    except AnalysisError as e:  # pragma: no cover - analyze=False skips this
        return None, e.diagnostics
    except SqlError as e:
        return None, [Diagnostic("AR000", Severity.ERROR, "<plan>", str(e),
                                 "fix the SQL; this failure precedes graph "
                                 "analysis")]
    return pp, analyze_graph(pp.graph)
