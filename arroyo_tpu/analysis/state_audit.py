"""Replay-soundness auditor: prove operator state is checkpoint-covered.

The engine's headline guarantees — byte-exact recovery and exactly-once
sinks via 2PC — rest on a convention the type system cannot see: every
piece of mutable per-operator state an operator grows in its hot path must
be mirrored into the TableManager at barrier time and rebuilt at restore,
and every external side effect of a committing operator must wait for the
job-level commit message. PR 2 and PR 4 each found violations of exactly
this convention by chaos-testing after the fact; this module proves the
invariant statically, over every Operator/SourceOperator subclass in
``operators/``, ``windows/``, and ``connectors/`` (the LR2xx series, same
Diagnostic model as the plan analyzer and repo lint).

Per class the auditor builds a **mutable-state model**: instance
attributes assigned or mutated (``self.x = …``, ``+=``, ``.append`` /
``.add`` / ``.pop`` / …, subscript stores) inside hot-path methods
(``process_batch`` / ``handle_watermark`` / ``handle_tick`` / ``run`` /
``on_close``), resolved through the class's own helper methods (a mutation
in ``_drain()`` called from ``process_batch`` counts) and through sweep-
known base classes. Each hot-mutated attribute is then classified:

    covered          assigned/mutated in the ``on_start`` closure: restore
                     rebuilds it (from restored tables or deterministically)
                     before any batch flows, so replay sees the same value
    barrier-flushed  consumed AND reset inside the ``handle_checkpoint``
                     closure: its pre-barrier content was persisted or
                     emitted at the barrier, and post-barrier content is
                     rebuilt by source replay (e.g. a committing sink's
                     per-epoch buffer)
    lazy-memo        every hot-path store sits under an ``is None`` /
                     identity guard on the attribute itself: a derived
                     cache deterministically rebuilt on first use
    ephemeral        explicitly waived with ``# state: ephemeral — why``
                     on a line (or the line above one) that assigns or
                     mutates the attribute anywhere in the class
    LR201 (ERROR)    none of the above: unregistered mutable state — a
                     crash+restore silently forgets it and replay diverges

Rule catalog:

    LR201 unregistered-mutable-state   hot-path-mutated attribute with no
                                       checkpoint coverage (above)
    LR202 side-effect-not-commit-gated storage put / socket send / broker
                                       publish reachable from the hot path
                                       of a committing class
                                       (``is_committing`` can return True)
                                       must sit under ``handle_commit`` (or
                                       the post-commit control message) —
                                       waive with ``# effect: idempotent —
                                       why`` when the effect is safe to
                                       replay
    LR203 checkpoint-restore-asymmetry table name-sets written at the
                                       barrier, read at restore, and
                                       declared in ``tables()`` must agree
                                       (TableManager.restore loads by the
                                       DECLARED specs; an undeclared table
                                       restores with default retention, an
                                       unrestored one is silent data loss)
    LR204 unordered-iteration-emit     iterating a set/dict without
                                       ``sorted()`` on a path that reaches
                                       ``collector.collect`` — set order
                                       varies across processes (str hash
                                       randomization) and dict insertion
                                       order diverges after a restore, so
                                       emission order is not replay-stable

Waivers: LR201 takes the attribute-bound ``# state: ephemeral — why``
grammar; LR202 takes ``# effect: idempotent — why``; every rule also
accepts the repo-lint ``# lint: waive LR2xx — why`` form. A waiver with no
justification text does not suppress the finding.

The static verdict is cross-checked at runtime: tests/test_state_audit.py
runs a smoke pipeline, checkpoints, restores, and diffs every audited-
covered attribute across the roundtrip, failing if the auditor and the
engine disagree.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .diagnostics import Diagnostic, Severity, finish
from .repo_lint import ModuleInfo, _parse

# hot-path roots: methods the task run loop invokes per batch/signal/tick
HOT_ROOTS = ("process_batch", "process_batches", "handle_watermark",
             "handle_tick", "run", "on_close")
# LR202 scopes to the pre-barrier hot path; on_close is a legitimate final
# commit point (graceful drain: the operator is the only writer left)
EFFECT_ROOTS = ("process_batch", "process_batches", "handle_watermark",
                "handle_tick", "run")
CKPT_ROOT = "handle_checkpoint"
RESTORE_ROOT = "on_start"
COMMIT_ROOT = "handle_commit"

# attribute method calls that mutate the receiver in place
MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "push",
    "extend", "extendleft", "update", "insert", "remove", "discard",
    "clear", "setdefault", "sort", "reverse", "rotate",
    # tiered-state annex accessors (state/spill.py): probes tombstone what
    # they promote and spills move ownership, so every one of these is a
    # state mutation the replay contract must cover
    "lookup_many", "scan_expired", "spill", "spill_rows", "probe",
    "touch", "adopt",
})

_STATE_WAIVE_RE = re.compile(
    r"state:\s*ephemeral\s*(?:[-—:,]\s*)?(.*)", re.I)
_EFFECT_WAIVE_RE = re.compile(
    r"effect:\s*idempotent\s*(?:[-—:,]\s*)?(.*)", re.I)

# side-effect call shapes for LR202: (set of trailing call names that are
# effects on ANY receiver) and (names that are effects only with a
# receiver whose identifier suggests an external channel)
_EFFECT_ANY_RECV = frozenset({
    "produce", "publish", "basic_publish", "xadd", "send_message",
    "put_record", "put_records", "sendall",
})
_EFFECT_CHANNEL_RECV = frozenset({"send"})
_CHANNEL_HINTS = ("sock", "ws", "conn", "producer", "channel", "client",
                  "sess", "broker")
_STORAGE_WRITES = frozenset({"write_bytes", "write_text", "put_bytes"})


# --------------------------------------------------------------- AST mining


def _root_self_attr(expr: ast.expr) -> Optional[str]:
    """First attribute above ``self`` in a dotted chain (``self.a.b`` ->
    ``a``); None when the chain does not bottom out at ``self``."""
    attr = None
    while isinstance(expr, ast.Attribute):
        attr = expr.attr
        expr = expr.value
    if isinstance(expr, ast.Name) and expr.id == "self":
        return attr
    return None


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _recv_ident(call: ast.Call) -> str:
    """Identifier of the receiver (``producer`` in self.producer.produce)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Attribute):
            return v.attr
        if isinstance(v, ast.Subscript) and isinstance(v.value, (ast.Name, ast.Attribute)):
            return getattr(v.value, "id", getattr(v.value, "attr", ""))
    return ""


def _dotted(expr: ast.expr) -> str:
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


def _guard_attrs(test: ast.expr) -> set[str]:
    """Attributes null-checked by an ``if`` test made up ONLY of true
    lazy-init shapes: ``self.a is None`` / ``not self.a``. A test mixing
    in any other condition is NOT a memo guard — the monotone-advance
    pattern ``self.a is None or v > self.a`` and the change-tracking
    pattern ``self.a is not new`` both mutate real hot-path state."""
    if isinstance(test, ast.BoolOp):
        out: set[str] = set()
        for v in test.values:
            sub = _guard_attrs(v)
            if not sub:
                return set()
            out |= sub
        return out
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.ops[0], ast.Is):
        left, right = test.left, test.comparators[0]
        for side, other in ((left, right), (right, left)):
            a = _root_self_attr(side)
            if a and isinstance(other, ast.Constant) and other.value is None:
                return {a}
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        a = _root_self_attr(test.operand)
        if a:
            return {a}
    return set()


@dataclass
class AttrEvent:
    attr: str
    kind: str  # "store" | "mut" | "load"
    line: int
    memo: bool = False  # store under an is-None/identity guard on itself


@dataclass
class MethodModel:
    name: str
    relpath: str
    lineno: int
    events: list[AttrEvent] = field(default_factory=list)
    self_calls: set[str] = field(default_factory=set)
    # (table_name_or_None_if_dynamic, line) per table-manager access
    table_uses: list[tuple[Optional[str], int]] = field(default_factory=list)
    # TableSpec literal names (None = dynamic) declared in this method
    table_specs: list[tuple[Optional[str], int]] = field(default_factory=list)
    collects: bool = False  # calls collector.collect directly
    effects: list[tuple[str, int]] = field(default_factory=list)
    returns_true: bool = False  # any `return` that is not literal False/None
    local_unordered: set[str] = field(default_factory=set)  # set-typed locals
    # locals built as plain dicts in this method: per-call insertion order,
    # reproducible on replay, so iterating them is order-safe
    local_det_dicts: set[str] = field(default_factory=set)
    # (table_name_or_None, line) per checkpoint_manifest/restore_manifest
    # call — the tiered-state manifest convention check (name must end in
    # "__spill")
    manifest_uses: list[tuple[Optional[str], int]] = field(default_factory=list)
    fn: Optional[ast.FunctionDef] = None


_UNORDERED_CTORS = frozenset({"set", "dict", "frozenset", "defaultdict",
                              "Counter", "OrderedDict"})
# set-typed values iterate in hash order (varies across processes under str
# hash randomization); dict-typed ATTRIBUTES iterate in insertion order,
# which diverges once a restore rebuilds them in checkpoint-file order
_SET_CTORS = frozenset({"set", "frozenset"})
_DICT_CTORS = frozenset({"dict", "defaultdict", "Counter", "OrderedDict"})
# consumers that erase iteration order, so an unordered iterable is safe
_ORDER_INSENSITIVE = frozenset({"sorted", "min", "max", "sum", "any", "all",
                                "len", "set", "frozenset"})


def _is_unordered_expr(expr: ast.expr) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp, ast.Dict, ast.DictComp)):
        return True
    if isinstance(expr, ast.Call) and _call_name(expr) in _UNORDERED_CTORS:
        return True
    return False


def _is_set_expr(expr: ast.expr) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) and _call_name(expr) in _SET_CTORS:
        return True
    return False


def _is_dict_build_expr(expr: ast.expr) -> bool:
    if isinstance(expr, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(expr, ast.Call) and _call_name(expr) in _DICT_CTORS:
        return True
    return False


def _mine_method(fn: ast.FunctionDef, relpath: str) -> MethodModel:
    m = MethodModel(fn.name, relpath, fn.lineno, fn=fn)

    def record_call(n: ast.Call) -> None:
        name = _call_name(n)
        recv = _recv_ident(n)
        if isinstance(n.func, ast.Attribute):
            a = _root_self_attr(n.func.value)
            if a is not None and name in MUTATORS:
                m.events.append(AttrEvent(a, "mut", n.lineno))
            if isinstance(n.func.value, ast.Name) and n.func.value.id == "self":
                m.self_calls.add(name)
        if name in ("global_keyed", "expiring_time_key"):
            arg = n.args[0] if n.args else None
            lit = arg.value if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str) else None
            m.table_uses.append((lit, n.lineno))
        if name in ("persist_mark", "restore_marks"):
            # the shared meta-mark helpers (operators/base.py) take the
            # table name as their second argument
            arg = n.args[1] if len(n.args) > 1 else None
            lit = arg.value if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str) else None
            m.table_uses.append((lit, n.lineno))
        if name in ("checkpoint_manifest", "restore_manifest"):
            # tiered-state manifest helpers (state/spill.py): same
            # second-argument table-name shape as persist_mark, and the
            # name must follow the "<base>__spill" convention — the
            # checkpoint metadata and spill-run GC both key on the suffix
            arg = n.args[1] if len(n.args) > 1 else None
            lit = arg.value if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str) else None
            m.table_uses.append((lit, n.lineno))
            m.manifest_uses.append((lit, n.lineno))
        if name == "TableSpec":
            arg = n.args[0] if n.args else next(
                (k.value for k in n.keywords if k.arg == "name"), None)
            lit = arg.value if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str) else None
            m.table_specs.append((lit, n.lineno))
        if name == "collect" and "collector" in recv.lower():
            m.collects = True
        # LR202 effect shapes
        if name in _EFFECT_ANY_RECV:
            m.effects.append((f"{recv or '<expr>'}.{name}()", n.lineno))
        elif name in _EFFECT_CHANNEL_RECV and \
                any(h in recv.lower() for h in _CHANNEL_HINTS):
            m.effects.append((f"{recv}.{name}()", n.lineno))
        elif name in _STORAGE_WRITES and "storage" in _dotted(n.func).lower():
            m.effects.append((f"storage.{name}()", n.lineno))
        elif isinstance(n.func, ast.Name) and n.func.id == "open" and \
                len(n.args) >= 2 and isinstance(n.args[1], ast.Constant) and \
                isinstance(n.args[1].value, str) and \
                any(c in n.args[1].value for c in "wax"):
            m.effects.append(("open(..., 'w')", n.lineno))

    def store_target(t: ast.expr, line: int, memo_guarded: frozenset) -> None:
        if isinstance(t, ast.Attribute):
            a = _root_self_attr(t)
            if a is not None:
                if t.attr == a and isinstance(t.value, ast.Name):
                    m.events.append(AttrEvent(a, "store", line,
                                              memo=a in memo_guarded))
                else:  # self.a.b = ... mutates a
                    m.events.append(AttrEvent(a, "mut", line))
        elif isinstance(t, ast.Subscript):
            a = _root_self_attr(t.value)
            if a is not None:
                m.events.append(AttrEvent(a, "mut", line))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                store_target(e, line, memo_guarded)
        elif isinstance(t, ast.Starred):
            store_target(t.value, line, memo_guarded)

    def walk_expr(e: ast.AST) -> None:
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                record_call(n)
            elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
                a = _root_self_attr(n)
                if a is not None:
                    m.events.append(AttrEvent(a, "load", n.lineno))

    def walk_stmts(stmts: Iterable[ast.stmt], memo_guarded: frozenset) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.If):
                walk_expr(st.test)
                walk_stmts(st.body,
                           memo_guarded | frozenset(_guard_attrs(st.test)))
                walk_stmts(st.orelse, memo_guarded)
                continue
            if isinstance(st, ast.Assign):
                walk_expr(st.value)
                for t in st.targets:
                    store_target(t, st.lineno, memo_guarded)
                    if isinstance(t, ast.Name):
                        if _is_set_expr(st.value):
                            m.local_unordered.add(t.id)
                        elif _is_dict_build_expr(st.value):
                            m.local_det_dicts.add(t.id)
                continue
            if isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                if st.value is not None:
                    walk_expr(st.value)
                    if isinstance(st, ast.AnnAssign) and \
                            isinstance(st.target, ast.Name):
                        if _is_set_expr(st.value):
                            m.local_unordered.add(st.target.id)
                        elif _is_dict_build_expr(st.value):
                            m.local_det_dicts.add(st.target.id)
                if isinstance(st, ast.AnnAssign) and st.value is None:
                    continue  # bare annotation: no store happens
                kind_guard = memo_guarded if isinstance(st, ast.AnnAssign) \
                    else frozenset()
                t = st.target
                if isinstance(st, ast.AugAssign):
                    if isinstance(t, ast.Attribute):
                        a = _root_self_attr(t)
                        if a is not None:
                            m.events.append(AttrEvent(a, "mut", st.lineno))
                    elif isinstance(t, ast.Subscript):
                        a = _root_self_attr(t.value)
                        if a is not None:
                            m.events.append(AttrEvent(a, "mut", st.lineno))
                        walk_expr(t)
                else:
                    store_target(t, st.lineno, kind_guard)
                continue
            if isinstance(st, ast.Delete):
                for t in st.targets:
                    if isinstance(t, ast.Subscript):
                        a = _root_self_attr(t.value)
                        if a is not None:
                            m.events.append(AttrEvent(a, "mut", st.lineno))
                    elif isinstance(t, ast.Attribute):
                        a = _root_self_attr(t)
                        if a is not None:
                            m.events.append(AttrEvent(a, "mut", st.lineno))
                continue
            if isinstance(st, ast.For):
                walk_expr(st.iter)
                store_target(st.target, st.lineno, frozenset())
                walk_stmts(st.body, memo_guarded)
                walk_stmts(st.orelse, memo_guarded)
                continue
            if isinstance(st, ast.While):
                walk_expr(st.test)
                walk_stmts(st.body, memo_guarded)
                walk_stmts(st.orelse, memo_guarded)
                continue
            if isinstance(st, ast.With):
                for item in st.items:
                    walk_expr(item.context_expr)
                walk_stmts(st.body, memo_guarded)
                continue
            if isinstance(st, ast.Try):
                walk_stmts(st.body, memo_guarded)
                for h in st.handlers:
                    walk_stmts(h.body, memo_guarded)
                walk_stmts(st.orelse, memo_guarded)
                walk_stmts(st.finalbody, memo_guarded)
                continue
            if isinstance(st, ast.Return):
                if st.value is not None:
                    walk_expr(st.value)
                    is_false = isinstance(st.value, ast.Constant) and \
                        st.value.value in (False, None)
                    if not is_false:
                        m.returns_true = True
                continue
            # expression statements and everything else
            for sub in ast.iter_child_nodes(st):
                walk_expr(sub)

    walk_stmts(fn.body, frozenset())
    return m


@dataclass
class ClassModel:
    name: str
    relpath: str
    lineno: int
    bases: list[str]
    own_methods: dict[str, MethodModel]
    module: ModuleInfo

    def qualname(self) -> str:
        return f"{self.relpath}:{self.name}"


def _mine_class(cd: ast.ClassDef, mod: ModuleInfo) -> ClassModel:
    methods = {}
    for st in cd.body:
        if isinstance(st, ast.FunctionDef):
            methods[st.name] = _mine_method(st, mod.relpath)
    return ClassModel(cd.name, mod.relpath, cd.lineno,
                      [_dotted(b) for b in cd.bases], methods, mod)


# -------------------------------------------------------- class resolution


OPERATOR_BASES = ("Operator", "SourceOperator")


class Sweep:
    """All classes mined from the audited modules. Classes are keyed by
    QUALIFIED name (relpath:Class) so two same-named classes in different
    modules are both audited; base references resolve by simple name,
    preferring a same-module definition."""

    def __init__(self) -> None:
        self.classes: dict[str, ClassModel] = {}  # qualname -> model
        self._by_name: dict[str, list[ClassModel]] = {}

    def add_module(self, mod: ModuleInfo) -> None:
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.ClassDef):
                model = _mine_class(n, mod)
                if model.qualname() not in self.classes:
                    self.classes[model.qualname()] = model
                    self._by_name.setdefault(model.name, []).append(model)

    def _resolve_base(self, name: str, relpath: str) -> Optional[ClassModel]:
        cands = self._by_name.get(name, [])
        for c in cands:
            if c.relpath == relpath:
                return c
        return cands[0] if cands else None

    def _base_chain(self, model: ClassModel, seen: set[str]) -> list[ClassModel]:
        out = [model]
        for b in model.bases:
            b = b.rsplit(".", 1)[-1]
            if b in seen:
                continue
            seen.add(b)
            sub = self._resolve_base(b, model.relpath)
            if sub is not None:
                out.extend(self._base_chain(sub, seen))
        return out

    def is_operator(self, model: ClassModel) -> tuple[bool, bool]:
        """(is_operator_subclass, is_source)."""
        names = {model.name}
        for m in self._base_chain(model, {model.name}):
            names.update(b.rsplit(".", 1)[-1] for b in m.bases)
        is_src = "SourceOperator" in names
        return (bool(names & set(OPERATOR_BASES)), is_src)

    def resolved_methods(self, model: ClassModel) -> dict[str, MethodModel]:
        """Own methods plus inherited ones from sweep-known bases
        (nearest definition wins)."""
        out: dict[str, MethodModel] = {}
        for m in self._base_chain(model, {model.name}):
            for name, mm in m.own_methods.items():
                out.setdefault(name, mm)
        return out


# ------------------------------------------------------------ the analysis


@dataclass
class AttrVerdict:
    classification: str  # covered | barrier-flushed | lazy-memo | ephemeral
    #                      | ctor-constant | unregistered
    justification: str = ""
    sites: tuple = ()  # (relpath, line) mutation sites in hot paths


@dataclass
class ClassAudit:
    cls: str  # class name
    relpath: str
    attrs: dict[str, AttrVerdict] = field(default_factory=dict)

    def covered_attrs(self) -> list[str]:
        return sorted(a for a, v in self.attrs.items()
                      if v.classification == "covered")


def _closure(methods: dict[str, MethodModel], roots: Iterable[str]) -> set[str]:
    todo = [r for r in roots if r in methods]
    seen: set[str] = set()
    while todo:
        name = todo.pop()
        if name in seen:
            continue
        seen.add(name)
        todo.extend(c for c in methods[name].self_calls
                    if c in methods and c not in seen)
    return seen


def _attr_waiver(attr: str, methods: dict[str, MethodModel],
                 mods: dict[str, ModuleInfo]) -> Optional[str]:
    """``# state: ephemeral — why`` bound to any line that stores/mutates
    ``attr`` (or the line above it), anywhere in the class — the idiomatic
    spot is the attribute's ``__init__`` assignment."""
    for mm in methods.values():
        mod = mods.get(mm.relpath)
        if mod is None:
            continue
        for ev in mm.events:
            if ev.attr != attr or ev.kind == "load":
                continue
            for ln in (ev.line, ev.line - 1):
                m = _STATE_WAIVE_RE.search(mod.comments.get(ln, ""))
                if m and m.group(1).strip():
                    return m.group(1).strip()
    return None


def _line_waiver(mod: Optional[ModuleInfo], line: int, rule_id: str,
                 extra_re: Optional[re.Pattern] = None) -> Optional[str]:
    if mod is None:
        return None
    just = mod.waiver(line, rule_id)
    if just:
        return just
    if extra_re is not None:
        for ln in (line, line - 1):
            m = extra_re.search(mod.comments.get(ln, ""))
            if m and m.group(1).strip():
                return m.group(1).strip()
    return None


def audit_sweep(sweep: Sweep, mods: dict[str, ModuleInfo]
                ) -> tuple[list[Diagnostic], dict[str, ClassAudit]]:
    diags: list[Diagnostic] = []
    audits: dict[str, ClassAudit] = {}

    for qual in sorted(sweep.classes):
        model = sweep.classes[qual]
        cname = model.name
        is_op, is_source = sweep.is_operator(model)
        if not is_op or cname in OPERATOR_BASES:
            continue
        methods = sweep.resolved_methods(model)
        mod = mods.get(model.relpath)

        hot = _closure(methods, HOT_ROOTS)
        ckpt = _closure(methods, (CKPT_ROOT,))
        restore = _closure(methods, (RESTORE_ROOT,))
        effect_scope = _closure(methods, EFFECT_ROOTS)

        audit = ClassAudit(cname, model.relpath)
        audits[model.qualname()] = audit

        # ---- the per-attribute state model (LR201) -----------------------
        attr_events: dict[str, list[tuple[str, AttrEvent]]] = {}
        for mname, mm in methods.items():
            for ev in mm.events:
                attr_events.setdefault(ev.attr, []).append((mname, ev))

        for attr in sorted(attr_events):
            evs = attr_events[attr]
            hot_muts = [(mn, ev) for mn, ev in evs
                        if mn in hot and ev.kind in ("store", "mut")]
            real_muts = [(mn, ev) for mn, ev in hot_muts
                         if not (ev.kind == "store" and ev.memo)]
            stores_everywhere = [(mn, ev) for mn, ev in evs
                                 if ev.kind in ("store", "mut")]
            if not hot_muts:
                if stores_everywhere and all(mn == "__init__"
                                             for mn, _ in stores_everywhere):
                    audit.attrs[attr] = AttrVerdict("ctor-constant")
                continue
            sites = tuple(sorted({(methods[mn].relpath, ev.line)
                                  for mn, ev in real_muts or hot_muts}))
            restored = any(mn in restore and ev.kind in ("store", "mut")
                           for mn, ev in evs)
            if restored:
                audit.attrs[attr] = AttrVerdict("covered", sites=sites)
                continue
            if not real_muts:
                audit.attrs[attr] = AttrVerdict("lazy-memo", sites=sites)
                continue
            flushed = any(mn in ckpt and ev.kind == "store"
                          for mn, ev in evs) and \
                any(mn in ckpt and ev.kind == "load" for mn, ev in evs)
            if flushed:
                audit.attrs[attr] = AttrVerdict("barrier-flushed", sites=sites)
                continue
            just = _attr_waiver(attr, methods, mods)
            if just is None and sites:
                just = _line_waiver(mods.get(sites[0][0]), sites[0][1], "LR201")
            if just:
                audit.attrs[attr] = AttrVerdict("ephemeral", just, sites)
                continue
            audit.attrs[attr] = AttrVerdict("unregistered", sites=sites)
            rp, line = sites[0]
            diags.append(Diagnostic(
                "LR201", Severity.ERROR, f"{rp}:{line}",
                f"{cname}.{attr} is mutated on the hot path but never "
                "restored in on_start, never flushed at the barrier, and "
                "not waived: a crash+restore silently forgets it, so "
                "replay diverges from the original run",
                "mirror it into a TableManager table in handle_checkpoint "
                "and rebuild it in on_start, or annotate the assignment "
                "with `# state: ephemeral — <why replay-safe>`"))

        # ---- LR202: side effects outside the commit gate -----------------
        committing = any(mm.returns_true
                         for mname, mm in methods.items()
                         if mname == "is_committing")
        if committing:
            for mname in sorted(effect_scope):
                mm = methods[mname]
                for desc, line in mm.effects:
                    emod = mods.get(mm.relpath)
                    if _line_waiver(emod, line, "LR202", _EFFECT_WAIVE_RE):
                        continue
                    diags.append(Diagnostic(
                        "LR202", Severity.ERROR, f"{mm.relpath}:{line}",
                        f"{cname}: external side effect {desc} is reachable "
                        f"from {mname}() but the class commits via 2PC "
                        "(is_committing): effects must wait for "
                        "handle_commit / the commit control message, or a "
                        "replayed epoch re-fires them",
                        "move the effect under handle_commit, or waive with "
                        "`# effect: idempotent — <why replay is safe>`"))

        # ---- LR203: tables written vs restored vs declared ---------------
        decl_m = methods.get("tables")
        declared_pairs = decl_m.table_specs if decl_m else []
        ckpt_uses = [u for mn in ckpt for u in methods[mn].table_uses]
        restore_uses = [u for mn in restore for u in methods[mn].table_uses]
        if is_source and "run" in methods:
            run_cl = _closure(methods, ("run",))
            run_uses = [u for mn in run_cl for u in methods[mn].table_uses]
            ckpt_uses += run_uses
            restore_uses += run_uses
        dynamic = any(n is None for n, _ in declared_pairs) or \
            any(n is None for n, _ in ckpt_uses + restore_uses)
        if not dynamic and (declared_pairs or ckpt_uses or restore_uses):
            declared = {n for n, _ in declared_pairs}
            written = {n for n, _ in ckpt_uses}
            restored_t = {n for n, _ in restore_uses}
            site = f"{model.relpath}:{model.lineno}"
            if not _line_waiver(mod, model.lineno, "LR203"):
                for n in sorted(written - declared):
                    diags.append(Diagnostic(
                        "LR203", Severity.ERROR, site,
                        f"{cname} writes state table {n!r} at the barrier "
                        "but does not declare it in tables(): restore loads "
                        "by the declared specs, so this table comes back "
                        "with default retention (or not at all)",
                        f"add TableSpec({n!r}, ...) to tables()"))
                for n in sorted(restored_t - declared):
                    diags.append(Diagnostic(
                        "LR203", Severity.ERROR, site,
                        f"{cname} restores state table {n!r} in on_start "
                        "but does not declare it in tables()",
                        f"add TableSpec({n!r}, ...) to tables()"))
                for n in sorted(written - restored_t):
                    diags.append(Diagnostic(
                        "LR203", Severity.ERROR, site,
                        f"{cname} writes state table {n!r} at the barrier "
                        "but never reads it at restore: the snapshot is "
                        "dead weight and the state it mirrors is silently "
                        "lost on recovery",
                        "load it in on_start (table_manager."
                        f"global_keyed/expiring_time_key({n!r}))"))
                for n in sorted(restored_t - written):
                    diags.append(Diagnostic(
                        "LR203", Severity.ERROR, site,
                        f"{cname} restores state table {n!r} in on_start "
                        "but never writes it at the barrier: after the "
                        "first checkpoint the restored value is stale",
                        "write it in handle_checkpoint"))
                for n in sorted(declared - written - restored_t):
                    diags.append(Diagnostic(
                        "LR203", Severity.WARNING, site,
                        f"{cname} declares state table {n!r} in tables() "
                        "but neither writes it at the barrier nor reads it "
                        "at restore",
                        "remove the declaration or wire the table"))

        # ---- LR203b: the spill-manifest table name convention ------------
        # checkpoint metadata lifts run references and the spill-run GC
        # scans for liveness keyed on the "__spill" suffix: a manifest
        # persisted under any other name checkpoints fine but its runs are
        # invisible to GC liveness — they would be deleted under a live
        # checkpoint
        for mname, mm in sorted(model.own_methods.items()):
            for lit, line in mm.manifest_uses:
                if lit is None or lit.endswith("__spill"):
                    continue
                if _line_waiver(mods.get(mm.relpath), line, "LR203"):
                    continue
                diags.append(Diagnostic(
                    "LR203", Severity.ERROR, f"{mm.relpath}:{line}",
                    f"{cname}: spill manifest table {lit!r} does not end "
                    "in '__spill': checkpoint metadata and spill-run GC "
                    "both key on that suffix, so the runs this manifest "
                    "references are invisible to liveness tracking and "
                    "get deleted under a live checkpoint",
                    "name the manifest table '<base>__spill'"))

        # ---- LR204: unordered iteration feeding emission -----------------
        unordered_attrs: set[str] = set()
        for mm in methods.values():
            if mm.fn is None:
                continue
            for st in ast.walk(mm.fn):
                if isinstance(st, ast.Assign):
                    targets = st.targets
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    targets = [st.target]  # `self.buf: dict = {}` style
                else:
                    continue
                if not _is_unordered_expr(st.value):
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            _root_self_attr(t) == t.attr:
                        unordered_attrs.add(t.attr)
        emit_scope = hot | ckpt
        collecting = {mn for mn in methods if methods[mn].collects}
        # methods whose closure reaches a collect call
        reaches_collect = {mn for mn in methods
                           if _closure(methods, (mn,)) & collecting}
        for mname in sorted(emit_scope & reaches_collect):
            mm = methods[mname]
            if mm.fn is None:
                continue
            # arguments of order-insensitive consumers are exempt
            # (``sorted(x for x in self.buf)`` is the FIX, not a finding)
            exempt: set[int] = set()
            for n in ast.walk(mm.fn):
                if isinstance(n, ast.Call) and \
                        _call_name(n) in _ORDER_INSENSITIVE:
                    for a in ast.walk(n):
                        if a is not n:
                            exempt.add(id(a))
            iters: list[tuple[ast.expr, int]] = []
            for n in ast.walk(mm.fn):
                if isinstance(n, ast.For) and id(n.iter) not in exempt:
                    iters.append((n.iter, n.lineno))
                elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                    ast.GeneratorExp)) and id(n) not in exempt:
                    iters.extend((g.iter, n.lineno) for g in n.generators
                                 if id(g.iter) not in exempt)
            for it, lineno in iters:
                flagged = None
                if isinstance(it, ast.Call) and _call_name(it) in (
                        "keys", "values", "items"):
                    recv = it.func.value if isinstance(it.func, ast.Attribute) \
                        else None
                    a = _root_self_attr(recv) if recv is not None else None
                    nm = a or (recv.id if isinstance(recv, ast.Name) else "")
                    if isinstance(recv, ast.Name) and \
                            recv.id in mm.local_det_dicts:
                        continue  # per-call dict: replay-deterministic order
                    if isinstance(recv, ast.Attribute) and \
                            recv.attr == "columns":
                        # Batch.columns insertion order is fixed by batch
                        # construction, identical across replays
                        continue
                    flagged = f"{'self.' + a if a else nm or '<expr>'}." \
                              f"{_call_name(it)}()"
                elif isinstance(it, ast.Attribute):
                    a = _root_self_attr(it)
                    if a in unordered_attrs:
                        flagged = f"self.{a}"
                elif isinstance(it, ast.Name) and it.id in mm.local_unordered:
                    flagged = it.id
                elif isinstance(it, ast.Call) and _call_name(it) in _SET_CTORS:
                    flagged = f"{_call_name(it)}(...)"
                if flagged is None:
                    continue
                lmod = mods.get(mm.relpath)
                if _line_waiver(lmod, lineno, "LR204"):
                    continue
                diags.append(Diagnostic(
                    "LR204", Severity.ERROR, f"{mm.relpath}:{lineno}",
                    f"{cname}.{mname} iterates {flagged} (set/dict order) "
                    "on a path that reaches collector.collect: set order "
                    "varies across processes and dict insertion order "
                    "diverges after a restore, so emitted row order is not "
                    "replay-stable",
                    "iterate sorted(...) (or an explicitly ordered "
                    "structure), or waive with justification if order "
                    "provably cannot reach the output"))

    return finish(diags), audits


# ------------------------------------------------------------- entry points

AUDITED_DIRS = ("operators", "windows", "connectors")

RULES = ("LR201", "LR202", "LR203", "LR204")


def audit_modules(infos: list[ModuleInfo]) -> tuple[list[Diagnostic],
                                                    dict[str, ClassAudit]]:
    """Audit already-parsed modules (the lint sweep hands its own)."""
    sweep = Sweep()
    mods: dict[str, ModuleInfo] = {}
    for info in infos:
        mods[info.relpath] = info
        sweep.add_module(info)
    return audit_sweep(sweep, mods)


def audit_source(source: str, relpath: str = "operators/fixture.py"
                 ) -> list[Diagnostic]:
    """Audit one file's text (test surface)."""
    return audit_modules([_parse(source, relpath)])[0]


def audit_package(pkg_dir: Optional[str] = None
                  ) -> tuple[list[Diagnostic], dict[str, ClassAudit]]:
    """Audit the installed package's operator/window/connector modules."""
    if pkg_dir is None:
        import arroyo_tpu

        pkg_dir = os.path.dirname(os.path.abspath(arroyo_tpu.__file__))
    root = os.path.dirname(pkg_dir)
    infos: list[ModuleInfo] = []
    for d in AUDITED_DIRS:
        base = os.path.join(pkg_dir, d)
        if not os.path.isdir(base):
            continue
        for fname in sorted(os.listdir(base)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(base, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as f:
                infos.append(_parse(f.read(), rel))
    return audit_modules(infos)


def coverage_for_class(cls: type,
                       audits: Optional[dict[str, ClassAudit]] = None
                       ) -> Optional[ClassAudit]:
    """The audit entry for a live operator class (runtime cross-check
    surface): matched against the package audit by defining module + name
    where possible (same-named classes in different modules stay distinct),
    walking the MRO so test subclasses resolve to their audited base."""
    if audits is None:
        audits = audit_package()[1]
    for base in cls.__mro__:
        relpath = base.__module__.replace(".", "/") + ".py"
        hit = audits.get(f"{relpath}:{base.__name__}")
        if hit is not None:
            return hit
    by_name = {a.cls: a for a in audits.values()}
    for base in cls.__mro__:
        if base.__name__ in by_name:
            return by_name[base.__name__]
    return None
