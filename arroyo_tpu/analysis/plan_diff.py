"""Plan-diff pass: prove which operator state survives a live evolution.

Live pipeline evolution (``POST /api/v1/pipelines/<id>/evolve``) restarts a
*modified* plan from its predecessor's final checkpoint. Checkpointed bytes
are keyed by ``operator-{node_id}/table-{name}`` and typed by the operator
that wrote them, so restoring them under a changed plan is only sound when
the new operator would read exactly the layout the old one wrote. This pass
decides that at plan time — the same prove-don't-hope posture as the
replay-soundness auditor (LR2xx): it reuses LR203's literal table-name model
(operators declare their state as literal ``TableSpec`` names; the
checkpoint/restore sets must agree) and AR008's spec-instantiation machinery
(instantiate the registered constructor, read ``tables()`` — exactly what
the engine will build) to derive a per-node **state identity**:

    (op kind, declared TableSpecs, state-shaping config digest)

where the config digest covers everything that shapes state bytes or their
meaning: key fields, window widths/slides/gaps, TTLs, aggregate expressions,
connector/format/path of sources and sinks. Parallelism and descriptions are
excluded — rescale never changes state identity.

Operators are matched across the old and new graphs by stable lineage
(node id, then counter-stripped node name + identity, then identity alone —
planner node ids embed a sequence counter, so inserting one operator renames
everything planned after it) and every node is classified:

    carried        identical state identity: state restored verbatim from
                   the old node's checkpoint directory
    stateless      declares no state tables; nothing to carry
    rebuilt        a genuinely new stateful operator: restores nothing and
                   re-derives its state from rows replayed after the carried
                   source offsets (AR011, INFO). A redefined SINK also lands
                   here, not in incompatible: its only state is transient
                   pending-commit buffers, which the evolve drain's final
                   checkpoint-then-stop flushed to committed output before
                   the old set exited
    dropped        an old stateful operator with no successor: its state is
                   explicitly dropped and logged at restore (AR012, WARNING)
    incompatible   same lineage but changed identity (schema/key/window/
                   aggregate change): the new operator would misread the old
                   bytes, and re-deriving from mid-stream offsets would
                   silently lose the pre-checkpoint prefix — hard ERROR
                   (AR010), the pipeline never reaches Scheduling

``plan_fingerprint`` is the plan-hash stamped into job-level checkpoint
metadata and verified at restore: a restore against a different plan fails
loudly unless an explicit evolution mapping (the ``mapping`` this pass
emits) covers the change — degrade-not-corrupt.

Rule catalog (README "Static analysis" documents each):

    AR010 evolve-incompatible       changed state identity on a surviving
                                    operator would misread checkpointed
                                    bytes (ERROR; rejects the evolution)
    AR011 evolve-rebuilt            new stateful operator re-derives from
                                    replay; its pre-evolution prefix does
                                    not exist (INFO)
    AR012 evolve-dropped-state      old operator state has no successor and
                                    will be dropped (WARNING)
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Optional

from ..graph import Graph, Node, OpName, _jsonable
from .diagnostics import Diagnostic, Severity, finish

# config keys that never shape state bytes: layout/runtime decoration and
# pacing knobs (they change WHEN rows emit, never what checkpointed state
# means — a rethrottled source restores against the same fingerprint)
_NON_STATE_KEYS = ("description", "parallelism", "event_rate", "rate_phases",
                   "idle-time-ms")

# planner node ids are f"{kind}_{counter}" or f"{kind}_{counter}_{hint}":
# the counter is a global sequence, so ANY earlier plan edit renames every
# later node. Lineage matching strips it.
_ID_RE = re.compile(r"^(?P<kind>.+?)_(?P<n>\d+)(?:_(?P<hint>.*))?$")

# repr() fallbacks of live objects embed addresses ("<... at 0x7f...>");
# scrub them so identities and fingerprints are stable across processes
_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def stable_name(node_id: str) -> str:
    """Node id with the planner's sequence counter stripped:
    ``agg_4_tumbling_aggregate`` -> ``agg_tumbling_aggregate``."""
    m = _ID_RE.match(node_id)
    if not m:
        return node_id
    hint = m.group("hint")
    return f"{m.group('kind')}_{hint}" if hint else m.group("kind")


def _scrub(obj):
    if isinstance(obj, dict):
        if "__callable__" in obj:
            return "<callable>"
        return {k: _scrub(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_scrub(v) for v in obj]
    if isinstance(obj, str):
        return _ADDR_RE.sub(" at 0x..", obj)
    return obj


def _digest(obj) -> str:
    return hashlib.sha256(
        json.dumps(_scrub(_jsonable(obj)), sort_keys=True,
                   separators=(",", ":")).encode()
    ).hexdigest()[:16]


def _table_specs(node: Node) -> Optional[tuple]:
    """The node's declared state tables via AR008's spec-instantiation
    idiom: build the registered constructor on a COPY of the config
    (constructors may validate-and-mutate) and read ``tables()`` — the
    literal table-name model the replay-soundness auditor (LR203) proves
    checkpoint/restore agreement over. None when the constructor is
    unavailable here (optional dependency): the diff then falls back to
    the op-kind stateful heuristic rather than guessing a layout."""
    from ..engine.engine import construct_operator

    try:
        op = construct_operator(node.op, dict(node.config))
        specs = list(op.tables())
    except Exception:
        return None
    return tuple(sorted((s.name, s.kind, int(s.retention_micros))
                        for s in specs))


# ops that hold checkpointed state even when their constructor cannot be
# instantiated here (mirrors plan_passes._STATEFUL_OPS + sources/sinks,
# whose offset/commit tables also live in checkpoints)
_FALLBACK_STATEFUL = {
    OpName.TUMBLING_AGGREGATE, OpName.SLIDING_AGGREGATE,
    OpName.SESSION_AGGREGATE, OpName.INSTANT_JOIN,
    OpName.UPDATING_AGGREGATE, OpName.JOIN_WITH_EXPIRATION,
    OpName.WINDOW_FUNCTION, OpName.LOOKUP_JOIN,
    OpName.SOURCE, OpName.SINK,
}


@dataclass
class NodeIdentity:
    node_id: str
    op: OpName
    stable: str
    specs: Optional[tuple]  # None: constructor unavailable
    cfg_digest: str

    @property
    def stateful(self) -> bool:
        if self.specs is None:
            return self.op in _FALLBACK_STATEFUL
        return bool(self.specs)

    @property
    def identity(self) -> tuple:
        """The state identity two nodes must share for a verbatim carry."""
        return (self.op.value,
                self.specs if self.specs is not None else "<unavailable>",
                self.cfg_digest)


def node_identity(node: Node) -> NodeIdentity:
    cfg = {k: v for k, v in node.config.items() if k not in _NON_STATE_KEYS}
    return NodeIdentity(node.node_id, node.op, stable_name(node.node_id),
                        _table_specs(node), _digest(cfg))


def plan_fingerprint(graph: Graph) -> str:
    """Stable hash of everything that shapes checkpointed state and its
    meaning: per-node (id, op, state-shaping config, declared tables) plus
    the edge topology and schemas. Deliberately EXCLUDES parallelism — a
    rescale restores against the same fingerprint — and survives the
    Graph.dumps()/loads() round-trip the control plane ships IR through."""
    nodes = []
    for n in sorted(graph.nodes.values(), key=lambda n: n.node_id):
        ident = node_identity(n)
        nodes.append({"node_id": n.node_id, "op": n.op.value,
                      "cfg": ident.cfg_digest,
                      "tables": list(map(list, ident.specs or ()))})
    edges = sorted(
        json.dumps({"src": e.src, "dst": e.dst, "type": e.edge_type.value,
                    "schema": _scrub(_jsonable(e.schema.to_json()))},
                   sort_keys=True, separators=(",", ":"))
        for e in graph.edges
    )
    payload = json.dumps({"nodes": nodes, "edges": edges}, sort_keys=True,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass
class NodeClassification:
    node_id: str  # new-graph node id ("dropped": the OLD node id)
    action: str  # carried | stateless | rebuilt | dropped | incompatible
    from_node: Optional[str] = None  # old-graph node id (carried)
    detail: str = ""

    def to_json(self) -> dict:
        d = {"node_id": self.node_id, "action": self.action}
        if self.from_node is not None:
            d["from"] = self.from_node
        if self.detail:
            d["detail"] = self.detail
        return d


@dataclass
class PlanDiff:
    classifications: list[NodeClassification]
    diagnostics: list[Diagnostic]
    mapping: dict = field(default_factory=dict)

    @property
    def rejected(self) -> bool:
        return any(d.severity == Severity.ERROR for d in self.diagnostics)

    def to_json(self) -> dict:
        return {
            "classifications": [c.to_json() for c in self.classifications],
            "rejected": self.rejected,
            "mapping": self.mapping,
        }


def diff_plans(old_graph: Graph, new_graph: Graph) -> PlanDiff:
    """Match operators across the old and new plans by stable identity and
    classify each (module docstring has the taxonomy). Returns the
    classifications, the AR010-012 diagnostics, and the evolution mapping
    the restore path applies (``TableManager.restore`` / ``Engine.build``):

        {"old_plan_hash": ..., "new_plan_hash": ...,
         "nodes": {new_id: {"action": "carried", "from": old_id,
                            "tables": [...]}
                   | {"action": "rebuilt"} | {"action": "stateless"}},
         "dropped": [old_id, ...]}
    """
    old_ids = {n.node_id: node_identity(n) for n in old_graph.topo_order()}
    new_ids = {n.node_id: node_identity(n) for n in new_graph.topo_order()}

    cls: list[NodeClassification] = []
    diags: list[Diagnostic] = []
    matched: dict[str, str] = {}  # new id -> old id
    unmatched_old = dict(old_ids)  # topo-ordered (dict preserves insertion)

    def match(nid: str, oid: str) -> None:
        matched[nid] = oid
        unmatched_old.pop(oid, None)

    # stateless new nodes never carry anything: classify directly
    for nid, ident in new_ids.items():
        if not ident.stateful:
            cls.append(NodeClassification(nid, "stateless"))

    stateful_new = {nid: i for nid, i in new_ids.items() if i.stateful}

    # pass A — same node id, same identity: the operator is untouched
    for nid, ident in stateful_new.items():
        old = unmatched_old.get(nid)
        if old is not None and old.stateful and old.identity == ident.identity:
            match(nid, nid)

    # pass B — same counter-stripped name + identity: the planner renumbered
    # it because an earlier statement changed, but the operator is the same
    for nid, ident in stateful_new.items():
        if nid in matched:
            continue
        for oid, old in unmatched_old.items():
            if (old.stateful and old.stable == ident.stable
                    and old.identity == ident.identity):
                match(nid, oid)
                break

    # pass C — identity alone (a rename: same state, different SQL alias)
    for nid, ident in stateful_new.items():
        if nid in matched:
            continue
        for oid, old in unmatched_old.items():
            if old.stateful and old.identity == ident.identity:
                match(nid, oid)
                break

    for nid, oid in matched.items():
        cls.append(NodeClassification(
            nid, "carried", from_node=oid,
            detail="" if nid == oid else f"renamed from {oid}"))

    # pass D — same lineage, CHANGED identity: the old bytes would be
    # misread (or the pre-checkpoint prefix silently lost). Hard reject.
    for nid, ident in stateful_new.items():
        if nid in matched:
            continue
        old = None
        if nid in unmatched_old and unmatched_old[nid].stateful:
            old = unmatched_old[nid]
        else:
            for oid, cand in unmatched_old.items():
                if cand.stateful and cand.stable == ident.stable:
                    old = cand
                    break
        if old is None:
            cls.append(NodeClassification(
                nid, "rebuilt",
                detail="new stateful operator: state re-derived from rows "
                       "replayed after the carried source offsets"))
            diags.append(Diagnostic(
                "AR011", Severity.INFO, nid,
                f"{ident.op.value} is new in the evolved plan: its state is "
                "rebuilt by replay, so results covering rows consumed before "
                "the evolution point will not include it",
                "expected for a genuinely new aggregation; if this operator "
                "was meant to carry state, keep its window/key/aggregate "
                "configuration identical"))
            continue
        unmatched_old.pop(old.node_id, None)
        if ident.op == OpName.SINK and old.op == OpName.SINK:
            # sinks are the one stateful kind whose identity may change:
            # their only state is transient pending-commit buffers, and the
            # evolve drain's final checkpoint-then-stop flushes them to
            # committed part files BEFORE the old set exits (on_close) —
            # the carried prefix is already durable, immutable output, so
            # the redefined sink starts empty without losing a byte
            cls.append(NodeClassification(
                nid, "rebuilt", from_node=old.node_id,
                detail="sink definition changed: the old sink's pending-"
                       "commit buffers were flushed at the drain barrier; "
                       "committed output is immutable"))
            diags.append(Diagnostic(
                "AR011", Severity.INFO, nid,
                f"sink {nid} is redefined (was {old.node_id}): its pending-"
                "commit buffers were flushed by the drain's final "
                "checkpoint, so it restarts empty with the carried prefix "
                "already committed",
                "no action needed; previously committed output files are "
                "never rewritten"))
            continue
        what = _identity_delta(old, ident)
        cls.append(NodeClassification(
            nid, "incompatible", from_node=old.node_id, detail=what))
        diags.append(Diagnostic(
            "AR010", Severity.ERROR, nid,
            f"incompatible evolution of {ident.op.value} "
            f"(was {old.node_id}): {what}; restoring the old checkpoint "
            "bytes under the new definition would misread state, and "
            "replaying from mid-stream offsets would silently drop the "
            "pre-evolution prefix",
            "evolution can only carry state across identical window/key/"
            "aggregate/table definitions; deploy this change as a new "
            "pipeline instead"))

    for oid, old in unmatched_old.items():
        if oid in matched.values() or not old.stateful:
            continue
        cls.append(NodeClassification(
            oid, "dropped",
            detail="no successor in the evolved plan; state dropped"))
        diags.append(Diagnostic(
            "AR012", Severity.WARNING, oid,
            f"{old.op.value} has no successor in the evolved plan: its "
            "checkpointed state will be explicitly dropped at restore "
            "(logged, never silently resurrected)",
            "expected when an aggregation was removed; re-adding it later "
            "starts from empty state"))

    mapping_nodes: dict[str, dict] = {}
    dropped: list[str] = []
    for c in cls:
        if c.action == "carried":
            ident = new_ids[c.node_id]
            mapping_nodes[c.node_id] = {
                "action": "carried", "from": c.from_node,
                "tables": [s[0] for s in (ident.specs or ())],
            }
        elif c.action == "rebuilt":
            mapping_nodes[c.node_id] = {"action": "rebuilt"}
            if c.from_node and c.from_node not in {
                    m.get("from") for m in mapping_nodes.values()}:
                # a redefined sink's predecessor: its buffered state is
                # explicitly dropped (the drain already committed it)
                dropped.append(c.from_node)
        elif c.action == "stateless":
            mapping_nodes[c.node_id] = {"action": "stateless"}
        elif c.action == "dropped":
            dropped.append(c.node_id)
    # stateless old nodes the evolved plan renumbered away still appear in
    # checkpoint metadata's operator list; record them as (harmless) drops
    # so the restore path's stale-operator gate knows they were accounted for
    for oid, old in unmatched_old.items():
        if oid not in matched.values() and not old.stateful:
            dropped.append(oid)
    mapping = {
        "old_plan_hash": plan_fingerprint(old_graph),
        "new_plan_hash": plan_fingerprint(new_graph),
        "nodes": mapping_nodes,
        "dropped": sorted(set(dropped)),
    }
    order = {"incompatible": 0, "dropped": 1, "rebuilt": 2, "carried": 3,
             "stateless": 4}
    cls.sort(key=lambda c: (order[c.action], c.node_id))
    return PlanDiff(cls, finish(diags), mapping)


def _identity_delta(old: "NodeIdentity", new: "NodeIdentity") -> str:
    if old.op != new.op:
        return f"operator kind changed ({old.op.value} -> {new.op.value})"
    if (old.specs or ()) != (new.specs or ()):
        o = {s[0] for s in (old.specs or ())}
        n = {s[0] for s in (new.specs or ())}
        if o != n:
            return (f"declared state tables changed "
                    f"({sorted(o)} -> {sorted(n)})")
        return "state table kinds/retentions changed"
    return ("state-shaping configuration changed (key schema, window "
            "width/slide/gap, TTL, or aggregate expressions)")
