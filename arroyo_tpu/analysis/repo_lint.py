"""Repo lint engine: AST checks encoding invariants this repo paid to learn.

Each rule exists because its violation has already cost a debugging session
here (see CHANGES.md): ad-hoc sleep loops hid unrecoverable retries until
the chaos suite replaced them with the shared layer; a swallowed exception
let a crashed pipeline report success; an unseeded random in an operator
made replay nondeterministic; a peer dial under the connection-map lock
stalled every sender. The linter makes the lesson structural.

Rule catalog:

    LR101 ad-hoc-retry-sleep   ``time.sleep`` inside an except handler whose
                               delay does not come from the shared
                               utils/retry layer (Backoff.next_delay)
    LR102 swallowed-exception  bare ``except:`` anywhere; ``except
                               (Base)Exception: pass`` in engine/state/
                               connector/controller code
    LR103 unseeded-random      module-level random / np.random calls in
                               operator or engine code (replay determinism)
    LR104 host-sync-hot-path   ``.block_until_ready()`` / ``float()`` /
                               ``np.asarray`` on device values inside
                               operator ``process_batch`` hot paths
    LR105 lock-across-blocking RETIRED as a standalone rule: folded into
                               the interprocedural LR403 (concurrency
                               auditor), which follows same-class helper
                               calls to the blocking sink. The LR105 id
                               still binds as a waiver alias at LR403
                               sites, so existing waivers keep suppressing
    LR106 fault-site-coverage  storage/network/queue mutations must route
                               through ``faults`` hooks; every declared
                               fault site must be wired somewhere
    LR107 emit-in-loop         direct ``collector.collect(...)`` inside a
                               Python loop in operator hot-path code: one
                               sub-threshold batch per iteration pays full
                               per-batch overhead per emit; build columns
                               across iterations and emit once (the
                               coalescing layer smooths queue transits, but
                               cannot remove per-collect routing work)
    LR108 bare-print           ``print()`` in arroyo_tpu/ library code
                               (outside cli.py/__main__.py): worker stdout
                               IS the JSON-lines control protocol, so a
                               stray print corrupts controller event
                               parsing — and it bypasses the configured
                               logging format/level; route through
                               ``logging.getLogger(...)``
    LR109 ad-hoc-self-timing   ``time.time()``/``time.monotonic()``/
                               ``time.perf_counter()``/``time.thread_time()``
                               in operator/window/state code: self-
                               measurement belongs in the profiler hooks
                               (obs/profile.py TaskProfiler wraps every
                               operator hook), or cost attribution
                               fragments into untrackable side channels.
                               Legitimate wall-clock uses (cache TTLs,
                               event-time idle detection, coalescing
                               deadlines) carry waivers naming the reason
    LR110 logger-in-function   ``logging.getLogger("name")`` inside a
                               function body: acquire the module's logger
                               ONCE at module level (``_log = logging.
                               getLogger(...)``) — per-call acquisition
                               hides the logger from level configuration
                               audits, re-pays the registry lookup on hot
                               error paths, and encourages the inline
                               ``import logging`` that shadows the
                               structured-events bridge setup. Bare
                               ``logging.getLogger()`` (the root logger,
                               used by logging-INIT code) is exempt
    LR111 jit-in-hot-path      ``jax.jit`` / ``pjit`` invocation inside an
                               operator hot-path method (process_batch /
                               handle_watermark / handle_tick): a per-batch
                               jit builds a fresh callable and re-traces +
                               XLA-compiles on every call — the classic
                               silent perf bug the whole-segment compiler
                               exists to prevent. Compiled callables belong
                               in the segment-compiler cache (engine/
                               segment.py) or a once-per-config builder
                               (ops/slot_agg.py _build_slot_jax); hot
                               paths only CALL them

The LR2xx series (replay-soundness audit: checkpoint-coverage of operator
state, commit-gated side effects, checkpoint/restore table symmetry,
ordered emission) lives in ``state_audit.py`` and runs as part of every
``lint_paths`` sweep that touches operators/, windows/, or connectors/.
The LR3xx series (trace-safety audit: purity/host-sync, shape stability,
allowlist drift, and dual-path dtype parity of segment-compiled and device
code) lives in ``trace_audit.py`` and runs as a whole-program pass over
every ``lint_paths`` sweep.

Waivers: append ``# lint: waive LR1xx — justification`` on the flagged
line (or the line above). A waiver with no justification text does not
suppress the finding.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .diagnostics import Diagnostic, Severity, finish

_WAIVE_RE = re.compile(r"lint:\s*waive\s+(LR\d+)\s*(?:[-—:,]\s*)?(.*)", re.I)


@dataclass
class ModuleInfo:
    relpath: str  # forward-slash path relative to the repo/package root
    tree: ast.AST
    comments: dict[int, str] = field(default_factory=dict)  # line -> text
    # local name -> canonical dotted origin, mined from module imports
    # (``import jax.numpy as whatever`` -> {"whatever": "jax.numpy"},
    # ``from jax import jit as J`` -> {"J": "jax.jit"}), so no rule keyed
    # on a module/function name can be dodged by an import alias
    aliases: dict[str, str] = field(default_factory=dict)

    def in_dirs(self, *dirs: str) -> bool:
        parts = self.relpath.split("/")
        return any(d in parts for d in dirs)

    def canonical(self, dotted: str) -> str:
        """Rewrite the leading segment of a dotted name through the
        module's import aliases (``whatever.asarray`` -> ``jax.numpy.
        asarray``). Names with no alias pass through unchanged."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        root = self.aliases.get(head)
        if root is None:
            return dotted
        return f"{root}.{rest}" if rest else root

    def waiver(self, line: int, rule_id: str) -> Optional[str]:
        """Justification text if a valid waiver covers (line, rule)."""
        for ln in (line, line - 1):
            m = _WAIVE_RE.search(self.comments.get(ln, ""))
            if m and m.group(1).upper() == rule_id and m.group(2).strip():
                return m.group(2).strip()
        return None


def _mine_aliases(tree: ast.AST) -> dict[str, str]:
    """Module-wide import alias map (absolute imports only: relative
    imports bind package-internal names the rules never key on)."""
    out: dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.asname:
                    out[a.asname] = a.name
                else:  # `import jax.numpy` binds the root name `jax`
                    root = a.name.split(".")[0]
                    out.setdefault(root, root)
        elif isinstance(n, ast.ImportFrom) and n.module and not n.level:
            for a in n.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{n.module}.{a.name}"
    return out


def _parse(source: str, relpath: str) -> ModuleInfo:
    info = ModuleInfo(relpath.replace(os.sep, "/"), ast.parse(source))
    info.aliases = _mine_aliases(info.tree)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                info.comments[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return info


# ------------------------------------------------------------- AST helpers


def _call_name(call: ast.Call) -> str:
    """Trailing identifier of the called expression ('sleep', 'put', ...)."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _receiver_name(call: ast.Call) -> str:
    """Identifier the method is called on ('time' in time.sleep, '_out' in
    self._out.get); empty for plain names."""
    f = call.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Attribute):
            return v.attr
    return ""


def _dotted(expr: ast.expr) -> str:
    """Best-effort dotted name ('np.random.uniform')."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


def _mentions_lock(expr: ast.expr) -> bool:
    for n in ast.walk(expr):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident is not None and "lock" in ident.lower():
            return True
    return False


def _walk_skipping_nested_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a statement body without descending into nested function/class
    defs (their bodies execute later, outside the enclosing region)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


Finding = tuple[int, str, str]  # line, message, hint


# ------------------------------------------------------------------- rules


def rule_lr101(mod: ModuleInfo) -> Iterable[Finding]:
    """time.sleep inside an except handler = a hand-rolled retry backoff,
    unless the delay comes from the shared retry layer."""
    if mod.relpath.endswith("utils/retry.py"):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            # canonical first: `from time import sleep as zz; zz(...)`
            # must resolve — the bare-name dodge the alias map exists for
            is_sleep = mod.canonical(_dotted(n.func)) == "time.sleep" or (
                _call_name(n) == "sleep"
                and _receiver_name(n) in ("time", "_time"))
            if not is_sleep:
                continue
            from_shared = any(
                isinstance(a, ast.Call) and _call_name(a) == "next_delay"
                for arg in n.args for a in ast.walk(arg)
            )
            if not from_shared:
                yield (n.lineno,
                       "ad-hoc retry backoff: time.sleep inside an except "
                       "handler with a delay not drawn from the shared retry "
                       "layer",
                       "use utils/retry.py (retry_call, or Backoff.next_delay "
                       "for loops)")


def rule_lr102(mod: ModuleInfo) -> Iterable[Finding]:
    """Bare except anywhere; silently-swallowed broad except in the
    engine/state/connector/controller layers."""
    strict_scope = mod.in_dirs("engine", "state", "connectors", "controller")
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield (node.lineno,
                   "bare except: catches KeyboardInterrupt/SystemExit and "
                   "hides programming errors",
                   "catch Exception (or the specific errors) instead")
            continue
        if not strict_scope:
            continue
        broad = isinstance(node.type, ast.Name) and node.type.id in (
            "Exception", "BaseException")
        swallows = len(node.body) == 1 and isinstance(node.body[0], ast.Pass)
        if broad and swallows:
            yield (node.lineno,
                   "swallowed exception: broad except with a bare `pass` in "
                   "engine/state/connector code can hide real failures "
                   "(a crashed pipeline once reported success this way)",
                   "log it, narrow the type, or waive with justification if "
                   "failure here is genuinely unactionable")


_RANDOM_FNS = {"random", "randrange", "randint", "uniform", "choice",
               "choices", "shuffle", "sample", "normal", "rand", "randn"}


def rule_lr103(mod: ModuleInfo) -> Iterable[Finding]:
    """Module-level random/np.random draws in operator or engine code break
    replay determinism (checkpoint recovery re-executes these paths)."""
    if not mod.in_dirs("operators", "ops", "windows", "parallel", "engine"):
        return
    for n in ast.walk(mod.tree):
        if not isinstance(n, ast.Call):
            continue
        dn = mod.canonical(_dotted(n.func))
        if dn.startswith(("random.", "numpy.random.")) and \
                dn.rsplit(".", 1)[-1] in _RANDOM_FNS:
            yield (n.lineno,
                   f"unseeded {dn}() in operator/engine code: output differs "
                   "across replays, so checkpoint recovery is no longer "
                   "byte-exact",
                   "derive the value deterministically (task identity, "
                   "config seed) or use a seeded Random instance")


def rule_lr104(mod: ModuleInfo) -> Iterable[Finding]:
    """Host-sync in the per-batch hot path: block_until_ready anywhere in
    operator code; float()/np.asarray()/np.array() applied to values that
    came off the device inside process_batch."""
    if not mod.in_dirs("operators", "ops", "windows", "parallel"):
        return
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and _call_name(n) == "block_until_ready":
            yield (n.lineno,
                   ".block_until_ready() in operator code forces a host sync "
                   "per batch, serializing the device pipeline",
                   "let values stay on device; sync only at sinks or "
                   "checkpoint boundaries")
    for fn in ast.walk(mod.tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name in ("process_batch", "process_batches")):
            continue
        device_names: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                produces_device = any(
                    isinstance(c, ast.Call) and (
                        _call_name(c) == "eval_jnp"
                        or mod.canonical(_dotted(c.func)).startswith(
                            ("jax.", "jnp."))
                    )
                    for c in ast.walk(n.value)
                )
                if produces_device:
                    device_names.add(n.targets[0].id)
        if not device_names:
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            arg0 = n.args[0]
            if not (isinstance(arg0, ast.Name) and arg0.id in device_names):
                continue
            dn = mod.canonical(_dotted(n.func))
            if dn == "float" or dn in ("numpy.asarray", "numpy.array",
                                       "np.asarray", "np.array"):
                yield (n.lineno,
                       f"{dn}() on a device value inside {fn.name}: forces a "
                       "blocking device->host transfer in the per-batch hot "
                       "path",
                       "keep the value in jnp, or move the transfer to flush/"
                       "checkpoint time")


# LR105 (intraprocedural lock-across-blocking) is retired: the concurrency
# auditor's LR403 subsumes it with interprocedural reach (same-class helper
# closures, lock entry contexts) and runs in every lint_paths sweep below.
# Existing `# lint: waive LR105` comments still bind at LR403 sites.


# file-suffix -> (functions that mutate storage/network/queues, gateways
# that count as routing through the fault layer)
_LR106_TARGETS = {
    "state/storage.py": (
        ("read_bytes", "write_bytes", "read_text", "write_text", "exists",
         "isdir", "listdir", "remove", "rmtree"),
        ("fault_point", "_guarded"),
    ),
    "engine/network.py": (
        ("put", "_read_loop"),
        ("fault_point",),
    ),
    "engine/queues.py": (
        ("put",),
        ("fault_point",),
    ),
}


def rule_lr106(mod: ModuleInfo) -> Iterable[Finding]:
    """Every storage/network/queue mutation must route through the faults
    hooks — otherwise the chaos suite silently stops covering it."""
    target = next((v for k, v in _LR106_TARGETS.items()
                   if mod.relpath.endswith(k)), None)
    if target is None:
        return
    required, gateways = target
    # intra-module call graph over every function (methods by bare name)
    funcs: dict[str, list[ast.FunctionDef]] = {}
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.FunctionDef):
            funcs.setdefault(n.name, []).append(n)

    def reaches_gateway(name: str, seen: set[str]) -> bool:
        if name in seen:
            return False
        seen.add(name)
        for fn in funcs.get(name, []):
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    cn = _call_name(n)
                    if cn in gateways:
                        return True
                    if cn in funcs and reaches_gateway(cn, seen):
                        return True
        return False

    for name in required:
        for fn in funcs.get(name, []):
            if not reaches_gateway(name, set()):
                yield (fn.lineno,
                       f"{name}() mutates storage/network/queue state but "
                       "never routes through a faults hook; chaos tests "
                       "cannot exercise its failure path",
                       "call faults.fault_point(...) (directly or via the "
                       "module's guarded helper) inside the operation")


def rule_lr107(mod: ModuleInfo) -> Iterable[Finding]:
    """Per-iteration emits in operator hot paths: N tiny batches through
    collector -> queue -> data plane where one coalesced batch would do.
    The fused multi-window closes (InstantJoin/SlidingAggregate) exist
    precisely to keep this pattern out of the emission path."""
    if not mod.in_dirs("operators", "windows", "ops"):
        return
    seen: set[int] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for n in _walk_skipping_nested_defs(node):
            if (isinstance(n, ast.Call) and _call_name(n) == "collect"
                    and "collector" in _receiver_name(n).lower()
                    and n.lineno not in seen):
                seen.add(n.lineno)
                yield (n.lineno,
                       "collector.collect() inside a loop emits one "
                       "sub-threshold batch per iteration through the full "
                       "collector/queue/data-plane path",
                       "accumulate the iterations' columns and emit one "
                       "batch after the loop (see the fused multi-window "
                       "closes), or waive with justification")


def rule_lr108(mod: ModuleInfo) -> Iterable[Finding]:
    """Bare print() in library code. A worker subprocess's stdout is the
    JSON-lines wire protocol to the controller (scheduler.py docstring):
    a print from engine/operator/connector code interleaves garbage into
    the event stream (the reader skips unparseable lines, silently losing
    the message). CLI entry points (cli.py, __main__.py) own their stdout
    and are exempt; bench.py and tools/ live outside the package."""
    if not mod.relpath.startswith("arroyo_tpu/"):
        return
    if mod.relpath in ("arroyo_tpu/cli.py", "arroyo_tpu/__main__.py"):
        return
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "print":
            yield (n.lineno,
                   "bare print() in library code: worker stdout is the "
                   "JSON-lines control protocol (a stray line corrupts "
                   "controller event parsing) and prints bypass the "
                   "configured logging format/level",
                   "route through logging.getLogger('arroyo_tpu...') — or "
                   "waive with justification for genuinely CLI-owned output")


_LR109_TIME_FNS = {"time", "monotonic", "perf_counter", "thread_time",
                   "process_time", "monotonic_ns", "perf_counter_ns",
                   "thread_time_ns", "process_time_ns"}


def rule_lr109(mod: ModuleInfo) -> Iterable[Finding]:
    """Clock reads in operator/window/state code. Self-time measurement is
    the profiler's job (obs/profile.py wraps every operator hook with
    wall + thread-CPU accounting) — a stray stopwatch in an operator both
    duplicates that attribution and, worse, escapes it. Non-measurement
    clock uses (cache TTLs, idle detection, flush deadlines) are real and
    carry waivers so each documents why it is not self-measurement."""
    if not mod.in_dirs("operators", "windows", "state", "ops"):
        return
    for n in ast.walk(mod.tree):
        if not isinstance(n, ast.Call):
            continue
        dn = mod.canonical(_dotted(n.func))
        clock = (dn.startswith("time.") and
                 dn.split(".", 1)[1] in _LR109_TIME_FNS) or \
            (_receiver_name(n) in ("time", "_time")
             and _call_name(n) in _LR109_TIME_FNS)
        if clock:
            yield (n.lineno,
                   f"{_receiver_name(n) or dn.rsplit('.', 1)[0]}."
                   f"{_call_name(n)}() in operator/"
                   "window/state code: self-measurement belongs in the "
                   "profiler hooks (obs/profile.py), where it lands in "
                   "arroyo_worker_self_time_seconds instead of a side "
                   "channel",
                   "let the task run loop attribute the cost; for a "
                   "genuine wall-clock need (TTL, idle detection, flush "
                   "deadline), waive with the reason")


def rule_lr110(mod: ModuleInfo) -> Iterable[Finding]:
    """Named logger acquisition inside a function body. The package's
    convention is one module-level ``_log = logging.getLogger(...)``;
    inline acquisition (found twice in controller.py before this rule)
    drifts into per-call ``import logging`` blocks and makes the set of
    logger names impossible to audit statically."""
    if not mod.relpath.startswith("arroyo_tpu/"):
        return
    seen: set[int] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in ast.walk(node):
            if (isinstance(n, ast.Call) and _call_name(n) == "getLogger"
                    and _receiver_name(n) == "logging"
                    and (n.args or n.keywords)  # bare root-logger is exempt
                    and n.lineno not in seen):
                seen.add(n.lineno)
                yield (n.lineno,
                       "logging.getLogger(...) inside a function body: "
                       "loggers are acquired once at module level in this "
                       "package, so names stay statically auditable and "
                       "hot error paths skip the registry lookup",
                       "hoist to a module-level `_log = logging."
                       "getLogger(\"arroyo_tpu...\")` and use _log here")


_LR111_HOT_METHODS = ("process_batch", "process_batches", "handle_watermark",
                      "handle_tick")
_LR111_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit",
                    "jax.experimental.pjit.pjit")


def rule_lr111(mod: ModuleInfo) -> Iterable[Finding]:
    """jit/pjit invocation inside operator hot paths. ``jax.jit(fn)`` per
    batch builds a fresh jitted callable whose trace cache dies with it —
    every batch pays a full retrace + XLA compile (tens of ms) that
    profiles as 'process' self-time and silently eats the win it was meant
    to buy. Compiled callables are built once per (segment, schema) in the
    segment-compiler cache, or once per operator config; hot paths only
    CALL them."""
    if not mod.in_dirs("operators", "windows", "ops"):
        return
    for fn in ast.walk(mod.tree):
        if not (isinstance(fn, ast.FunctionDef)
                and fn.name in _LR111_HOT_METHODS):
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            dn = mod.canonical(_dotted(n.func))
            if dn in _LR111_JIT_NAMES or dn.endswith((".jit", ".pjit")):
                yield (n.lineno,
                       f"{dn}() inside {fn.name}: a per-batch jit builds a "
                       "fresh callable and re-traces/compiles on every "
                       "batch — the retrace-per-batch bug the segment "
                       "compiler (engine/segment.py) exists to prevent",
                       "build the jitted callable once — in the segment-"
                       "compiler cache or a per-config builder — and only "
                       "call it from the hot path")


RULES: tuple[tuple[str, Severity, object], ...] = (
    ("LR101", Severity.ERROR, rule_lr101),
    ("LR102", Severity.ERROR, rule_lr102),
    ("LR103", Severity.ERROR, rule_lr103),
    ("LR104", Severity.WARNING, rule_lr104),
    ("LR106", Severity.ERROR, rule_lr106),
    ("LR107", Severity.ERROR, rule_lr107),
    ("LR108", Severity.ERROR, rule_lr108),
    ("LR109", Severity.ERROR, rule_lr109),
    ("LR110", Severity.ERROR, rule_lr110),
    ("LR111", Severity.ERROR, rule_lr111),
)

# fault sites every full-package lint must find wired (mirrors faults.SITES;
# a literal copy so the linter itself has no runtime imports of the engine)
_DECLARED_FAULT_SITES = (
    "storage.put", "storage.get", "storage.delete", "storage.list",
    "storage.multipart", "network.send", "network.recv", "queue.put",
    "connector.poll", "connector.commit", "worker", "worker.heartbeat",
    "node.start_worker", "controller_rpc", "commit", "rescale",
    "autoscale_decide", "spill_write", "spill_probe", "spill_compact",
    "admission", "fleet_place", "job_tick", "evolve_drain", "evolve_cutover",
    "lock_contend",
)


def lint_module(mod: ModuleInfo) -> list[Diagnostic]:
    """Run every rule over one parsed module; waived findings suppressed."""
    out: list[Diagnostic] = []
    for rule_id, sev, rule in RULES:
        for line, message, hint in rule(mod):
            if mod.waiver(line, rule_id):
                continue
            out.append(Diagnostic(rule_id, sev, f"{mod.relpath}:{line}",
                                  message, hint))
    return out


def lint_source(source: str, relpath: str) -> list[Diagnostic]:
    """Lint one file's text."""
    return lint_module(_parse(source, relpath))


def _site_literals(tree: ast.AST) -> set[str]:
    # sites reach fault_point either directly or through a module's guarded
    # gateway (storage.py's _guarded/_guarded_v, spill.py's _write_run),
    # which takes the site as its first argument
    out = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) \
                and _call_name(n) in ("fault_point", "_guarded", "_guarded_v",
                                      "_write_run", "_encode_and_write") \
                and n.args:
            a = n.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.add(a.value)
    return out


def lint_paths(paths: list[str], root: Optional[str] = None) -> list[Diagnostic]:
    """Lint every .py file under ``paths`` (files or directories).

    When the sweep includes the faults package itself (i.e. a whole-package
    run), additionally checks that every declared fault site is wired at
    least once somewhere in the sweep (LR106). Modules under the audited
    operator/window/connector dirs additionally run the replay-soundness
    auditor (state_audit, LR201-LR204) as one whole-program pass over the
    sweep, so ``python -m arroyo_tpu lint`` is the single entry point."""
    root = os.path.abspath(root or os.getcwd())
    files: list[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    diags: list[Diagnostic] = []
    wired_sites: set[str] = set()
    saw_faults_pkg = False
    audited: list[ModuleInfo] = []
    parsed: list[ModuleInfo] = []
    for f in files:
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        with open(f) as fh:
            src = fh.read()
        try:
            mod = _parse(src, rel)
        except SyntaxError as e:
            diags.append(Diagnostic("LR000", Severity.ERROR, f"{rel}:{e.lineno or 0}",
                                    f"file does not parse: {e.msg}"))
            continue
        parsed.append(mod)
        diags.extend(lint_module(mod))
        wired_sites |= _site_literals(mod.tree)
        if mod.in_dirs("operators", "windows", "connectors"):
            audited.append(mod)
        if rel.endswith("faults/__init__.py"):
            saw_faults_pkg = True
    if audited:
        from .state_audit import audit_modules

        diags.extend(audit_modules(audited)[0])
    if parsed:
        # trace-safety audit (LR3xx): a whole-program pass over the sweep —
        # it self-selects its scope (jit roots + eval_jnp twins), so running
        # it over every parsed module keeps `lint` the single entry point
        from .trace_audit import audit_trace_modules

        diags.extend(audit_trace_modules(parsed))
        # concurrency audit (LR4xx): whole-program over the sweep — classes
        # resolve across every parsed module, findings self-scope to the
        # threaded engine/state/controller layers
        from .concurrency_audit import audit_concurrency_modules

        diags.extend(audit_concurrency_modules(parsed))
    if saw_faults_pkg:
        for site in _DECLARED_FAULT_SITES:
            if site not in wired_sites:
                diags.append(Diagnostic(
                    "LR106", Severity.ERROR, "arroyo_tpu/faults/__init__.py:1",
                    f"declared fault site {site!r} has no fault_point call "
                    "site anywhere in the package",
                    "wire the site or remove it from faults.SITES"))
    return finish(diags)
