"""Concurrency auditor: whole-program lock/thread analysis (LR4xx).

The control plane holds five threaded subsystems — fleet admission, the
evolve state machine, the background capacity probe, the node daemon, and
the buffered data plane — whose safety rested on convention (the reference
runtime gets these guarantees from Rust ownership). This pass makes the
convention checkable, in the spirit of RacerD's lock-region reasoning,
over every module under ``engine/``, ``state/`` and ``controller/``.

Per class the auditor builds two models:

**Thread-role model** — which methods run on which thread. Roles are
seeded from ``threading.Thread(target=self._m, name="...")`` call sites
(the thread's ``name=`` constant, else the target method name; nested
``def`` targets become pseudo-methods of the class since they close over
``self``) and from the annotation grammar ``# thread: <role>`` on a
``def`` line for dynamically-dispatched entry points (e.g. HTTP handler
routes). Public methods — and private methods no same-class code calls —
additionally carry the implicit ``caller`` role (they are entered from
outside the class, on whatever thread the caller runs). Roles propagate
through same-class ``self.*()`` calls. ``__init__`` carries no role: it
happens-before every thread the object starts.

**Lock-attribution map** — which ``self.*`` attributes are read/mutated
while which locks are held. Lock attributes are mined from
``threading.Lock/RLock/Condition`` (and ``obs.lockorder.make_lock``)
assignments; ``Condition(self._lock)`` aliases to its underlying lock.
``with self.<lock>:`` regions are tracked through a statement walk, and a
private helper only ever called with a lock held inherits that lock as
its entry context (fixpoint over same-class call sites), so attribution
survives the extract-a-helper refactor that blinds intraprocedural
checks.

Rule catalog:

    LR401 (ERROR)  unlocked-shared-attr  attribute written outside
                   ``__init__`` and accessed on >= 2 thread roles with no
                   single lock common to every access (or, in lock-free
                   classes, written on >= 2 roles). Waive per attribute
                   with ``# concurrency: single-writer — why`` on (or
                   above) a write line
    LR402 (ERROR)  lock-order-cycle      cycle in the global
                   acquires-while-holding graph over ``Class.attr`` lock
                   nodes (edges from nested ``with`` regions, same-class
                   helper closures, and cross-class calls through typed
                   attributes); also re-acquiring a non-reentrant lock
                   already held (self-deadlock)
    LR403 (ERROR)  lock-across-blocking  blocking call (sleep / socket /
                   storage / queue / join / os.write) while holding a
                   lock — interprocedural: follows same-class helper
                   calls and lock entry contexts, subsuming LR105, whose
                   id still binds as a waiver alias.
                   ``Condition.wait`` on a condition whose underlying
                   lock is held is exempt (wait releases it)
    LR404 (WARNING) non-atomic-check-act  an ``if``/``while`` test reads
                   a shared attribute under one lock set and a write to
                   the same attribute in the guarded body runs under a
                   disjoint one — the fleet-ledger/queue-position shape.
                   Only fires for attributes the class elsewhere writes
                   under a lock (i.e. treats as shared)

Waivers: LR401/LR404 take the attribute-bound ``# concurrency:
single-writer — why`` grammar; every rule also accepts the repo-lint
``# lint: waive LR4xx — why`` form (LR403 additionally accepts the
legacy ``LR105`` id). A waiver with no justification does not suppress.

The static LR402 graph is cross-checked at runtime: ``obs/lockorder.py``
wraps production locks (opt-in) and records acquires-while-holding edges
while the test suite runs; tests/test_concurrency_audit.py asserts every
observed edge appears in the static graph.

Known approximations (documented, deliberate): nested functions that are
not thread targets are skipped (they run inline; their lock regions are
rare in this codebase); cross-class calls contribute lock-order edges but
not blocking reach; role propagation stays within one class.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .diagnostics import Diagnostic, Severity, finish
from .repo_lint import (ModuleInfo, _call_name, _dotted, _mentions_lock,
                        _parse, _receiver_name, _walk_skipping_nested_defs)

RULES: tuple[str, ...] = ("LR401", "LR402", "LR403", "LR404")

# modules audited (the threaded control/data plane); every parsed module
# still contributes classes so cross-class lock references resolve
_AUDIT_DIRS = ("engine", "state", "controller")

_CONC_WAIVE_RE = re.compile(
    r"concurrency:\s*single-writer\s*(?:[-—:,]\s*)?(.*)", re.I)
_ROLE_RE = re.compile(r"#\s*thread:\s*([A-Za-z0-9_.\-]+)")

# in-place mutators on an attribute receiver (self.x.append(...) mutates x)
_MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "push",
    "extend", "extendleft", "update", "insert", "remove", "discard",
    "clear", "setdefault", "sort", "reverse", "rotate",
})

# blocking sinks (superset of the retired intraprocedural LR105 list:
# os.write/os.read are added because the data plane writes socket fds
# through them, and Event/Condition waits through the sync-attr model)
_BLOCKING = frozenset({
    "sleep", "sendall", "recv", "accept", "connect", "urlopen",
    "check_output", "put_bytes", "get_bytes", "read_bytes", "write_bytes",
})


# --------------------------------------------------------------- data model


@dataclass
class LockAttr:
    attr: str
    kind: str  # "lock" | "rlock" | "cond"
    alias_of: Optional[str]  # Condition(self._lock) -> "_lock"
    line: int


@dataclass
class Access:
    attr: str
    kind: str  # "store" | "mut" | "load"
    line: int
    locks: frozenset  # lock keys held at the site (mined, pre-entry-ctx)


@dataclass
class SelfCall:
    callee: str
    line: int
    locks: frozenset
    caller: str


@dataclass
class Blocking:
    name: str
    line: int
    locks: frozenset
    cond_key: Optional[str]  # set for Condition.wait: its underlying lock


@dataclass
class Acquire:
    key: str
    line: int
    held: frozenset


@dataclass
class ForeignCall:
    attr: str  # self.<attr>.<method>() receiver attribute
    method: str
    line: int
    locks: frozenset


@dataclass
class CheckAct:
    attr: str
    check_line: int
    check_locks: frozenset
    act_line: int
    act_locks: frozenset


@dataclass
class MethodModel:
    name: str
    fn: ast.AST
    accesses: list = field(default_factory=list)
    self_calls: list = field(default_factory=list)
    blocking: list = field(default_factory=list)
    acquires: list = field(default_factory=list)
    foreign_calls: list = field(default_factory=list)
    checkacts: list = field(default_factory=list)
    ann_role: Optional[str] = None  # from `# thread: <role>`
    pseudo: bool = False  # nested-def thread target
    entry_locks: frozenset = frozenset()
    roles: set = field(default_factory=set)


@dataclass
class ClassModel:
    name: str
    mod: ModuleInfo
    node: ast.ClassDef
    locks: dict = field(default_factory=dict)  # attr -> LockAttr
    events: set = field(default_factory=set)  # threading.Event attrs
    attr_types: dict = field(default_factory=dict)  # attr -> class name
    methods: dict = field(default_factory=dict)  # name -> MethodModel
    thread_seeds: dict = field(default_factory=dict)  # method -> role

    def sync_attrs(self) -> set:
        return set(self.locks) | self.events

    def lock_key(self, attr: str) -> str:
        """Canonical graph node for a lock attribute of this class,
        resolved through Condition aliasing."""
        la = self.locks.get(attr)
        seen = set()
        while la is not None and la.alias_of and la.alias_of not in seen:
            seen.add(la.alias_of)
            attr = la.alias_of
            la = self.locks.get(attr)
        return f"{self.name}.{attr}"

    def lock_kind(self, attr: str) -> str:
        la = self.locks.get(attr)
        if la is not None and la.alias_of and la.alias_of in self.locks:
            la = self.locks[la.alias_of]
        return la.kind if la is not None else "lock"


# ------------------------------------------------------------- class mining


def _root_self_attr(expr: ast.expr) -> Optional[str]:
    """The X in self.X / self.X[...] / self.X.y (store targets)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    # peel trailing attribute chain down to the one hanging off `self`
    chain = expr
    while isinstance(chain, ast.Attribute):
        if isinstance(chain.value, ast.Name) and chain.value.id == "self":
            return chain.attr
        chain = chain.value
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _self_attr_of(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _lock_ctor(mod: ModuleInfo, call: ast.Call):
    """(kind, alias_attr) when `call` constructs a lock/condition, else
    None. Recognizes threading primitives and obs.lockorder.make_lock."""
    dn = mod.canonical(_dotted(call.func))
    base = dn.rsplit(".", 1)[-1]
    kind = alias = None
    if dn.startswith("threading.") and base in (
            "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"):
        kind = {"RLock": "rlock", "Condition": "cond"}.get(base, "lock")
    elif base == "make_lock":
        kv = _kwarg(call, "kind")
        kind = kv.value if isinstance(kv, ast.Constant) and \
            isinstance(kv.value, str) else "lock"
    if kind is None:
        return None
    lock_arg = _kwarg(call, "lock")
    if lock_arg is None and kind == "cond" and call.args:
        lock_arg = call.args[0]
    if lock_arg is not None:
        alias = _self_attr_of(lock_arg)
    return kind, alias


def _ann_name(ann: Optional[ast.expr]) -> Optional[str]:
    """Class name out of a parameter annotation: handles ``C``, ``m.C``,
    ``"C"`` forward refs, ``Optional[C]`` and ``C | None``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        t = ann.value.strip().strip("\"'").rsplit(".", 1)[-1]
        return t if t and t != "None" else None
    if isinstance(ann, ast.Subscript):
        return _ann_name(ann.slice)
    if isinstance(ann, ast.BinOp):
        return _ann_name(ann.left) or _ann_name(ann.right)
    t = _dotted(ann).rsplit(".", 1)[-1]
    return t if t and t != "None" else None


def _thread_name_const(call: ast.Call) -> Optional[str]:
    nv = _kwarg(call, "name")
    if isinstance(nv, ast.Constant) and isinstance(nv.value, str):
        return nv.value
    if isinstance(nv, ast.JoinedStr):
        for v in nv.values:  # f"ckpt-gc-{job}" -> "ckpt-gc"
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                piece = v.value.strip().rstrip("-_. ")
                if piece:
                    return piece
    return None


def _def_role(mod: ModuleInfo, fn: ast.AST) -> Optional[str]:
    for ln in (fn.lineno, fn.lineno - 1):
        m = _ROLE_RE.search(mod.comments.get(ln, ""))
        if m:
            return m.group(1)
    return None


class Sweep:
    """Whole-program view: every class in the sweep, keyed by name, plus
    the subset of modules the LR4xx rules actually audit."""

    def __init__(self) -> None:
        self.classes: dict[str, ClassModel] = {}
        self.audited: list[ModuleInfo] = []
        self._acq_memo: dict[tuple[str, str], frozenset] = {}

    def add_module(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = _mine_class(mod, node)
        if mod.in_dirs(*_AUDIT_DIRS):
            self.audited.append(mod)

    # transitive lock keys acquired by Class.method and its same-class
    # callees (for cross-class lock-order edges)
    def acquired_closure(self, cls_name: str, method: str) -> frozenset:
        key = (cls_name, method)
        if key in self._acq_memo:
            return self._acq_memo[key]
        self._acq_memo[key] = frozenset()  # cycle guard
        cm = self.classes.get(cls_name)
        if cm is None or method not in cm.methods:
            return frozenset()
        out = set()
        stack, seen = [method], set()
        while stack:
            m = stack.pop()
            if m in seen or m not in cm.methods:
                continue
            seen.add(m)
            mm = cm.methods[m]
            out.update(a.key for a in mm.acquires if not a.key.startswith("<"))
            stack.extend(c.callee for c in mm.self_calls)
        self._acq_memo[key] = frozenset(out)
        return self._acq_memo[key]


def _mine_class(mod: ModuleInfo, cnode: ast.ClassDef) -> ClassModel:
    cm = ClassModel(cnode.name, mod, cnode)
    defs = [n for n in cnode.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    # ---- pass 1: sync attrs, attr types, thread seeds --------------------
    for fn in defs:
        ann: dict[str, str] = {}
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t:
                ann[a.arg] = t
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                attr = _self_attr_of(n.targets[0])
                if attr is None:
                    continue
                if isinstance(n.value, ast.Call):
                    lc = _lock_ctor(mod, n.value)
                    if lc is not None:
                        kind, alias = lc
                        cm.locks[attr] = LockAttr(attr, kind, alias, n.lineno)
                        continue
                    dn = mod.canonical(_dotted(n.value.func))
                    if dn in ("threading.Event",):
                        cm.events.add(attr)
                        continue
                    ctor = dn.rsplit(".", 1)[-1]
                    if ctor[:1].isupper():
                        cm.attr_types.setdefault(attr, ctor)
                elif isinstance(n.value, ast.Name) and n.value.id in ann:
                    cm.attr_types.setdefault(attr, ann[n.value.id])
            if isinstance(n, ast.Call):
                dn = mod.canonical(_dotted(n.func))
                if dn.rsplit(".", 1)[-1] != "Thread" or \
                        not (dn.startswith("threading.") or dn == "Thread"):
                    continue
                target = _kwarg(n, "target")
                role = _thread_name_const(n) or ""
                tattr = _self_attr_of(target) if target is not None else None
                if tattr is not None:
                    cm.thread_seeds[tattr] = role or tattr
                elif isinstance(target, ast.Name):
                    # nested `def _probe(): ...` closing over self: register
                    # as a pseudo-method carrying the thread role
                    for inner in ast.walk(fn):
                        if isinstance(inner, ast.FunctionDef) and \
                                inner.name == target.id and inner is not fn:
                            pname = f"{fn.name}.{inner.name}"
                            mm = MethodModel(pname, inner, pseudo=True)
                            cm.methods[pname] = mm
                            cm.thread_seeds[pname] = role or inner.name
                            break

    # ---- pass 2: mine every method body ----------------------------------
    for fn in defs:
        mm = MethodModel(fn.name, fn)
        mm.ann_role = _def_role(mod, fn)
        cm.methods[fn.name] = mm
        _mine_method(mod, cm, mm)
    for mm in cm.methods.values():
        if mm.pseudo:
            _mine_method(mod, cm, mm)
    return cm


def _mine_method(mod: ModuleInfo, cm: ClassModel, mm: MethodModel) -> None:
    sync = cm.sync_attrs()

    def lock_key_of(expr: ast.expr) -> Optional[str]:
        attr = _self_attr_of(expr)
        if attr is not None:
            if attr in cm.locks:
                return cm.lock_key(attr)
            if "lock" in attr.lower() or "cond" in attr.lower():
                return f"{cm.name}.{attr}"  # untracked but lock-named
            return None
        if isinstance(expr, ast.Attribute):  # self.obj._lock / foreign
            owner = _self_attr_of(expr.value)
            if owner is not None:
                tname = cm.attr_types.get(owner)
                leaf = expr.attr
                if "lock" in leaf.lower() or "cond" in leaf.lower():
                    return f"{tname or '?'}.{leaf}"
        if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
            return f"<local:{expr.id}>"  # held for LR403, not a graph node
        return None

    def cond_key_of(attr: str) -> Optional[str]:
        la = cm.locks.get(attr)
        if la is not None and la.kind == "cond":
            return cm.lock_key(attr)
        return None

    def record_store(target: ast.expr, held: frozenset, checks) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                record_store(el, held, checks)
            return
        attr = _root_self_attr(target)
        if attr is None or attr in sync:
            return
        kind = "store" if _self_attr_of(target) is not None else "mut"
        mm.accesses.append(Access(attr, kind, target.lineno, held))
        _match_check(attr, target.lineno, held, checks)

    def _match_check(attr: str, line: int, held: frozenset, checks) -> None:
        for attrs, locks, cline in reversed(checks):
            if attr in attrs:
                mm.checkacts.append(CheckAct(attr, cline, locks, line, held))
                return

    def handle_call(n: ast.Call, held: frozenset) -> None:
        name = _call_name(n)
        recv = _receiver_name(n)
        dn = mod.canonical(_dotted(n.func))
        fv = getattr(n.func, "value", None)
        # same-class call: self.m(...)
        callee_attr = _self_attr_of(n.func) if \
            isinstance(n.func, ast.Attribute) else None
        if callee_attr is not None and callee_attr not in sync:
            mm.self_calls.append(SelfCall(callee_attr, n.lineno, held,
                                          mm.name))
        # explicit acquire on a lock-valued expression
        if name == "acquire" and fv is not None:
            k = lock_key_of(fv)
            if k is not None:
                mm.acquires.append(Acquire(k, n.lineno, held))
                return
        # in-place mutation through a method (self.x.append(...))
        if name in _MUTATORS and fv is not None:
            attr = _root_self_attr(fv)
            if attr is not None and attr not in sync:
                mm.accesses.append(Access(attr, "mut", n.lineno, held))
        # cross-class call through a typed attribute (self.db.record(...))
        if fv is not None and isinstance(fv, ast.Attribute):
            owner = _self_attr_of(fv)
            if owner is not None and owner in cm.attr_types:
                mm.foreign_calls.append(ForeignCall(
                    owner, name, n.lineno, held))
        # blocking classification ----------------------------------------
        blocking = name in _BLOCKING or dn in ("os.write", "os.read")
        cond_key = None
        if name == "join" and recv not in ("path", "os") and not blocking:
            blocking = not isinstance(fv, ast.Constant)
        if name in ("get", "put") and (
                "queue" in recv.lower() or "inbox" in recv.lower()):
            # dict-style .get(key[, default]) carries positional args; a
            # blocking queue get() has none. put(item) always has one, so
            # only the block=False kwarg exempts it.
            blocking = not any(
                k.arg == "block" and isinstance(k.value, ast.Constant)
                and k.value.value is False for k in n.keywords)
            if name == "get" and n.args:
                blocking = False
        if name in ("wait", "wait_for") and fv is not None:
            wattr = _self_attr_of(fv)
            if wattr is not None:
                if wattr in cm.locks and cm.locks[wattr].kind == "cond":
                    blocking, cond_key = True, cond_key_of(wattr)
                elif wattr in cm.events:
                    blocking = True
        if blocking:
            mm.blocking.append(Blocking(name, n.lineno, held, cond_key))

    def scan_value(expr: Optional[ast.expr], held: frozenset, checks,
                   is_check: bool = False) -> set:
        """Record loads/calls inside one expression; returns the self
        attrs loaded (used to seed LR404 check frames)."""
        loaded: set = set()
        if expr is None:
            return loaded
        skip: set = set()
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                handle_call(n, held)
                if isinstance(n.func, ast.Attribute):
                    skip.add(id(n.func))
            elif isinstance(n, ast.Attribute) and id(n) not in skip:
                attr = _self_attr_of(n)
                if attr is not None and attr not in sync and \
                        isinstance(n.ctx, ast.Load):
                    mm.accesses.append(Access(attr, "load", n.lineno, held))
                    loaded.add(attr)
            stack.extend(ast.iter_child_nodes(n))
        return loaded if is_check else loaded

    def walk_stmts(stmts, held: frozenset, checks) -> None:
        for st in stmts:
            walk_stmt(st, held, checks)

    def walk_stmt(st: ast.stmt, held: frozenset, checks) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs run later / are mined as pseudo-methods
        if isinstance(st, ast.With):
            new_held = set(held)
            for item in st.items:
                k = lock_key_of(item.context_expr)
                if k is None and _mentions_lock(item.context_expr):
                    k = f"<anon:{item.context_expr.lineno}>"
                scan_value(item.context_expr, held, checks)
                if k is None:
                    continue
                if k in held:
                    # re-entry: legal for rlocks, self-deadlock otherwise
                    mm.acquires.append(Acquire(k, st.lineno, frozenset(held)))
                else:
                    mm.acquires.append(Acquire(k, st.lineno, frozenset(held)))
                    new_held.add(k)
            walk_stmts(st.body, frozenset(new_held), checks)
            return
        if isinstance(st, (ast.If, ast.While)):
            guard = scan_value(st.test, held, checks, is_check=True)
            frame = (guard, held, st.lineno) if guard else None
            sub = checks + [frame] if frame else checks
            walk_stmts(st.body, held, sub)
            walk_stmts(st.orelse, held, sub)
            return
        if isinstance(st, ast.For):
            scan_value(st.iter, held, checks)
            walk_stmts(st.body, held, checks)
            walk_stmts(st.orelse, held, checks)
            return
        if isinstance(st, ast.Try):
            walk_stmts(st.body, held, checks)
            for h in st.handlers:
                walk_stmts(h.body, held, checks)
            walk_stmts(st.orelse, held, checks)
            walk_stmts(st.finalbody, held, checks)
            return
        if isinstance(st, ast.Assign):
            scan_value(st.value, held, checks)
            for t in st.targets:
                record_store(t, held, checks)
                if isinstance(t, ast.Subscript):
                    scan_value(t.slice, held, checks)
            return
        if isinstance(st, ast.AugAssign):
            scan_value(st.value, held, checks)
            attr = _root_self_attr(st.target)
            if attr is not None and attr not in sync:
                mm.accesses.append(Access(attr, "mut", st.lineno, held))
                _match_check(attr, st.lineno, held, checks)
            return
        if isinstance(st, ast.AnnAssign):
            scan_value(st.value, held, checks)
            if st.value is not None:
                record_store(st.target, held, checks)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                attr = _root_self_attr(t)
                if attr is not None and attr not in sync:
                    mm.accesses.append(Access(attr, "mut", st.lineno, held))
            return
        # generic statement: scan its expressions, recurse into any bodies
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                scan_value(child, held, checks)
            elif isinstance(child, ast.stmt):
                walk_stmt(child, held, checks)

    body = mm.fn.body if isinstance(
        mm.fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else []
    walk_stmts(body, frozenset(), [])


# -------------------------------------------------- roles + entry contexts


def _is_public(name: str) -> bool:
    if name == "__init__":
        return False
    if name.startswith("__") and name.endswith("__"):
        return True
    return not name.startswith("_")


def _assign_roles(cm: ClassModel) -> None:
    callers: dict[str, int] = {}
    for mm in cm.methods.values():
        if mm.name == "__init__":
            continue  # init happens-before every thread start
        for c in mm.self_calls:
            callers[c.callee] = callers.get(c.callee, 0) + 1
    # helpers only reachable from __init__ run pre-thread: no role at all
    init_reach: set = set()
    if "__init__" in cm.methods:
        stack = [c.callee for c in cm.methods["__init__"].self_calls]
        while stack:
            m = stack.pop()
            if m in init_reach or m not in cm.methods:
                continue
            init_reach.add(m)
            stack.extend(c.callee for c in cm.methods[m].self_calls)
    for name, mm in cm.methods.items():
        role = cm.thread_seeds.get(name)
        if role:
            mm.roles.add(role)
        if mm.ann_role:
            mm.roles.add(mm.ann_role)
        if mm.pseudo or role or mm.ann_role or name == "__init__":
            continue
        if _is_public(name) or (callers.get(name, 0) == 0
                                and name not in init_reach):
            mm.roles.add("caller")
    # propagate along same-class calls (init excluded as a source)
    for _ in range(len(cm.methods) + 1):
        changed = False
        for mm in cm.methods.values():
            if mm.name == "__init__" or not mm.roles:
                continue
            for c in mm.self_calls:
                cal = cm.methods.get(c.callee)
                if cal is not None and not mm.roles <= cal.roles:
                    cal.roles |= mm.roles
                    changed = True
        if not changed:
            break


def _entry_fixpoint(cm: ClassModel) -> None:
    """Private helpers only ever called with a lock held inherit it as
    their entry context (intersection over same-class call sites)."""
    sites: dict[str, list] = {}
    for mm in cm.methods.values():
        for c in mm.self_calls:
            sites.setdefault(c.callee, []).append((mm.name, c.locks))
    for _ in range(10):
        changed = False
        for name, mm in cm.methods.items():
            if _is_public(name) or mm.pseudo or mm.ann_role or \
                    cm.thread_seeds.get(name) or name == "__init__":
                continue
            ss = sites.get(name)
            if not ss:
                continue
            new = None
            for caller, locks in ss:
                eff = locks | cm.methods[caller].entry_locks \
                    if caller in cm.methods else locks
                new = eff if new is None else (new & eff)
            new = new or frozenset()
            if new != mm.entry_locks:
                mm.entry_locks = frozenset(new)
                changed = True
        if not changed:
            break


# ----------------------------------------------------------------- waivers


def _attr_waiver(cm: ClassModel, attr: str) -> bool:
    """`# concurrency: single-writer — why` on/above any write of attr
    (or its __init__ assignment) suppresses LR401/LR404 for that attr."""
    lines = set()
    for mm in cm.methods.values():
        for ev in mm.accesses:
            if ev.attr == attr and ev.kind in ("store", "mut"):
                lines.add(ev.line)
    for n in ast.walk(cm.node):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in tgts:
                if _root_self_attr(t) == attr:
                    lines.add(n.lineno)
    for line in lines:
        for ln in (line, line - 1):
            m = _CONC_WAIVE_RE.search(cm.mod.comments.get(ln, ""))
            if m and m.group(1).strip():
                return True
    return False


def _line_waived(mod: ModuleInfo, line: int, *rule_ids: str) -> bool:
    return any(mod.waiver(line, rid) for rid in rule_ids)


# ------------------------------------------------------------------- rules


def _fmt_locks(locks: Iterable[str]) -> str:
    ls = sorted(l for l in locks if not l.startswith("<"))
    return "/".join(ls) if ls else "no lock"


def _eff(mm: MethodModel, locks: frozenset) -> frozenset:
    return locks | mm.entry_locks


def _rule_lr401(cm: ClassModel) -> Iterable[Diagnostic]:
    per_attr: dict[str, list] = {}
    for mm in cm.methods.values():
        if mm.name == "__init__" or not mm.roles:
            continue
        for ev in mm.accesses:
            per_attr.setdefault(ev.attr, []).append(
                (mm.roles, ev.kind, _eff(mm, ev.locks), ev.line))
    for attr in sorted(per_attr):
        evs = per_attr[attr]
        writes = [e for e in evs if e[1] in ("store", "mut")]
        if not writes:
            continue
        roles_all = set()
        for roles, _k, _l, _ln in evs:
            roles_all |= roles
        if len(roles_all) < 2:
            continue
        if cm.locks:
            common = None
            for _r, _k, locks, _ln in evs:
                common = locks if common is None else (common & locks)
            if common:
                continue
        else:
            w_roles = set()
            for roles, _k, _l, _ln in writes:
                w_roles |= roles
            if len(w_roles) < 2:
                continue
        site_line = min(ln for _r, _k, _l, ln in writes)
        if _attr_waiver(cm, attr) or \
                _line_waived(cm.mod, site_line, "LR401"):
            continue
        unlocked = sorted({ln for _r, _k, locks, ln in evs if not locks})
        yield Diagnostic(
            "LR401", Severity.ERROR, f"{cm.mod.relpath}:{site_line}",
            f"{cm.name}.{attr} is written outside __init__ and accessed on "
            f"thread roles {sorted(roles_all)} with no common lock "
            f"(unlocked access lines: {unlocked[:6]})",
            "guard every access with one lock, or waive the attribute with "
            "`# concurrency: single-writer — why` if one role provably owns "
            "all writes")


def _sccs(edges: dict) -> list:
    """Strongly connected components (iterative Tarjan) over the edge
    dict {(src, dst): site}; returns node lists, only SCCs with a cycle."""
    adj: dict[str, list] = {}
    nodes: list = []
    for (s, d) in edges:
        adj.setdefault(s, []).append(d)
        adj.setdefault(d, [])
    for n in sorted(adj):
        nodes.append(n)
        adj[n].sort()
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            for i in range(pi, len(adj[node])):
                nxt = adj[node][i]
                if nxt not in index:
                    work[-1] = (node, i + 1)
                    work.append((nxt, 0))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (node, node) in edges:
                    out.append(sorted(comp))
    return out


def static_lock_graph(sweep: Sweep) -> dict:
    """The acquires-while-holding graph: {(held, acquired): "path:line"}
    over canonical ``Class.attr`` lock nodes. This is what the runtime
    witness (obs/lockorder.py) cross-checks observed edges against."""
    edges: dict = {}

    def add(src: str, dst: str, mod: ModuleInfo, line: int) -> None:
        if src.startswith("<") or dst.startswith("<"):
            return
        site = f"{mod.relpath}:{line}"
        cur = edges.get((src, dst))
        if cur is None or site < cur:
            edges[(src, dst)] = site

    def reentrant(key: str) -> bool:
        cls, _, attr = key.partition(".")
        owner = sweep.classes.get(cls)
        return owner is not None and owner.lock_kind(attr) == "rlock"

    for cname in sorted(sweep.classes):
        cm = sweep.classes[cname]
        for mname in sorted(cm.methods):
            mm = cm.methods[mname]
            for a in mm.acquires:
                for h in _eff(mm, a.held):
                    # h == key: re-acquiring a held lock — legal only for
                    # rlocks; the self-edge makes it an SCC (self-deadlock)
                    if h != a.key or not reentrant(a.key):
                        add(h, a.key, cm.mod, a.line)
            for c in mm.self_calls:
                held = _eff(mm, c.locks)
                if not held:
                    continue
                for k in sweep.acquired_closure(cm.name, c.callee):
                    for h in held:
                        if h != k or not reentrant(k):
                            add(h, k, cm.mod, c.line)
            for f in mm.foreign_calls:
                held = _eff(mm, f.locks)
                if not held:
                    continue
                tname = cm.attr_types.get(f.attr)
                if tname is None or tname not in sweep.classes:
                    continue
                for k in sweep.acquired_closure(tname, f.method):
                    for h in held:
                        if h != k or not reentrant(k):
                            add(h, k, cm.mod, f.line)
    return edges


def _rule_lr402(sweep: Sweep) -> Iterable[Diagnostic]:
    # audit-scope filter: only report cycles whose first site lies in an
    # audited module (the graph itself spans the whole sweep)
    audited_paths = {m.relpath for m in sweep.audited}
    mods_by_path = {m.relpath: m for m in sweep.audited}
    edges = static_lock_graph(sweep)
    for comp in _sccs(edges):
        comp_edges = sorted(
            (site, s, d) for (s, d), site in edges.items()
            if s in comp and d in comp)
        if not comp_edges:
            continue
        site, s0, d0 = comp_edges[0]
        path, _, line_s = site.rpartition(":")
        if path not in audited_paths:
            continue
        mod = mods_by_path[path]
        if any(_line_waived(mods_by_path.get(es.rpartition(":")[0]),
                            int(es.rpartition(":")[2]), "LR402")
               for es, _s, _d in comp_edges
               if es.rpartition(":")[0] in mods_by_path):
            continue
        if len(comp) == 1:
            msg = (f"non-reentrant lock {comp[0]} re-acquired while already "
                   "held (self-deadlock)")
        else:
            msg = ("lock-ordering cycle (deadlock potential): " +
                   " -> ".join(comp + [comp[0]]) + "; first edge "
                   f"{s0} -> {d0}")
        yield Diagnostic(
            "LR402", Severity.ERROR, site, msg,
            "impose one global acquire order (or collapse to a single "
            "lock); waive an edge site with `# lint: waive LR402 — why` "
            "only for a provably unreachable interleaving")


def _rule_lr403(sweep: Sweep) -> Iterable[Diagnostic]:
    emitted: set = set()
    for mod in sweep.audited:
        classes = [sweep.classes[n.name] for n in mod.tree.body
                   if isinstance(n, ast.ClassDef)
                   and n.name in sweep.classes
                   and sweep.classes[n.name].mod is mod]
        # direct + entry-context findings
        for cm in classes:
            for mname in sorted(cm.methods):
                mm = cm.methods[mname]
                for b in mm.blocking:
                    held = _eff(mm, b.locks)
                    if not held:
                        continue
                    if b.cond_key is not None and b.cond_key in held:
                        continue  # Condition.wait releases its lock
                    if _line_waived(mod, b.line, "LR403", "LR105"):
                        emitted.add((mod.relpath, b.line))
                        continue
                    emitted.add((mod.relpath, b.line))
                    yield Diagnostic(
                        "LR403", Severity.ERROR,
                        f"{mod.relpath}:{b.line}",
                        f"blocking call {b.name}() while holding "
                        f"{_fmt_locks(held)}: every contending thread "
                        "stalls for the full call",
                        "move the blocking call outside the lock (copy "
                        "state under the lock, act after release)")
            # helper reach: blocking sink inside a callee whose own entry
            # context did not prove the lock (the old LR105 blind spot)
            for mname in sorted(cm.methods):
                mm = cm.methods[mname]
                for c in mm.self_calls:
                    held = _eff(mm, c.locks)
                    if not held:
                        continue
                    for b in _reach_blocking(cm, c.callee):
                        if (mod.relpath, b.line) in emitted:
                            continue
                        eff = held | b.locks
                        if b.cond_key is not None and b.cond_key in eff:
                            continue
                        if _line_waived(mod, c.line, "LR403", "LR105") or \
                                _line_waived(mod, b.line, "LR403", "LR105"):
                            continue
                        emitted.add((mod.relpath, b.line))
                        yield Diagnostic(
                            "LR403", Severity.ERROR,
                            f"{mod.relpath}:{b.line}",
                            f"blocking call {b.name}() reached via "
                            f"self.{c.callee}() from {cm.name}.{mm.name} "
                            f"while holding {_fmt_locks(held)}",
                            "move the blocking call (or the helper call) "
                            "outside the lock")
        # module-level functions: the legacy intraprocedural region scan
        yield from _module_level_lr403(mod, emitted)


def _reach_blocking(cm: ClassModel, root: str) -> list:
    out, stack, seen = [], [root], set()
    while stack:
        m = stack.pop()
        if m in seen or m not in cm.methods:
            continue
        seen.add(m)
        mm = cm.methods[m]
        out.extend(mm.blocking)
        stack.extend(c.callee for c in mm.self_calls)
    return out


def _module_level_lr403(mod: ModuleInfo, emitted: set) -> Iterable[Diagnostic]:
    in_class: set = set()
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.ClassDef):
            for sub in ast.walk(n):
                in_class.add(id(sub))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.With) or id(node) in in_class:
            continue
        if not any(_mentions_lock(i.context_expr) for i in node.items):
            continue
        for n in _walk_skipping_nested_defs(node):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            recv = _receiver_name(n)
            blocking = name in _BLOCKING or \
                mod.canonical(_dotted(n.func)) in ("os.write", "os.read")
            if name == "join" and recv not in ("path", "os") and not blocking:
                blocking = not isinstance(
                    getattr(n.func, "value", None), ast.Constant)
            if name in ("get", "put") and (
                    "queue" in recv.lower() or "inbox" in recv.lower()):
                blocking = not any(
                    k.arg == "block" and isinstance(k.value, ast.Constant)
                    and k.value.value is False for k in n.keywords)
                if name == "get" and n.args:
                    blocking = False  # dict-style .get(key[, default])
            if not blocking or (mod.relpath, n.lineno) in emitted:
                continue
            emitted.add((mod.relpath, n.lineno))
            if _line_waived(mod, n.lineno, "LR403", "LR105"):
                continue
            yield Diagnostic(
                "LR403", Severity.ERROR, f"{mod.relpath}:{n.lineno}",
                f"blocking call {name}() while holding a lock (with-lock "
                f"region at line {node.lineno}): all contending threads "
                "stall for the full call",
                "move the blocking call outside the lock (copy state under "
                "the lock, act on it after release)")


def _rule_lr404(cm: ClassModel) -> Iterable[Diagnostic]:
    if not cm.locks:
        return
    locked_writes: dict[str, set] = {}
    for mm in cm.methods.values():
        if mm.name == "__init__":
            continue
        for ev in mm.accesses:
            if ev.kind in ("store", "mut"):
                eff = _eff(mm, ev.locks)
                if eff:
                    locked_writes.setdefault(ev.attr, set()).update(eff)
    for mname in sorted(cm.methods):
        mm = cm.methods[mname]
        if mm.name == "__init__":
            continue
        for ca in mm.checkacts:
            check = _eff(mm, ca.check_locks)
            act = _eff(mm, ca.act_locks)
            if check & act:
                continue
            if not locked_writes.get(ca.attr):
                continue  # never lock-attributed: LR401's (or nobody's) job
            if _attr_waiver(cm, ca.attr) or \
                    _line_waived(cm.mod, ca.act_line, "LR404"):
                continue
            yield Diagnostic(
                "LR404", Severity.WARNING,
                f"{cm.mod.relpath}:{ca.act_line}",
                f"non-atomic check-then-act on {cm.name}.{ca.attr}: guard "
                f"read at line {ca.check_line} under "
                f"{_fmt_locks(check)}, dependent write under "
                f"{_fmt_locks(act)} — the checked condition can be "
                "invalidated between the two",
                "hold one lock across both the check and the write, or "
                "waive with `# concurrency: single-writer — why`")


# ------------------------------------------------------------ entry points


def build_sweep(mods: Iterable[ModuleInfo]) -> Sweep:
    sweep = Sweep()
    for mod in mods:
        sweep.add_module(mod)
    for cm in sweep.classes.values():
        _assign_roles(cm)
        _entry_fixpoint(cm)
    return sweep


def audit_concurrency_modules(mods: list) -> list:
    """LR4xx over parsed modules: whole-program (classes resolve across
    every module given) but findings only in engine/state/controller."""
    sweep = build_sweep(mods)
    diags: list[Diagnostic] = []
    audited_paths = {m.relpath for m in sweep.audited}
    for cname in sorted(sweep.classes):
        cm = sweep.classes[cname]
        if cm.mod.relpath not in audited_paths:
            continue
        if not cm.locks and not cm.thread_seeds and not any(
                mm.ann_role for mm in cm.methods.values()):
            continue
        diags.extend(_rule_lr401(cm))
        diags.extend(_rule_lr404(cm))
    diags.extend(_rule_lr402(sweep))
    diags.extend(_rule_lr403(sweep))
    return finish(diags)


def audit_concurrency_source(source: str,
                             relpath: str = "engine/fixture.py") -> list:
    """Audit one file's text (fixture entry point for tests)."""
    return audit_concurrency_modules([_parse(source, relpath)])


def static_lock_graph_package(root: Optional[str] = None) -> dict:
    """The static acquires-while-holding graph for the arroyo_tpu package
    ({(held, acquired): site}), for the runtime witness cross-check."""
    pkg = root or os.path.join(os.path.dirname(__file__), "..")
    pkg = os.path.abspath(pkg)
    base = os.path.dirname(pkg)
    mods = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            p = os.path.join(dirpath, f)
            rel = os.path.relpath(p, base).replace(os.sep, "/")
            with open(p) as fh:
                try:
                    mods.append(_parse(fh.read(), rel))
                except SyntaxError:
                    continue
    return static_lock_graph(build_sweep(mods))
