"""Plan-time dataflow analysis: a pass framework over the logical Graph.

Runs automatically at the end of SQL planning (sql/planner.py) and behind
``python -m arroyo_tpu check <pipeline.sql>``. Each pass walks the planned
graph and emits Diagnostics; ERROR findings reject the pipeline before any
state is allocated or a device step compiled — the reference rejects the
same pipelines in its planner/DataFusion fork (the ``--fail`` SQL tests,
e.g. most_active_driver_last_hour_unaligned.sql).

Rule catalog (README "Static analysis" section documents each with examples):

    AR001 edge-schema-consistency   operator configs must only reference
                                    columns their input edges carry
    AR002 unaligned-hop             hop() slide must evenly divide width
    AR003 updating-into-window      retracting streams cannot feed
                                    event-time window operators
    AR004 unbounded-state           non-TTL'd updating state over unbounded
                                    sources grows without bound (warning)
    AR005 retraction-sink-mismatch  updating operator feeding an
                                    append-only-formatted sink (warning)
    AR006 barrier-reachability      every operator must sit downstream of
                                    sources so checkpoint barriers reach it
    AR007 shuffle-key-consistency   shuffle edges must be keyed upstream
                                    with exactly the keys the consumer
                                    groups by
    AR008 table-spec-consistency    each node's declared TableSpecs must be
                                    collision-free (duplicate names would
                                    share one checkpoint file per subtask)
                                    and expiring specs must carry the
                                    operator's configured TTL (a mismatch
                                    silently widens or narrows the state
                                    restore window)
    AR009 segment-compilability     (trace_audit.pass_segment_compile)
                                    dual-path dtype parity of plan-marked-
                                    compilable segments: reject when the
                                    traced program would compute in a
                                    different dtype than the interpreted
                                    path; surface each unmarked chain's
                                    ``not compilable: <reason>`` as INFO
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Schema
from ..graph import EdgeType, Graph, Node, OpName
from .diagnostics import Diagnostic, Severity, finish

IS_RETRACT_FIELD = "_is_retract"

# connectors whose sources always terminate; impulse/nexmark are bounded
# only when an explicit count option caps them
_BOUNDED_CONNECTORS = {"single_file", "vec", "filesystem"}
_COUNT_CAPPED = {"impulse": "message_count", "nexmark": "event_count"}

_WINDOWED_OPS = (
    OpName.TUMBLING_AGGREGATE,
    OpName.SLIDING_AGGREGATE,
    OpName.SESSION_AGGREGATE,
    OpName.INSTANT_JOIN,
)

# operators that hold checkpointed state: a barrier that cannot reach them
# means their snapshots never cut consistently
_STATEFUL_OPS = _WINDOWED_OPS + (
    OpName.UPDATING_AGGREGATE,
    OpName.JOIN_WITH_EXPIRATION,
    OpName.WINDOW_FUNCTION,
    OpName.LOOKUP_JOIN,
)


class PassContext:
    """Graph + shared derived maps handed to every pass."""

    def __init__(self, graph: Graph):
        self.graph = graph
        self.diags: list[Diagnostic] = []
        self._updating: Optional[dict[str, bool]] = None
        self._unbounded: Optional[dict[str, bool]] = None

    def add(self, rule_id: str, severity: Severity, site: str, message: str,
            hint: str = "") -> None:
        self.diags.append(Diagnostic(rule_id, severity, site, message, hint))

    # ---------------------------------------------------- derived properties

    def updating(self) -> dict[str, bool]:
        """node id -> does its OUTPUT stream carry retractions. Mirrors the
        planner's Rel.updating trait, recomputed from the graph alone so
        shipped/hand-built IR is checked too."""
        if self._updating is None:
            out: dict[str, bool] = {}
            for node in self.graph.topo_order():
                ins = [out.get(e.src, False) for e in self.graph.in_edges(node.node_id)]
                if node.op == OpName.SOURCE:
                    upd = str(node.config.get("format", "")) == "debezium_json"
                elif node.op in (OpName.UPDATING_AGGREGATE, OpName.JOIN_WITH_EXPIRATION):
                    upd = True
                elif node.op in _WINDOWED_OPS:
                    upd = False  # event-time windows emit append-only results
                else:  # value/key/watermark/unnest/async_udf/window_fn/... pass through
                    upd = any(ins)
                out[node.node_id] = upd
            self._updating = out
        return self._updating

    def unbounded(self) -> dict[str, bool]:
        """node id -> is it fed (transitively) by an unbounded source."""
        if self._unbounded is None:
            out: dict[str, bool] = {}
            for node in self.graph.topo_order():
                if node.op == OpName.SOURCE:
                    conn = str(node.config.get("connector", ""))
                    if conn in _BOUNDED_CONNECTORS:
                        ub = False
                    elif conn in _COUNT_CAPPED:
                        ub = node.config.get(_COUNT_CAPPED[conn]) is None
                    else:
                        ub = True
                else:
                    ub = any(out.get(e.src, False)
                             for e in self.graph.in_edges(node.node_id))
                out[node.node_id] = ub
            self._unbounded = out
        return self._unbounded

    def input_columns(self, node_id: str) -> set[str]:
        """Union of column names this node's input edges deliver (plus the
        implicit system columns every batch may carry)."""
        cols: set[str] = {TIMESTAMP_FIELD, KEY_FIELD, IS_RETRACT_FIELD}
        for e in self.graph.in_edges(node_id):
            cols.update(f.name for f in e.schema.fields)
        return cols


def _expr_columns(obj) -> set[str]:
    """Column names referenced anywhere inside a config value holding
    Expr nodes (single expr, (name, expr) pairs, nested lists)."""
    from ..expr import Expr

    out: set[str] = set()
    if isinstance(obj, Expr):
        out |= obj.columns()
    elif isinstance(obj, dict):
        for v in obj.values():
            out |= _expr_columns(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out |= _expr_columns(v)
    return out


def _fmt_micros(us: int) -> str:
    if us % 1_000_000 == 0:
        return f"{us // 1_000_000}s"
    if us % 1000 == 0:
        return f"{us // 1000}ms"
    return f"{us}us"


# --------------------------------------------------------------------- passes


def pass_edge_schema(ctx: PassContext) -> None:
    """AR001: operator configs may only name columns their inputs carry.
    (Duplicate edge columns are impossible here: Schema.__post_init__
    already rejects them at construction.)"""
    # which config keys hold input-referencing expressions, per operator
    expr_keys = {
        OpName.VALUE: ("projections", "filter"),
        OpName.KEY: ("keys",),
        OpName.WATERMARK: ("expr",),
        OpName.ASYNC_UDF: ("arg_exprs",),
        OpName.TUMBLING_AGGREGATE: ("aggregates",),
        OpName.SLIDING_AGGREGATE: ("aggregates",),
        OpName.SESSION_AGGREGATE: ("aggregates",),
        OpName.UPDATING_AGGREGATE: ("aggregates",),
        OpName.WINDOW_FUNCTION: ("order_by", "functions"),
        OpName.UNNEST: (),  # references its input by name, not by Expr
    }
    for node in ctx.graph.nodes.values():
        keys = expr_keys.get(node.op)
        if keys is None or not ctx.graph.in_edges(node.node_id):
            continue
        avail = ctx.input_columns(node.node_id)
        used: set[str] = set()
        for k in keys:
            used |= _expr_columns(node.config.get(k))
        if node.op == OpName.UNNEST:
            used.add(str(node.config.get("column")))
        missing = sorted(used - avail)
        if missing:
            ctx.add("AR001", Severity.ERROR, node.node_id,
                    f"{node.op.value} references column(s) {missing} absent "
                    f"from its input edge schema(s)",
                    "a projection upstream dropped or renamed them; carry "
                    "them through or fix the reference")


def pass_watermark_safety(ctx: PassContext) -> None:
    """AR002: unaligned hop(); AR003: updating inputs into event-time
    window operators (their watermark-driven flushes cannot retract)."""
    updating = ctx.updating()
    for node in ctx.graph.nodes.values():
        if node.op == OpName.SLIDING_AGGREGATE:
            width = int(node.config.get("width_micros", 0))
            slide = int(node.config.get("slide_micros", 0))
            if width <= 0 or slide <= 0 or width % slide != 0:
                ctx.add(
                    "AR002", Severity.ERROR, node.node_id,
                    f"hop(slide={_fmt_micros(slide)}, width={_fmt_micros(width)}) "
                    "is unaligned: the slide must be a positive divisor of the "
                    "width",
                    f"use a width that is a multiple of the slide, e.g. "
                    f"hop(interval '{max(slide, 1) // 1_000_000 or 1} seconds', "
                    f"interval '{(max(width // max(slide, 1), 1)) * (max(slide, 1) // 1_000_000 or 1)} seconds')",
                )
        if node.op in _WINDOWED_OPS:
            bad = [e.src for e in ctx.graph.in_edges(node.node_id)
                   if updating.get(e.src, False)]
            if bad:
                ctx.add(
                    "AR003", Severity.ERROR, node.node_id,
                    f"{node.op.value} consumes an updating (retracting) input "
                    f"from {sorted(bad)}; event-time windows emit once per "
                    "window and cannot retract already-emitted results",
                    "aggregate the updating stream with a non-windowed "
                    "(updating) aggregate, or window before the retracting "
                    "operator",
                )


def pass_unbounded_state(ctx: PassContext) -> None:
    """AR004: state that only grows. A non-windowed join or updating
    aggregate over an unbounded source with no TTL retains every key
    forever; the job dies by memory, slowly."""
    unbounded = ctx.unbounded()
    for node in ctx.graph.nodes.values():
        if not unbounded.get(node.node_id, False):
            continue
        if node.config.get("ttl_micros"):
            continue
        if node.op == OpName.JOIN_WITH_EXPIRATION:
            ctx.add(
                "AR004", Severity.WARNING, node.node_id,
                "non-windowed join over unbounded input(s) with no TTL: both "
                "join-side state tables retain every key seen, so state "
                "grows linearly with distinct keys for the life of the job",
                "SET updating_ttl = '1 hour' (or window both sides) to bound "
                "retained state",
            )
        elif node.op == OpName.UPDATING_AGGREGATE:
            ctx.add(
                "AR004", Severity.WARNING, node.node_id,
                "updating aggregate over unbounded input with no TTL: one "
                "accumulator per distinct group key is retained forever, so "
                "state grows with key cardinality for the life of the job",
                "SET updating_ttl = '1 hour' to expire idle groups, or use "
                "an event-time window",
            )


def pass_retraction_sink(ctx: PassContext) -> None:
    """AR005: updating stream into an append-only-formatted sink. The
    engine falls back to Debezium envelopes, so a consumer reading the
    declared plain format sees op/before/after wrappers it did not ask
    for (or double-counts retracted rows)."""
    updating = ctx.updating()
    for node in ctx.graph.nodes.values():
        if node.op != OpName.SINK:
            continue
        conn = str(node.config.get("connector", ""))
        if conn in ("preview", "stdout", "blackhole"):
            continue  # debug sinks render anything
        fmt = str(node.config.get("format", "json"))
        if fmt == "debezium_json":
            continue
        if any(updating.get(e.src, False) for e in ctx.graph.in_edges(node.node_id)):
            ctx.add(
                "AR005", Severity.WARNING, node.node_id,
                f"sink declares append-only format {fmt!r} but receives an "
                "updating stream; rows will be wrapped in Debezium "
                "envelopes the declared schema does not describe",
                "declare format = 'debezium_json' on the sink, or make the "
                "feeding query append-only (window the aggregate/join)",
            )


def pass_barrier_reachability(ctx: PassContext) -> None:
    """AR006: checkpoint barriers flow from sources; an operator with no
    path from a source never aligns a barrier, so its state is never
    snapshotted consistently. Also flags sources whose output reaches no
    sink (dead subgraphs hold barriers/watermarks for nothing)."""
    g = ctx.graph
    for node in g.nodes.values():
        if node.op != OpName.SOURCE and not g.in_edges(node.node_id):
            ctx.add(
                "AR006", Severity.ERROR, node.node_id,
                f"{node.op.value} has no input edges: checkpoint barriers "
                "can never reach it, so it will stall every checkpoint "
                "epoch",
                "connect it downstream of a source or remove it",
            )
    # source -> reaches-a-sink
    reaches_sink: dict[str, bool] = {}
    for node in reversed(g.topo_order()):
        if node.op == OpName.SINK:
            reaches_sink[node.node_id] = True
        else:
            reaches_sink[node.node_id] = any(
                reaches_sink.get(e.dst, False) for e in g.out_edges(node.node_id)
            )
    for node in g.nodes.values():
        if node.op == OpName.SOURCE and not reaches_sink.get(node.node_id, False):
            ctx.add(
                "AR006", Severity.WARNING, node.node_id,
                "source output never reaches a sink; it still gates "
                "watermarks and checkpoint barriers for the whole pipeline",
                "remove the dead branch or add the missing INSERT INTO",
            )


def pass_shuffle_keys(ctx: PassContext) -> None:
    """AR007: a shuffle edge repartitions by the _key routing hash; the
    nearest upstream KEY node must compute exactly the columns the
    consumer groups/partitions by, or parallel instances see torn groups."""
    g = ctx.graph
    for e in g.edges:
        if e.edge_type != EdgeType.SHUFFLE:
            continue
        dst = g.nodes[e.dst]
        want = list(dst.config.get("key_fields")
                    or dst.config.get("partition_fields") or [])
        # walk up through forwarding operators to the key calculation
        cur = e.src
        seen = set()
        key_node: Optional[Node] = None
        while cur not in seen:
            seen.add(cur)
            n = g.nodes[cur]
            if n.op == OpName.KEY:
                key_node = n
                break
            ins = g.in_edges(cur)
            if n.op in (OpName.VALUE, OpName.WATERMARK) and len(ins) == 1:
                cur = ins[0].src
                continue
            break
        if key_node is None:
            ctx.add(
                "AR007", Severity.ERROR, f"{e.src} -> {e.dst}",
                "shuffle edge with no upstream key calculation: batches "
                "carry no _key routing hash, so repartitioning is undefined",
                "insert a KEY node computing the consumer's group-by "
                "columns before the shuffle",
            )
            continue
        have = [name for name, _expr in key_node.config.get("keys", [])]
        if want and sorted(have) != sorted(want):
            ctx.add(
                "AR007", Severity.ERROR, f"{e.src} -> {e.dst}",
                f"shuffle key mismatch: upstream keys by {sorted(have)} but "
                f"{dst.op.value} groups by {sorted(want)}; rows of one group "
                "would land on different instances",
                "make the KEY node compute exactly the consumer's group-by "
                "columns",
            )


def pass_table_specs(ctx: PassContext) -> None:
    """AR008: instantiate each node's operator (the registered constructor,
    exactly what the engine will build) and audit its declared TableSpecs.

    Duplicate names within one node collide on the checkpoint path scheme
    — ``operator-{op}/table-{name}-{subtask}`` — so two tables would write
    one file and restore would resurrect whichever won. An expiring spec
    whose retention differs from the operator's configured ``ttl_micros``
    makes restore load a different horizon than the live operator expires,
    so recovered state diverges from the state the run would have had.
    Nodes whose constructor is unavailable here (unregistered connector,
    missing client package) are skipped — the audit proves what it can
    see, it does not block planning on optional dependencies."""
    from ..engine.engine import construct_operator

    for node in ctx.graph.nodes.values():
        try:
            # a COPY of the config: constructors may validate-and-mutate
            # their cfg (e.g. setdefault a Lock), and the analysis probe
            # must not plant runtime objects into the planned graph
            op = construct_operator(node.op, dict(node.config))
            specs = list(op.tables())
        except Exception:
            continue
        seen: dict[str, int] = {}
        for s in specs:
            seen[s.name] = seen.get(s.name, 0) + 1
        for name in sorted(n for n, c in seen.items() if c > 1):
            ctx.add(
                "AR008", Severity.ERROR, node.node_id,
                f"{node.op.value} declares {seen[name]} state tables named "
                f"{name!r}: the checkpoint path scheme keys files by "
                "(operator, table, subtask), so they would overwrite each "
                "other and restore would resurrect only one",
                "give every TableSpec a unique name within the operator "
                "(chained members are prefixed c<i>. for exactly this "
                "reason)",
            )
        ttl = node.config.get("ttl_micros")
        if not ttl:
            continue
        ttl = int(ttl)
        for s in specs:
            if s.kind != "expiring_time_key" or s.retention_micros == ttl:
                continue
            ctx.add(
                "AR008", Severity.ERROR, node.node_id,
                f"{node.op.value} is configured with ttl_micros="
                f"{_fmt_micros(ttl)} but declares table {s.name!r} with "
                f"retention {_fmt_micros(s.retention_micros)}: restore "
                "would load a different state horizon than the live "
                "operator expires",
                "derive the TableSpec retention from the configured TTL",
            )


# AR009 lives with the trace-safety auditor (dual-path dtype model shared
# with the LR3xx rules) but runs as an ordinary plan pass
from .trace_audit import pass_segment_compile  # noqa: E402

PLAN_PASSES: tuple[tuple[str, Callable[[PassContext], None]], ...] = (
    ("edge-schema-consistency", pass_edge_schema),
    ("watermark-safety", pass_watermark_safety),
    ("unbounded-state", pass_unbounded_state),
    ("retraction-sink-mismatch", pass_retraction_sink),
    ("barrier-reachability", pass_barrier_reachability),
    ("shuffle-key-consistency", pass_shuffle_keys),
    ("table-spec-consistency", pass_table_specs),
    ("segment-compilability", pass_segment_compile),
)


def analyze_graph(graph: Graph) -> list[Diagnostic]:
    """Run every plan pass; returns deterministically ordered diagnostics
    (never raises — callers decide what severity rejects)."""
    ctx = PassContext(graph)
    for _name, p in PLAN_PASSES:
        p(ctx)
    return finish(ctx.diags)
