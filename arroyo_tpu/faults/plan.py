"""Fault plan grammar and the deterministic injector.

A plan is a comma-separated list of fault specs::

    site:action[=arg][@cond[&cond]...]

    storage.put:fail_once@match=checkpoint-0000002
    storage.put:fail_n=3@match=compacted
    network.send:partition@step=40
    network.send:delay=25@after=10
    queue.put:delay=50@step=10
    worker:crash@barrier=3&step=1
    connector.poll:fail@prob=0.01

Sites are dotted names named by the instrumented call sites (see
``arroyo_tpu.faults.SITES``). Actions:

    fail        raise InjectedFault (transient) every time the spec matches
    fail_once   raise on the first match only
    fail_n=K    raise on the first K matches
    crash       raise InjectedCrash (a worker-fatal fault; tasks report
                task_failed and the engine aborts, like a process kill)
    partition   raise InjectedPartition (a ConnectionError: the data plane
                and sockets treat it exactly like a peer going away)
    drop        tell the call site to drop the item (frame, message, ...)
    dup         tell the call site to duplicate the item
    delay=MS    sleep MS milliseconds at the call site, then continue
    hang=S      sleep S seconds (models a stall; pairs with heartbeat
                timeouts), then continue
    force=V     tell the call site to substitute the value V for whatever
                it was about to use (site-specific: e.g. autoscale_decide
                forces a bogus target parallelism the rails must clamp)
    corrupt=M   tell the call site to corrupt the bytes in flight, M one of
                ``bitflip`` (flip one bit of the middle byte) or
                ``truncate`` (keep the first half) — the storage data paths
                apply it to puts (persistent corruption, like a truncated
                upload) and gets (read-side corruption, like bit rot);
                pair with ``@match=<path-substr>`` to hit one artifact

Conditions restrict when a spec matches. ``match=SUBSTR`` tests substring
containment against the call's ``key`` context (paths, shard ids, quads);
any other ``k=v`` compares stringified equality against the call's context
kwargs (``epoch``, ``barrier``, ``subtask``...). Two ordinal conditions run
against the per-spec hit counter of *matching* calls: ``step=N`` fires on
exactly the Nth match, ``after=N`` fires on every match from the Nth on.
``prob=P`` fires with probability P from the injector's seeded RNG — the
only nondeterminism, and it is reproducible given the same seed and call
sequence.

The first firing spec wins per call. All counters live in the injector, so
a given (plan, seed, call sequence) replays identically — the chaos suite
logs both on failure.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

_log = logging.getLogger("arroyo_tpu.faults")

# actions that raise at the fault point; everything else returns a verdict
# the call site applies itself (drop/dup/force) or that the injector
# applies inline (delay/hang)
_RAISING = ("fail", "fail_once", "fail_n", "crash", "partition")
_KNOWN_ACTIONS = _RAISING + ("drop", "dup", "delay", "hang", "force",
                             "corrupt")

# corrupt=<mode> carries a string arg (the corruption mode), not a number
CORRUPT_MODES = ("bitflip", "truncate")


class InjectedFault(RuntimeError):
    """A deliberately injected transient failure."""

    transient = True

    def __init__(self, site: str, detail: str = ""):
        super().__init__(f"injected fault at {site}" + (f" ({detail})" if detail else ""))
        self.site = site


class InjectedCrash(InjectedFault):
    """Worker-fatal injected failure (simulated crash): not transient, so
    retry layers let it propagate and the task dies."""

    transient = False


class InjectedPartition(ConnectionError):
    """Injected network partition; a ConnectionError so socket-facing code
    handles it exactly like a peer vanishing mid-stream."""

    transient = False

    def __init__(self, site: str):
        super().__init__(f"injected network partition at {site}")
        self.site = site


@dataclass
class FaultSpec:
    site: str
    action: str
    arg: Optional[object] = None  # float, or str for corrupt=<mode>
    conds: dict = field(default_factory=dict)
    hits: int = 0   # calls matching the non-ordinal conditions
    fired: int = 0  # times this spec actually fired

    def describe(self) -> str:
        a = self.action
        if self.arg is not None:
            a += (f"={self.arg:g}" if isinstance(self.arg, float)
                  else f"={self.arg}")
        c = "&".join(f"{k}={v}" for k, v in self.conds.items())
        return f"{self.site}:{a}" + (f"@{c}" if c else "")


class PlanSyntaxError(ValueError):
    pass


def parse_plan(plan: str) -> list[FaultSpec]:
    specs: list[FaultSpec] = []
    for raw in plan.split(","):
        raw = raw.strip()
        if not raw:
            continue
        if ":" not in raw:
            raise PlanSyntaxError(f"fault spec {raw!r}: expected site:action")
        site, rest = raw.split(":", 1)
        cond_str = ""
        if "@" in rest:
            rest, cond_str = rest.split("@", 1)
        action, arg = rest, None
        if "=" in rest:
            action, args = rest.split("=", 1)
            if action == "corrupt":
                if args not in CORRUPT_MODES:
                    raise PlanSyntaxError(
                        f"fault spec {raw!r}: corrupt mode must be one of "
                        f"{', '.join(CORRUPT_MODES)}")
                arg = args
            else:
                try:
                    arg = float(args)
                except ValueError as e:
                    raise PlanSyntaxError(f"fault spec {raw!r}: bad arg {args!r}") from e
        if action not in _KNOWN_ACTIONS:
            raise PlanSyntaxError(
                f"fault spec {raw!r}: unknown action {action!r} "
                f"(have: {', '.join(_KNOWN_ACTIONS)})")
        if action in ("fail_n", "delay", "hang", "force", "corrupt") \
                and arg is None:
            raise PlanSyntaxError(f"fault spec {raw!r}: {action} needs =ARG")
        conds: dict = {}
        if cond_str:
            for c in cond_str.split("&"):
                if "=" not in c:
                    raise PlanSyntaxError(f"fault spec {raw!r}: bad condition {c!r}")
                k, v = c.split("=", 1)
                conds[k.strip()] = v.strip()
        for ordinal in ("step", "after", "prob"):
            if ordinal in conds:
                try:
                    float(conds[ordinal])
                except ValueError as e:
                    raise PlanSyntaxError(
                        f"fault spec {raw!r}: {ordinal} must be numeric") from e
        specs.append(FaultSpec(site=site.strip(), action=action, arg=arg, conds=conds))
    return specs


class FaultInjector:
    """Holds a parsed plan plus deterministic per-spec counters and the
    seeded RNG. One instance is installed globally (``faults.install``);
    call sites consult it through ``faults.fault_point``."""

    def __init__(self, plan: str, seed: int = 0):
        self.plan = plan
        self.seed = int(seed)
        self.specs = parse_plan(plan)
        self.rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.fired_log: list[str] = []  # human trail of fired faults

    def hit(self, site: str, **ctx) -> Optional[tuple[str, Optional[float]]]:
        """Register a call at ``site``. Raises for raising actions; returns
        ("drop"|"dup"|"delay"|"hang", arg) verdicts the caller applies (delay
        and hang have already slept by the time they return); None when no
        spec fires."""
        fired_spec: Optional[FaultSpec] = None
        with self._lock:
            # every matching spec counts every call (its ordinal clock keeps
            # ticking even when another spec fires first); the first spec
            # whose ordinals+quota allow firing wins this call
            for spec in self.specs:
                if spec.site != site:
                    continue
                if not self._conds_match(spec, ctx):
                    continue
                spec.hits += 1
                if fired_spec is not None:
                    continue
                if not self._ordinals_fire(spec):
                    continue
                if spec.action == "fail_once" and spec.fired >= 1:
                    continue
                if spec.action == "fail_n" and spec.fired >= int(spec.arg or 0):
                    continue
                fired_spec = spec
            if fired_spec is None:
                return None
            fired_spec.fired += 1
            verdict = (fired_spec.action, fired_spec.arg)
            self.fired_log.append(
                f"{fired_spec.describe()} fired (hit #{fired_spec.hits}) ctx={ctx}")
        _log.info("fault %s fired at %s ctx=%s", fired_spec.describe(), site, ctx)
        action, arg = verdict
        if action in ("fail", "fail_once", "fail_n"):
            raise InjectedFault(site, fired_spec.describe())
        if action == "crash":
            raise InjectedCrash(site, fired_spec.describe())
        if action == "partition":
            raise InjectedPartition(site)
        if action == "delay":
            time.sleep((arg or 0.0) / 1000.0)
        elif action == "hang":
            time.sleep(arg or 0.0)
        return verdict

    # ------------------------------------------------------------- matching

    def _conds_match(self, spec: FaultSpec, ctx: dict) -> bool:
        for k, v in spec.conds.items():
            if k in ("step", "after", "prob"):
                continue  # ordinal/probabilistic: evaluated post-count
            if k == "match":
                if v not in str(ctx.get("key", "")):
                    return False
            elif k not in ctx or str(ctx[k]) != v:
                return False
        return True

    def _ordinals_fire(self, spec: FaultSpec) -> bool:
        c = spec.conds
        if "step" in c and spec.hits != int(float(c["step"])):
            return False
        if "after" in c and spec.hits < int(float(c["after"])):
            return False
        if "prob" in c and self.rng.random() >= float(c["prob"]):
            return False
        return True
