"""Deterministic fault injection threaded through the engine.

The exactly-once claims in this repo (Chandy-Lamport barrier checkpoints,
two-phase-commit sinks, crash-consistent compaction) are only claims until a
failure actually happens mid-protocol. This subsystem makes failures happen
on purpose, deterministically, at the seams where real deployments lose
data: storage puts/gets, the TCP data plane, queue backpressure, connector
poll/commit, and worker crashes mid-checkpoint. The chaos suite
(tests/test_faults.py plus the ``chaos``-marked axis of tests/test_smoke.py)
reruns golden-output pipelines under these faults and asserts byte-exact
recovery — exactly-once proved, not claimed.

Usage::

    faults.install("worker:crash@barrier=2&step=1", seed=7)   # direct
    # or config-driven (env: ARROYO_TPU__FAULTS__PLAN / __FAULTS__SEED):
    config.update({"faults.plan": "storage.put:fail_once@epoch=2"})

Call sites are no-ops (one global read) when no plan is installed, so the
hooks stay in production builds. Plan syntax lives in
``arroyo_tpu.faults.plan`` and the README's "Fault injection" section.

Instrumented sites:

    storage.put / storage.get / storage.delete / storage.list
                        object-store ops (ctx: key=path); retried by the
                        shared retry layer, so transient actions recover
                        without a job restart. put/get additionally honor
                        ``corrupt=bitflip|truncate@match=<path-substr>``:
                        the bytes in flight are deterministically damaged
                        (put = persistent corruption like a truncated
                        upload; get = read-side bit rot) so chaos tests
                        can prove the integrity envelope detects every
                        corruption class and restore quarantines + falls
                        back instead of loading garbage
    storage.multipart   per-part S3 multipart upload (ctx: key, part)
    network.send        data-plane frame send (ctx: key="e,s->n,d" quad,
                        worker); drop/dup/delay/partition
    network.recv        data-plane frame receive (ctx: key, kind)
    queue.put           task inbox enqueue (ctx: input); delay models
                        backpressure stalls
    connector.poll      broker source poll (ctx: connector, key)
    connector.commit    broker ack/commit (ctx: connector, epoch)
    worker              barrier-time crash point in the task run loop
                        (ctx: barrier, node, subtask) — fires AFTER the
                        subtask's state files are written and BEFORE its
                        checkpoint-completed response, the worst spot
    worker.heartbeat    worker->controller heartbeat emission (drop to
                        starve the controller's liveness check)
    node.start_worker   node daemon worker admission (ctx: job)
    controller_rpc      controller->node-daemon HTTP surface (ctx: key=path,
                        op=post|get): drop/delay/dup commands and event
                        polls — recovery is protocol-level (buffered event
                        queues, watchdog re-trigger, cumulative commits),
                        never a pretend-success
    commit              phase-2 commit fan-out of the controller's 2PC
                        (ctx: epoch, worker); drop proves a lost commit is
                        re-delivered with the next epoch, not lost
    rescale             the per-worker scale command of a live rescale
                        (the then_stop drain trigger; ctx: epoch, worker):
                        drop/delay it mid-transition — the stuck-epoch
                        watchdog must re-trigger the drain, never wedge
    autoscale_decide    the autoscaler's decision point (ctx: key=job,
                        target, direction): force=N substitutes a bogus
                        target the min/max rails must clamp, drop
                        suppresses the decision, fail costs one tick
    spill_write         tiered-state run write (state/spill.py; ctx:
                        key=path, epoch, subtask): a failure re-pins the
                        partition hot (SPILL_FALLBACK), never loses state
    spill_probe         tiered-state run read on the probe path (ctx: key,
                        epoch, subtask): retried once in place; a second
                        failure propagates so the set restores from the
                        checkpoint instead of inventing data
    spill_compact       spill-generation merge write (ctx: key, epoch,
                        subtask): a failure keeps the old generations —
                        more read amplification, zero correctness impact
    admission           worker placement for a job (controller _schedule +
                        NodeScheduler._place_once; ctx: key/job): fail
                        models a node-daemon 409 after the status poll
                        said free — the job re-queues into the fleet's
                        admission queue with deterministic backoff, NEVER
                        fails; delay models a slow admission RPC
    fleet_place         the fleet's per-job placement decision inside the
                        deficit-round-robin admission pass (ctx: key=job,
                        tenant, slots): drop suppresses the grant for the
                        pass, force grants regardless of credit/capacity
                        (the rails must absorb the oversubscription)
    job_tick            a job's controller supervision step (ctx: key=job):
                        delay=MS models a melting job's slow step — the
                        fleet.tick-budget-ms isolation must emit
                        JOB_TICK_OVERRUN and deprioritize it while its
                        neighbors keep their heartbeat/watchdog cadence
    evolve_drain        the per-worker drain trigger of a live evolution
                        (the final-checkpoint then_stop command; ctx:
                        epoch, worker): drop/delay it mid-drain — the
                        stuck-epoch watchdog must re-trigger the drain and
                        the evolved plan must still restore exactly the
                        drained lineage, never a torn one
    evolve_cutover      the blue/green cutover barrier of a live evolution
                        (ctx: epoch, key=job) — fires after the evolved
                        set's first epoch is durable and BEFORE its
                        withheld phase-2 commits are released; a crash
                        here must recover to exactly one committed
                        lineage (the commits re-deliver cumulatively on
                        restart, COMMIT_REDELIVERED)
    lock_contend        hold-time delay inside an instrumented critical
                        section (obs/lockorder.py make_lock proxies; ctx:
                        key="Class.attr"): ``delay=MS@match=<class>``
                        widens the race window the concurrency auditor
                        (LR4xx) flagged statically, so chaos tests can
                        turn a suspected interleaving into a schedulable
                        one. Locks are instrumented when constructed
                        while a plan naming the site is installed (or the
                        lock-order witness is enabled)
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from .plan import (  # noqa: F401 - public API
    FaultInjector,
    FaultSpec,
    InjectedCrash,
    InjectedFault,
    InjectedPartition,
    PlanSyntaxError,
    parse_plan,
)

_log = logging.getLogger("arroyo_tpu.faults")

_lock = threading.Lock()
_active: Optional[FaultInjector] = None
_from_config = False

SITES = (
    "storage.put", "storage.get", "storage.delete", "storage.list",
    "storage.multipart", "network.send", "network.recv", "queue.put",
    "connector.poll", "connector.commit", "worker", "worker.heartbeat",
    "node.start_worker", "controller_rpc", "commit", "rescale",
    "autoscale_decide", "spill_write", "spill_probe", "spill_compact",
    "admission", "fleet_place", "job_tick", "evolve_drain", "evolve_cutover",
    "lock_contend",
)


def install(plan: str, seed: int = 0, _config_origin: bool = False) -> FaultInjector:
    """Parse and activate ``plan``; returns the injector. The plan and seed
    are logged so any chaos failure is replayable."""
    global _active, _from_config
    inj = FaultInjector(plan, seed=seed)
    with _lock:
        _active = inj
        _from_config = _config_origin
    _log.info("fault plan installed: %r (seed=%d)", plan, seed)
    return inj


def clear() -> None:
    global _active, _from_config
    with _lock:
        _active = None
        _from_config = False


def active() -> Optional[FaultInjector]:
    return _active


def install_from_config() -> Optional[FaultInjector]:
    """Sync the injector with ``faults.plan`` / ``faults.seed`` config.

    Called at Engine construction so worker subprocesses pick plans up from
    the environment. A non-empty configured plan (re)installs with FRESH
    counters — each worker incarnation replays its faults, which is what
    restart-crash loops need. An empty config only clears a plan that came
    from config; plans installed directly by tests are left alone.
    """
    from ..config import config

    plan = config().get("faults.plan") or ""
    if plan:
        seed = int(config().get("faults.seed") or 0)
        return install(str(plan), seed=seed, _config_origin=True)
    with _lock:
        was_config = _from_config
    if was_config:
        clear()
    return None


def fault_point(site: str, **ctx) -> Optional[tuple]:
    """The hook embedded at instrumented call sites. Fast no-op when no
    plan is active. May raise InjectedFault/InjectedCrash/InjectedPartition
    or return a ("drop"|"dup"|"delay"|"hang", arg) verdict."""
    inj = _active
    if inj is None:
        return None
    return inj.hit(site, **ctx)
