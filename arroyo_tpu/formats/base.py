"""Shared deserializer machinery: rows -> columnar batches with flush policy.

Reference: ArrowDeserializer (crates/arroyo-formats/src/de.rs:249) —
incremental batch building with size/linger flush (should_flush de.rs:498)
and the BadData::{Drop,Fail} policy; format-specific subclasses only turn
payload bytes into row dicts.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..batch import STRING, TIMESTAMP_FIELD, Batch, Schema


class BadDataError(ValueError):
    pass


class RowBatchingDeserializer:
    """Accumulates decoded rows, flushing by batch size / linger."""

    def __init__(
        self,
        schema: Schema,
        batch_size: int = 512,
        linger_micros: int = 100_000,
        bad_data: str = "fail",
        event_time_field: Optional[str] = None,
    ):
        self.schema = schema
        self.batch_size = batch_size
        self.linger_micros = linger_micros
        self.bad_data = bad_data
        self.event_time_field = event_time_field
        self._rows: list[dict] = []
        self._first_buffer_time: Optional[float] = None
        self.errors = 0

    # -- subclass hook -------------------------------------------------------

    def _decode(self, payload) -> list[dict]:
        """payload (bytes/str) -> row dicts; raise on malformed input."""
        raise NotImplementedError

    # -- public API ----------------------------------------------------------

    def deserialize(self, payload, timestamp_micros: Optional[int] = None) -> None:
        try:
            rows = self._decode(payload)
        except Exception:
            if self.bad_data == "drop":
                self.errors += 1
                return
            raise
        if not rows:
            return
        if timestamp_micros is not None:
            for r in rows:
                r.setdefault(TIMESTAMP_FIELD, timestamp_micros)
        if self._first_buffer_time is None:
            self._first_buffer_time = time.monotonic()
        self._rows.extend(rows)

    def should_flush(self) -> bool:
        if len(self._rows) >= self.batch_size:
            return True
        return (
            bool(self._rows)
            and self._first_buffer_time is not None
            and (time.monotonic() - self._first_buffer_time) * 1e6 >= self.linger_micros
        )

    def flush(self) -> Optional[Batch]:
        if not self._rows:
            return None
        rows, self._rows = self._rows, []
        self._first_buffer_time = None
        return rows_to_batch(rows, self.schema, self.event_time_field)


def rows_to_batch(
    rows: list[dict], schema: Schema, event_time_field: Optional[str] = None
) -> Batch:
    from .json_fmt import parse_iso_micros

    cols: dict[str, np.ndarray] = {}
    for f in schema.fields:
        if f.name == TIMESTAMP_FIELD:
            continue
        vals = [r.get(f.name) for r in rows]
        if f.dtype == "timestamp":
            cols[f.name] = np.array(
                [0 if v is None else parse_iso_micros(v) for v in vals], dtype=np.int64
            )
        elif f.dtype == STRING:
            cols[f.name] = np.array(
                [None if v is None else str(v) for v in vals], dtype=object
            )
        elif f.dtype in ("float32", "float64"):
            cols[f.name] = np.array(
                [np.nan if v is None else float(v) for v in vals], dtype=f.numpy_dtype()
            )
        elif f.dtype == "bool":
            cols[f.name] = np.array([bool(v) for v in vals], dtype=np.bool_)
        else:
            cols[f.name] = np.array(
                [0 if v is None else int(v) for v in vals], dtype=f.numpy_dtype()
            )
    if event_time_field:
        cols[TIMESTAMP_FIELD] = np.asarray(cols[event_time_field]).astype(np.int64)
    else:
        now = int(time.time() * 1e6)
        ts = [r.get(TIMESTAMP_FIELD, now) for r in rows]
        cols[TIMESTAMP_FIELD] = np.array(ts, dtype=np.int64)
    # debezium rows carry the retract flag through to the batch (reference
    # de.rs debezium -> _updating_meta.is_retract); absent for append formats
    if rows and "_is_retract" in rows[0]:
        cols["_is_retract"] = np.array(
            [bool(r.get("_is_retract", False)) for r in rows], dtype=np.bool_)
    return Batch(cols)
