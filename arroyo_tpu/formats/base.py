"""Shared deserializer machinery: rows -> columnar batches with flush policy.

Reference: ArrowDeserializer (crates/arroyo-formats/src/de.rs:249) —
incremental batch building with size/linger flush (should_flush de.rs:498)
and the BadData::{Drop,Fail} policy; format-specific subclasses only turn
payload bytes into row dicts.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..batch import STRING, TIMESTAMP_FIELD, Batch, Schema


class BadDataError(ValueError):
    pass


# BAD_DATA_DROPPED events are throttled per deserializer so a poisoned topic
# can't flood the event log; the metric counter stays exact regardless
_DROP_EVENT_INTERVAL_S = 30.0


class RowBatchingDeserializer:
    """Accumulates decoded rows, flushing by batch size / linger.

    Owns the ``bad_data = fail | drop`` policy for EVERY connector:
    decode errors hit it in :meth:`deserialize`, and connectors route
    transport-level record errors through :meth:`drop_bad_data` instead of
    reimplementing the option inline, so drops are counted
    (``arroyo_bad_records_total``) and surfaced (``BAD_DATA_DROPPED``)
    uniformly no matter which layer rejected the record.
    """

    def __init__(
        self,
        schema: Schema,
        batch_size: int = 512,
        linger_micros: int = 100_000,
        bad_data: str = "fail",
        event_time_field: Optional[str] = None,
        task_info=None,
    ):
        self.schema = schema
        self.batch_size = batch_size
        self.linger_micros = linger_micros
        self.bad_data = bad_data
        self.event_time_field = event_time_field
        self.task_info = task_info
        self._rows: list[dict] = []
        self._first_buffer_time: Optional[float] = None
        self.errors = 0
        self._drops_unreported = 0
        self._last_drop_event: Optional[float] = None

    # -- subclass hook -------------------------------------------------------

    def _decode(self, payload) -> list[dict]:
        """payload (bytes/str) -> row dicts; raise on malformed input."""
        raise NotImplementedError

    # -- bad-data policy -----------------------------------------------------

    def drop_bad_data(self, err: Exception) -> bool:
        """The one ``bad_data`` decision point. Returns True when the record
        was dropped (policy ``drop``; drop recorded), False when the caller
        must re-raise (policy ``fail``)."""
        if self.bad_data != "drop":
            return False
        self.errors += 1
        ti = self.task_info
        if ti is None:
            return True
        from ..metrics import registry

        registry.add_bad_record(ti.job_id, ti.node_id)
        self._drops_unreported += 1
        now = time.monotonic()
        if (self._last_drop_event is None
                or now - self._last_drop_event >= _DROP_EVENT_INTERVAL_S):
            from ..obs.events import recorder

            recorder.record(
                ti.job_id, "WARN", "BAD_DATA_DROPPED",
                f"dropped {self._drops_unreported} bad record(s) under "
                f"bad_data=drop: {str(err)[:200]}",
                node=ti.node_id, subtask=ti.subtask_index,
                data={"dropped": self._drops_unreported,
                      "total_dropped": self.errors,
                      "last_error": str(err)[:400]})
            self._drops_unreported = 0
            self._last_drop_event = now
        return True

    # -- public API ----------------------------------------------------------

    def deserialize(self, payload, timestamp_micros: Optional[int] = None) -> None:
        try:
            rows = self._decode(payload)
        except Exception as exc:
            if self.drop_bad_data(exc):
                return
            raise
        if not rows:
            return
        if timestamp_micros is not None:
            for r in rows:
                r.setdefault(TIMESTAMP_FIELD, timestamp_micros)
        if self._first_buffer_time is None:
            self._first_buffer_time = time.monotonic()
        self._rows.extend(rows)

    def should_flush(self) -> bool:
        if len(self._rows) >= self.batch_size:
            return True
        return (
            bool(self._rows)
            and self._first_buffer_time is not None
            and (time.monotonic() - self._first_buffer_time) * 1e6 >= self.linger_micros
        )

    def flush(self) -> Optional[Batch]:
        if not self._rows:
            return None
        rows, self._rows = self._rows, []
        self._first_buffer_time = None
        return rows_to_batch(rows, self.schema, self.event_time_field)


def rows_to_batch(
    rows: list[dict], schema: Schema, event_time_field: Optional[str] = None
) -> Batch:
    from .json_fmt import parse_iso_micros

    cols: dict[str, np.ndarray] = {}
    for f in schema.fields:
        if f.name == TIMESTAMP_FIELD:
            continue
        vals = [r.get(f.name) for r in rows]
        if f.dtype == "timestamp":
            cols[f.name] = np.array(
                [0 if v is None else parse_iso_micros(v) for v in vals], dtype=np.int64
            )
        elif f.dtype == STRING:
            cols[f.name] = np.array(
                [None if v is None else str(v) for v in vals], dtype=object
            )
        elif f.dtype in ("float32", "float64"):
            cols[f.name] = np.array(
                [np.nan if v is None else float(v) for v in vals], dtype=f.numpy_dtype()
            )
        elif f.dtype == "bool":
            cols[f.name] = np.array([bool(v) for v in vals], dtype=np.bool_)
        else:
            cols[f.name] = np.array(
                [0 if v is None else int(v) for v in vals], dtype=f.numpy_dtype()
            )
    if event_time_field:
        cols[TIMESTAMP_FIELD] = np.asarray(cols[event_time_field]).astype(np.int64)
    else:
        now = int(time.time() * 1e6)
        ts = [r.get(TIMESTAMP_FIELD, now) for r in rows]
        cols[TIMESTAMP_FIELD] = np.array(ts, dtype=np.int64)
    # debezium rows carry the retract flag through to the batch (reference
    # de.rs debezium -> _updating_meta.is_retract); absent for append formats
    if rows and "_is_retract" in rows[0]:
        cols["_is_retract"] = np.array(
            [bool(r.get("_is_retract", False)) for r in rows], dtype=np.bool_)
    return Batch(cols)
