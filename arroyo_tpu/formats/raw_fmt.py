"""Raw formats: RawString / RawBytes (reference arroyo-rpc/src/formats.rs
RawStringFormat/RawBytesFormat — one "value" column per message)."""

from __future__ import annotations

from .base import RowBatchingDeserializer


class RawStringDeserializer(RowBatchingDeserializer):
    def _decode(self, payload) -> list[dict]:
        text = payload.decode("utf-8") if isinstance(payload, bytes) else str(payload)
        return [{"value": text}]


class RawBytesDeserializer(RowBatchingDeserializer):
    def _decode(self, payload) -> list[dict]:
        data = payload if isinstance(payload, bytes) else str(payload).encode()
        return [{"value": data}]


def serialize_raw_string(batch, field: str = "value") -> list[bytes]:
    col = batch[field]
    return [("" if v is None else str(v)).encode() for v in col]
