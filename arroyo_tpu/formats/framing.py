"""Message framing: byte stream -> individual messages.

Reference: FramingIterator (crates/arroyo-formats/src/de.rs:68) with
newline-delimited and length-delimited framing options
(arroyo-rpc/src/formats.rs Framing).
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional


def frame_iter(data: bytes, framing: Optional[str]) -> Iterator[bytes]:
    """Split one payload into messages. framing: None (whole payload is one
    message) | "newline" | "length" (u32 BE length prefix per message)."""
    if framing is None:
        if data:
            yield data
        return
    if framing == "newline":
        for line in data.split(b"\n"):
            if line.strip():
                yield line
        return
    if framing == "length":
        off = 0
        n = len(data)
        while off + 4 <= n:
            (ln,) = struct.unpack_from(">I", data, off)
            off += 4
            if off + ln > n:
                raise ValueError(
                    f"length-framed message of {ln} bytes overruns payload ({n - off} left)"
                )
            yield data[off : off + ln]
            off += ln
        return
    raise ValueError(f"unknown framing {framing!r} (have: newline, length)")


def frame_join(messages: list[bytes], framing: Optional[str]) -> bytes:
    if framing is None:
        if len(messages) > 1:
            raise ValueError("unframed output can hold only one message")
        return messages[0] if messages else b""
    if framing == "newline":
        return b"\n".join(messages) + (b"\n" if messages else b"")
    if framing == "length":
        return b"".join(struct.pack(">I", len(m)) + m for m in messages)
    raise ValueError(f"unknown framing {framing!r}")
