"""Avro: self-contained binary codec (no fastavro dependency).

Reference: crates/arroyo-formats/src/avro/ (de.rs/ser.rs/schema.rs) —
raw datums with a fixed schema, Confluent wire format (magic 0x00 + 4-byte
BE schema id + datum), and Object Container Files for the filesystem
connector. Supported schema subset: records of
null/boolean/int/long/float/double/bytes/string, nullable unions
([null, T] / [T, null]), enums, arrays, maps, and the timestamp-millis /
timestamp-micros logical types (normalized to int64 micros).
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Optional

CONFLUENT_MAGIC = b"\x00"
OCF_MAGIC = b"Obj\x01"


class AvroError(ValueError):
    pass


# --------------------------------------------------------------------------
# schema


class AvroSchema:
    """Parsed schema tree. Nodes are dicts: {"type": ..., ...}."""

    def __init__(self, schema: "str | dict | list"):
        if isinstance(schema, str):
            schema = json.loads(schema)
        self.root = schema
        if self._type_name(schema) != "record":
            raise AvroError("top-level avro schema must be a record")
        self.fields = schema["fields"]

    @staticmethod
    def _type_name(node) -> str:
        if isinstance(node, str):
            return node
        if isinstance(node, list):
            return "union"
        return node["type"]

    def field_names(self) -> list[str]:
        return [f["name"] for f in self.fields]


# --------------------------------------------------------------------------
# binary primitives


def _read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise AvroError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag


def _write_zigzag(out: io.BytesIO, v: int) -> None:
    u = (v << 1) if v >= 0 else (((-v) << 1) - 1)
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise AvroError("truncated bytes")
    return data


# --------------------------------------------------------------------------
# datum codec


def _decode(node, buf: io.BytesIO) -> Any:
    t = node if isinstance(node, str) else node
    if isinstance(t, list):  # union
        idx = _read_long(buf)
        if not 0 <= idx < len(t):
            raise AvroError(f"union index {idx} out of range")
        return _decode(t[idx], buf)
    if isinstance(t, dict):
        logical = t.get("logicalType")
        base = t["type"]
        if base == "record":
            return {f["name"]: _decode(f["type"], buf) for f in t["fields"]}
        if base == "enum":
            idx = _read_long(buf)
            return t["symbols"][idx]
        if base == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    return out
                if n < 0:  # block with byte size
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    out.append(_decode(t["items"], buf))
        if base == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    return out
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode()
                    out[k] = _decode(t["values"], buf)
        if base == "fixed":
            return buf.read(t["size"])
        v = _decode(base, buf)
        if logical == "timestamp-millis":
            return int(v) * 1000
        return v
    if t == "null":
        return None
    if t == "boolean":
        b = buf.read(1)
        return bool(b[0])
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    raise AvroError(f"unsupported avro type {t!r}")


def _encode(node, v, out: io.BytesIO) -> None:
    t = node
    if isinstance(t, list):  # union: pick null vs the other branch
        for i, branch in enumerate(t):
            if (v is None) == (AvroSchema._type_name(branch) == "null"):
                _write_zigzag(out, i)
                _encode(branch, v, out)
                return
        raise AvroError(f"no union branch for value {v!r} in {t}")
    if isinstance(t, dict):
        base = t["type"]
        logical = t.get("logicalType")
        if base == "record":
            for f in t["fields"]:
                _encode(f["type"], v.get(f["name"]), out)
            return
        if base == "enum":
            _write_zigzag(out, t["symbols"].index(v))
            return
        if base == "array":
            if v:
                _write_zigzag(out, len(v))
                for item in v:
                    _encode(t["items"], item, out)
            _write_zigzag(out, 0)
            return
        if base == "map":
            if v:
                _write_zigzag(out, len(v))
                for k, item in v.items():
                    kb = k.encode()
                    _write_zigzag(out, len(kb))
                    out.write(kb)
                    _encode(t["values"], item, out)
            _write_zigzag(out, 0)
            return
        if base == "fixed":
            out.write(v)
            return
        if logical == "timestamp-millis":
            v = int(v) // 1000
        _encode(base, v, out)
        return
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if v else b"\x00")
        return
    if t in ("int", "long"):
        _write_zigzag(out, int(v))
        return
    if t == "float":
        out.write(struct.pack("<f", float(v)))
        return
    if t == "double":
        out.write(struct.pack("<d", float(v)))
        return
    if t == "bytes":
        _write_zigzag(out, len(v))
        out.write(v)
        return
    if t == "string":
        b = str(v).encode("utf-8")
        _write_zigzag(out, len(b))
        out.write(b)
        return
    raise AvroError(f"unsupported avro type {t!r}")


def decode_datum(schema: AvroSchema, data: bytes) -> dict:
    """One bare binary datum -> row dict."""
    return _decode(schema.root, io.BytesIO(data))


def encode_datum(schema: AvroSchema, row: dict) -> bytes:
    out = io.BytesIO()
    _encode(schema.root, row, out)
    return out.getvalue()


# --------------------------------------------------------------------------
# confluent wire format


def decode_confluent(schema: AvroSchema, data: bytes) -> tuple[int, dict]:
    """magic 0x00 + 4-byte BE schema id + datum -> (schema_id, row)."""
    if len(data) < 5 or data[:1] != CONFLUENT_MAGIC:
        raise AvroError("not a confluent-framed avro message")
    schema_id = struct.unpack(">I", data[1:5])[0]
    return schema_id, decode_datum(schema, data[5:])


def encode_confluent(schema: AvroSchema, schema_id: int, row: dict) -> bytes:
    return CONFLUENT_MAGIC + struct.pack(">I", schema_id) + encode_datum(schema, row)


# --------------------------------------------------------------------------
# object container files (the filesystem-connector format)


def read_ocf(data: bytes) -> tuple[AvroSchema, list[dict]]:
    buf = io.BytesIO(data)
    if buf.read(4) != OCF_MAGIC:
        raise AvroError("not an avro object container file")
    meta: dict[str, bytes] = {}
    while True:
        n = _read_long(buf)
        if n == 0:
            break
        if n < 0:
            _read_long(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported OCF codec {codec!r}")
    schema = AvroSchema(meta["avro.schema"].decode())
    sync = buf.read(16)
    rows: list[dict] = []
    while True:
        try:
            count = _read_long(buf)
        except AvroError:
            break  # clean EOF
        size = _read_long(buf)
        block = buf.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        bbuf = io.BytesIO(block)
        for _ in range(count):
            rows.append(_decode(schema.root, bbuf))
        if buf.read(16) != sync:
            raise AvroError("OCF sync marker mismatch")
    return schema, rows


def write_ocf(schema: AvroSchema, rows: list[dict], codec: str = "null") -> bytes:
    out = io.BytesIO()
    out.write(OCF_MAGIC)
    meta = {
        "avro.schema": json.dumps(schema.root).encode(),
        "avro.codec": codec.encode(),
    }
    _write_zigzag(out, len(meta))
    for k, v in meta.items():
        kb = k.encode()
        _write_zigzag(out, len(kb))
        out.write(kb)
        _write_zigzag(out, len(v))
        out.write(v)
    _write_zigzag(out, 0)
    sync = b"arroyo-tpu-sync!"  # deterministic 16-byte marker
    out.write(sync)
    if rows:
        block = io.BytesIO()
        for r in rows:
            _encode(schema.root, r, block)
        payload = block.getvalue()
        if codec == "deflate":
            co = zlib.compressobj(wbits=-15)
            payload = co.compress(payload) + co.flush()
        _write_zigzag(out, len(rows))
        _write_zigzag(out, len(payload))
        out.write(payload)
        out.write(sync)
    return out.getvalue()


def schema_from_table(fields) -> AvroSchema:
    """Build a writer schema from a Schema's (name, dtype) fields."""
    tmap = {
        "int32": "int", "int64": "long", "uint64": "long",
        "float32": "float", "float64": "double", "bool": "boolean",
        "string": ["null", "string"],
        "timestamp": {"type": "long", "logicalType": "timestamp-micros"},
    }
    return AvroSchema({
        "type": "record",
        "name": "Row",
        "fields": [
            {"name": f.name, "type": tmap[f.dtype]}
            for f in fields
            if not f.name.startswith("_")
        ],
    })
