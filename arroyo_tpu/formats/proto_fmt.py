"""Protobuf format via compiled descriptor sets.

Reference: crates/arroyo-formats/src/proto/ (prost-reflect DynamicMessage
decoding against a FileDescriptorSet supplied in the table DDL). Here the
equivalent: the DDL supplies ``proto.descriptor_file`` (output of
``protoc --descriptor_set_out``) and ``proto.message_name``; messages decode
to row dicts through google.protobuf's message factory. Gated on
google.protobuf being importable (it is baked into this image).
"""

from __future__ import annotations

from typing import Optional

from .base import RowBatchingDeserializer


def _load_message_class(descriptor_file: str, message_name: str):
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    with open(descriptor_file, "rb") as f:
        fds = descriptor_pb2.FileDescriptorSet.FromString(f.read())
    pool = descriptor_pool.DescriptorPool()
    for fd in fds.file:
        pool.Add(fd)
    desc = pool.FindMessageTypeByName(message_name)
    return message_factory.GetMessageClass(desc)


def _message_to_row(msg) -> dict:
    row = {}
    for field, value in msg.ListFields():
        if field.is_repeated:
            row[field.name] = [
                _message_to_row(v) if field.message_type else v for v in value
            ]
        elif field.message_type:
            row[field.name] = _message_to_row(value)
        else:
            row[field.name] = value
    # include unset scalar fields with their defaults so columns stay dense
    for field in msg.DESCRIPTOR.fields:
        if field.name not in row and not field.message_type and \
                not field.is_repeated:
            row[field.name] = field.default_value
    return row


class ProtoDeserializer(RowBatchingDeserializer):
    def __init__(self, *args, descriptor_file: str, message_name: str,
                 confluent_wire_format: bool = False, **kw):
        super().__init__(*args, **kw)
        self.msg_class = _load_message_class(descriptor_file, message_name)
        self.confluent = confluent_wire_format

    def _decode(self, payload) -> list[dict]:
        data = payload if isinstance(payload, bytes) else str(payload).encode()
        if self.confluent:
            # magic byte + 4-byte schema id + message-indexes varint(s)
            if len(data) < 6 or data[:1] != b"\x00":
                raise ValueError("not a confluent-framed protobuf message")
            # single top-level message => indexes encoded as one 0 byte
            data = data[5:]
            if data[:1] == b"\x00":
                data = data[1:]
        msg = self.msg_class.FromString(data)
        return [_message_to_row(msg)]


def _assign_field(msg, field, value) -> None:
    if field.is_repeated:
        target = getattr(msg, field.name)
        for item in value:
            if field.message_type:
                _fill_message(target.add(), item)
            else:
                target.append(item)
    elif field.message_type:
        _fill_message(getattr(msg, field.name), value)
    else:
        setattr(msg, field.name, value)


def _fill_message(msg, row: dict) -> None:
    by_name = {f.name: f for f in msg.DESCRIPTOR.fields}
    for k, v in row.items():
        if v is None or k.startswith("_"):
            continue
        field = by_name.get(k)
        if field is None:
            raise ValueError(
                f"row column {k!r} has no field on {msg.DESCRIPTOR.full_name}"
            )
        _assign_field(msg, field, v)


def encode_rows(descriptor_file: str, message_name: str, rows: list[dict]) -> list[bytes]:
    cls = _load_message_class(descriptor_file, message_name)
    out = []
    for r in rows:
        m = cls()
        _fill_message(m, r)
        out.append(m.SerializeToString())
    return out
