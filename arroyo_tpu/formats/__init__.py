"""Formats: bytes <-> columnar batches (reference crates/arroyo-formats).

JSON (structured/unstructured/debezium), Avro (bare datums, Confluent wire
format, object container files), Protobuf (descriptor sets), raw
string/bytes; newline/length framing; BadData::{Drop,Fail} policy; Confluent
schema-registry resolver.
"""

from .base import BadDataError, RowBatchingDeserializer, rows_to_batch
from .framing import frame_iter, frame_join
from .json_fmt import (
    JsonDeserializer,
    format_iso_micros,
    parse_iso_micros,
    serialize_json_lines,
)
from .registry import (
    AvroDeserializer,
    DebeziumJsonDeserializer,
    default_framing,
    make_deserializer,
    serialize_batch,
)

__all__ = [
    "BadDataError",
    "RowBatchingDeserializer",
    "rows_to_batch",
    "frame_iter",
    "frame_join",
    "JsonDeserializer",
    "format_iso_micros",
    "parse_iso_micros",
    "serialize_json_lines",
    "AvroDeserializer",
    "DebeziumJsonDeserializer",
    "default_framing",
    "make_deserializer",
    "serialize_batch",
]
