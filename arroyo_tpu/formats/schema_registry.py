"""Confluent schema-registry resolver.

Reference: arroyo-rpc/src/schema_resolver.rs (ConfluentSchemaRegistry —
fetch/register subject schemas over the REST API). HTTP client uses urllib;
an in-memory registry backs tests and air-gapped runs.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Optional


class SchemaRegistryError(RuntimeError):
    pass


class ConfluentSchemaRegistry:
    """Minimal client for the Confluent REST API (subjects/ids endpoints)."""

    def __init__(self, endpoint: str, api_key: Optional[str] = None,
                 api_secret: Optional[str] = None, timeout: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self._auth = None
        if api_key:
            import base64

            token = base64.b64encode(f"{api_key}:{api_secret or ''}".encode()).decode()
            self._auth = f"Basic {token}"
        self._by_id: dict[int, str] = {}

    def _get(self, path: str) -> dict:
        req = urllib.request.Request(self.endpoint + path)
        if self._auth:
            req.add_header("Authorization", self._auth)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except Exception as e:  # noqa: BLE001
            raise SchemaRegistryError(f"schema registry GET {path} failed: {e}") from e

    def get_schema_by_id(self, schema_id: int) -> str:
        if schema_id not in self._by_id:
            self._by_id[schema_id] = self._get(f"/schemas/ids/{schema_id}")["schema"]
        return self._by_id[schema_id]

    def get_latest(self, subject: str) -> tuple[int, str]:
        d = self._get(f"/subjects/{subject}/versions/latest")
        return int(d["id"]), d["schema"]

    def register(self, subject: str, schema: str) -> int:
        body = json.dumps({"schema": schema}).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/subjects/{subject}/versions", data=body, method="POST",
            headers={"Content-Type": "application/vnd.schemaregistry.v1+json"},
        )
        if self._auth:
            req.add_header("Authorization", self._auth)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return int(json.loads(resp.read())["id"])
        except Exception as e:  # noqa: BLE001
            raise SchemaRegistryError(f"schema registry register failed: {e}") from e


class InMemorySchemaRegistry:
    """Test/air-gapped stand-in with the same surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._schemas: dict[int, str] = {}
        self._subjects: dict[str, list[int]] = {}
        self._next = 1

    def register(self, subject: str, schema: str) -> int:
        with self._lock:
            for sid, s in self._schemas.items():
                if s == schema:
                    self._subjects.setdefault(subject, []).append(sid)
                    return sid
            sid = self._next
            self._next += 1
            self._schemas[sid] = schema
            self._subjects.setdefault(subject, []).append(sid)
            return sid

    def get_schema_by_id(self, schema_id: int) -> str:
        with self._lock:
            if schema_id not in self._schemas:
                raise SchemaRegistryError(f"no schema with id {schema_id}")
            return self._schemas[schema_id]

    def get_latest(self, subject: str) -> tuple[int, str]:
        with self._lock:
            ids = self._subjects.get(subject)
            if not ids:
                raise SchemaRegistryError(f"no subject {subject}")
            return ids[-1], self._schemas[ids[-1]]
