"""Format registry: table DDL options -> deserializer / serializer.

Reference: Format enum dispatch (arroyo-rpc/src/formats.rs:37-162) used by
ArrowDeserializer::new. Options recognized (from CREATE TABLE ... WITH):
  format = 'json' | 'debezium_json' | 'avro' | 'protobuf' | 'raw_string' |
           'raw_bytes'
  framing = 'newline' | 'length'          (default: per-connector)
  bad_data = 'fail' | 'drop'
  'json.unstructured' = true
  'avro.schema' = '<json schema>'         (reader/writer schema)
  'avro.confluent_schema_registry' = true (magic byte + schema id framing)
  'proto.descriptor_file', 'proto.message_name'
"""

from __future__ import annotations

from typing import Optional

from ..batch import Schema
from .avro_fmt import AvroSchema, decode_confluent, decode_datum
from .base import RowBatchingDeserializer
from .json_fmt import JsonDeserializer
from .proto_fmt import ProtoDeserializer
from .raw_fmt import RawBytesDeserializer, RawStringDeserializer


class AvroDeserializer(RowBatchingDeserializer):
    def __init__(self, *args, avro_schema: AvroSchema,
                 confluent_wire_format: bool = False, **kw):
        super().__init__(*args, **kw)
        self.avro_schema = avro_schema
        self.confluent = confluent_wire_format

    def _decode(self, payload) -> list[dict]:
        data = payload if isinstance(payload, bytes) else str(payload).encode("latin-1")
        if self.confluent:
            _sid, row = decode_confluent(self.avro_schema, data)
            return [row]
        return [decode_datum(self.avro_schema, data)]


class DebeziumJsonDeserializer(JsonDeserializer):
    """Debezium envelopes -> updating rows with _is_retract
    (reference formats.rs Json{debezium}; de.rs debezium handling)."""

    def _decode(self, payload) -> list[dict]:
        import json as _json

        obj = _json.loads(payload)
        payload_obj = obj.get("payload", obj)
        op = payload_obj.get("op")
        before = payload_obj.get("before")
        after = payload_obj.get("after")
        rows = []
        if op in ("c", "r"):
            rows.append(dict(after, _is_retract=False))
        elif op == "d":
            rows.append(dict(before, _is_retract=True))
        elif op == "u":
            if before is not None:
                rows.append(dict(before, _is_retract=True))
            rows.append(dict(after, _is_retract=False))
        else:
            raise ValueError(f"unknown debezium op {op!r}")
        return rows


def make_deserializer(cfg: dict, schema: Schema,
                      task_info=None) -> RowBatchingDeserializer:
    """Build the configured deserializer for a source node config.

    ``task_info`` (types.TaskInfo) attributes dropped records to a
    job/operator for the ``arroyo_bad_records_total`` counter and the
    throttled ``BAD_DATA_DROPPED`` event; without it drops are only
    counted on the deserializer itself."""
    from ..config import config

    fmt = str(cfg.get("format", "json"))
    common = dict(
        schema=schema,
        batch_size=config().get("pipeline.source-batch-size"),
        linger_micros=config().get("pipeline.source-batch-linger-ms", 100) * 1000,
        bad_data=str(cfg.get("bad_data", "fail")),
        event_time_field=cfg.get("event_time_field"),
        task_info=task_info,
    )
    if fmt == "json":
        return JsonDeserializer(
            **common, unstructured=bool(cfg.get("json.unstructured", False))
        )
    if fmt == "debezium_json":
        return DebeziumJsonDeserializer(**common)
    if fmt == "avro":
        raw = cfg.get("avro.schema")
        if not raw:
            raise ValueError("avro format requires the 'avro.schema' option")
        return AvroDeserializer(
            **common,
            avro_schema=AvroSchema(raw),
            confluent_wire_format=bool(cfg.get("avro.confluent_schema_registry", False)),
        )
    if fmt == "protobuf":
        df = cfg.get("proto.descriptor_file")
        mn = cfg.get("proto.message_name")
        if not df or not mn:
            raise ValueError(
                "protobuf format requires 'proto.descriptor_file' and "
                "'proto.message_name' options"
            )
        return ProtoDeserializer(
            **common, descriptor_file=str(df), message_name=str(mn),
            confluent_wire_format=bool(cfg.get("proto.confluent_schema_registry", False)),
        )
    if fmt == "raw_string":
        return RawStringDeserializer(**common)
    if fmt == "raw_bytes":
        return RawBytesDeserializer(**common)
    raise ValueError(f"unknown format {fmt!r}")


def default_framing(cfg: dict) -> Optional[str]:
    v = cfg.get("framing")
    return str(v) if v else None


def serialize_batch(cfg: dict, batch, schema: Optional[Schema]) -> list[bytes]:
    """Sink-side: batch -> encoded messages for the configured format."""
    fmt = str(cfg.get("format", "json"))
    if fmt in ("json", "debezium_json"):
        from .json_fmt import serialize_json_lines

        return [l.encode() for l in serialize_json_lines(batch, schema)]
    if fmt == "avro":
        from .avro_fmt import encode_datum, schema_from_table

        raw = cfg.get("avro.schema")
        asch = AvroSchema(raw) if raw else schema_from_table(schema.fields)
        names = [f["name"] for f in asch.fields]
        rows = batch.to_pylist()
        return [encode_datum(asch, {n: r.get(n) for n in names}) for r in rows]
    if fmt == "protobuf":
        from .proto_fmt import encode_rows

        return encode_rows(
            str(cfg["proto.descriptor_file"]), str(cfg["proto.message_name"]),
            batch.to_pylist(),
        )
    if fmt == "raw_string":
        from .raw_fmt import serialize_raw_string

        return serialize_raw_string(batch)
    if fmt == "raw_bytes":
        col = batch["value"]
        return [v if isinstance(v, bytes) else str(v).encode() for v in col]
    raise ValueError(f"unknown sink format {fmt!r}")
