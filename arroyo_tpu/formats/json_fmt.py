"""JSON format: bytes/lines <-> columnar batches.

Equivalent of the reference's JSON path in crates/arroyo-formats
(de.rs:249 ArrowDeserializer with batch-size/linger flush; ser.rs for sinks).
Incremental column builders with a should_flush policy mirroring
``pipeline.source-batch-size`` / ``source-batch-linger`` (de.rs:498).
"""

from __future__ import annotations

import json
import time
from typing import Iterable, Optional

import numpy as np

from ..batch import STRING, TIMESTAMP_FIELD, Batch, Field, Schema


class BadDataError(ValueError):
    pass


class JsonDeserializer:
    """Accumulates JSON objects into columns, flushing by size/linger
    (reference de.rs:402,498). bad_data: "fail" | "drop"."""

    def __init__(
        self,
        schema: Schema,
        batch_size: int = 512,
        linger_micros: int = 100_000,
        bad_data: str = "fail",
        event_time_field: Optional[str] = None,
    ):
        self.schema = schema
        self.batch_size = batch_size
        self.linger_micros = linger_micros
        self.bad_data = bad_data
        self.event_time_field = event_time_field
        self._rows: list[dict] = []
        self._first_buffer_time: Optional[float] = None
        self.errors = 0

    def deserialize(self, line: str | bytes, timestamp_micros: Optional[int] = None) -> None:
        try:
            obj = json.loads(line)
            if not isinstance(obj, dict):
                raise BadDataError(f"expected JSON object, got {type(obj)}")
        except Exception:
            if self.bad_data == "drop":
                self.errors += 1
                return
            raise
        if timestamp_micros is not None:
            obj.setdefault(TIMESTAMP_FIELD, timestamp_micros)
        if self._first_buffer_time is None:
            self._first_buffer_time = time.monotonic()
        self._rows.append(obj)

    def should_flush(self) -> bool:
        if len(self._rows) >= self.batch_size:
            return True
        return (
            bool(self._rows)
            and self._first_buffer_time is not None
            and (time.monotonic() - self._first_buffer_time) * 1e6 >= self.linger_micros
        )

    def flush(self) -> Optional[Batch]:
        if not self._rows:
            return None
        rows, self._rows = self._rows, []
        self._first_buffer_time = None
        cols: dict[str, np.ndarray] = {}
        for f in self.schema.fields:
            if f.name == TIMESTAMP_FIELD:
                continue
            vals = [r.get(f.name) for r in rows]
            if f.dtype == STRING:
                cols[f.name] = np.array(
                    [None if v is None else str(v) for v in vals], dtype=object
                )
            elif f.dtype in ("float32", "float64"):
                cols[f.name] = np.array(
                    [np.nan if v is None else float(v) for v in vals], dtype=f.numpy_dtype()
                )
            elif f.dtype == "bool":
                cols[f.name] = np.array([bool(v) for v in vals], dtype=np.bool_)
            else:
                cols[f.name] = np.array(
                    [0 if v is None else int(v) for v in vals], dtype=f.numpy_dtype()
                )
        if self.event_time_field:
            cols[TIMESTAMP_FIELD] = np.asarray(cols[self.event_time_field]).astype(np.int64)
        else:
            now = int(time.time() * 1e6)
            ts = [r.get(TIMESTAMP_FIELD, now) for r in rows]
            cols[TIMESTAMP_FIELD] = np.array(ts, dtype=np.int64)
        return Batch(cols)


def serialize_json_lines(batch: Batch, include_internal: bool = False) -> list[str]:
    names = [
        n
        for n in batch.columns
        if include_internal or not n.startswith("_")
    ]
    cols = [batch.columns[n] for n in names]
    out = []
    for i in range(batch.num_rows):
        obj = {}
        for n, c in zip(names, cols):
            v = c[i]
            if isinstance(v, np.generic):
                v = v.item()
            if isinstance(v, float) and v != v:  # NaN -> null
                v = None
            obj[n] = v
        out.append(json.dumps(obj, separators=(",", ":"), default=str))
    return out
