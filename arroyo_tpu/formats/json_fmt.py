"""JSON format: bytes/lines <-> columnar batches.

Equivalent of the reference's JSON path in crates/arroyo-formats
(de.rs:249 ArrowDeserializer with batch-size/linger flush; ser.rs for sinks).
Incremental column builders with a should_flush policy mirroring
``pipeline.source-batch-size`` / ``source-batch-linger`` (de.rs:498).
"""

from __future__ import annotations

import json
import time
from typing import Iterable, Optional

import numpy as np

from ..batch import STRING, TIMESTAMP_FIELD, Batch, Field, Schema

IS_RETRACT_FIELD = "_is_retract"


class BadDataError(ValueError):
    pass


def parse_iso_micros(v) -> int:
    """ISO-8601 datetime (or epoch-micros int) -> int64 micros since epoch."""
    if isinstance(v, (int, float)):
        return int(v)
    from datetime import datetime, timezone

    s = str(v)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * 1_000_000)


def format_iso_micros(us: int) -> str:
    """int64 micros -> naive-UTC ISO string; fraction printed at millisecond
    precision when it is whole millis, microseconds otherwise, omitted when
    zero (matches arrow's display of timestamp columns)."""
    from datetime import datetime, timezone

    us = int(us)
    dt = datetime.fromtimestamp(us // 1_000_000, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    frac = us % 1_000_000
    if frac == 0:
        return base
    if frac % 1000 == 0:
        return f"{base}.{frac // 1000:03d}"
    return f"{base}.{frac:06d}"


class JsonDeserializer:
    """Accumulates JSON objects into columns, flushing by size/linger
    (reference de.rs:402,498). bad_data: "fail" | "drop"."""

    def __init__(
        self,
        schema: Schema,
        batch_size: int = 512,
        linger_micros: int = 100_000,
        bad_data: str = "fail",
        event_time_field: Optional[str] = None,
    ):
        self.schema = schema
        self.batch_size = batch_size
        self.linger_micros = linger_micros
        self.bad_data = bad_data
        self.event_time_field = event_time_field
        self._rows: list[dict] = []
        self._first_buffer_time: Optional[float] = None
        self.errors = 0

    def deserialize(self, line: str | bytes, timestamp_micros: Optional[int] = None) -> None:
        try:
            obj = json.loads(line)
            if not isinstance(obj, dict):
                raise BadDataError(f"expected JSON object, got {type(obj)}")
        except Exception:
            if self.bad_data == "drop":
                self.errors += 1
                return
            raise
        if timestamp_micros is not None:
            obj.setdefault(TIMESTAMP_FIELD, timestamp_micros)
        if self._first_buffer_time is None:
            self._first_buffer_time = time.monotonic()
        self._rows.append(obj)

    def should_flush(self) -> bool:
        if len(self._rows) >= self.batch_size:
            return True
        return (
            bool(self._rows)
            and self._first_buffer_time is not None
            and (time.monotonic() - self._first_buffer_time) * 1e6 >= self.linger_micros
        )

    def flush(self) -> Optional[Batch]:
        if not self._rows:
            return None
        rows, self._rows = self._rows, []
        self._first_buffer_time = None
        cols: dict[str, np.ndarray] = {}
        for f in self.schema.fields:
            if f.name == TIMESTAMP_FIELD:
                continue
            vals = [r.get(f.name) for r in rows]
            if f.dtype == "timestamp":
                cols[f.name] = np.array(
                    [0 if v is None else parse_iso_micros(v) for v in vals], dtype=np.int64
                )
            elif f.dtype == STRING:
                cols[f.name] = np.array(
                    [None if v is None else str(v) for v in vals], dtype=object
                )
            elif f.dtype in ("float32", "float64"):
                cols[f.name] = np.array(
                    [np.nan if v is None else float(v) for v in vals], dtype=f.numpy_dtype()
                )
            elif f.dtype == "bool":
                cols[f.name] = np.array([bool(v) for v in vals], dtype=np.bool_)
            else:
                cols[f.name] = np.array(
                    [0 if v is None else int(v) for v in vals], dtype=f.numpy_dtype()
                )
        if self.event_time_field:
            cols[TIMESTAMP_FIELD] = np.asarray(cols[self.event_time_field]).astype(np.int64)
        else:
            now = int(time.time() * 1e6)
            ts = [r.get(TIMESTAMP_FIELD, now) for r in rows]
            cols[TIMESTAMP_FIELD] = np.array(ts, dtype=np.int64)
        return Batch(cols)


def serialize_json_lines(
    batch: Batch, schema: Optional[Schema] = None, include_internal: bool = False
) -> list[str]:
    """Batch -> JSON lines. With a schema, timestamp columns format as ISO
    strings. Updating batches (_is_retract present) serialize as Debezium
    envelopes {"before","after","op"} (reference ser.rs debezium path)."""
    names = [
        n
        for n in batch.columns
        if (include_internal or not n.startswith("_")) and n != IS_RETRACT_FIELD
    ]
    ts_fields = set()
    if schema is not None:
        ts_fields = {f.name for f in schema.fields if f.dtype == "timestamp"}
    cols = [batch.columns[n] for n in names]
    retracts = (
        np.asarray(batch.columns[IS_RETRACT_FIELD], dtype=bool)
        if IS_RETRACT_FIELD in batch.columns
        else None
    )
    out = []
    for i in range(batch.num_rows):
        obj = {}
        for n, c in zip(names, cols):
            v = c[i]
            if isinstance(v, np.generic):
                v = v.item()
            if isinstance(v, float) and v != v:  # NaN -> null
                v = None
            if n in ts_fields and v is not None:
                v = format_iso_micros(v)
            obj[n] = v
        if retracts is not None:
            if retracts[i]:
                obj = {"before": obj, "after": None, "op": "d"}
            else:
                obj = {"before": None, "after": obj, "op": "c"}
        out.append(json.dumps(obj, separators=(",", ":"), default=str))
    return out
