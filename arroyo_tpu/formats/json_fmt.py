"""JSON format: bytes/lines <-> columnar batches.

Equivalent of the reference's JSON path in crates/arroyo-formats
(de.rs:249 ArrowDeserializer with batch-size/linger flush; ser.rs for sinks).
Incremental column builders with a should_flush policy mirroring
``pipeline.source-batch-size`` / ``source-batch-linger`` (de.rs:498).
"""

from __future__ import annotations

import json
import time
from typing import Iterable, Optional

import numpy as np

from ..batch import STRING, TIMESTAMP_FIELD, Batch, Field, Schema
from .base import BadDataError, RowBatchingDeserializer

IS_RETRACT_FIELD = "_is_retract"


def parse_iso_micros(v) -> int:
    """ISO-8601 datetime (or epoch-micros int) -> int64 micros since epoch."""
    if isinstance(v, (int, float)):
        return int(v)
    from datetime import datetime, timezone

    s = str(v)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * 1_000_000)


def format_iso_micros(us: int) -> str:
    """int64 micros -> naive-UTC ISO string; fraction printed at millisecond
    precision when it is whole millis, microseconds otherwise, omitted when
    zero (matches arrow's display of timestamp columns)."""
    from datetime import datetime, timezone

    us = int(us)
    dt = datetime.fromtimestamp(us // 1_000_000, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    frac = us % 1_000_000
    if frac == 0:
        return base
    if frac % 1000 == 0:
        return f"{base}.{frac // 1000:03d}"
    return f"{base}.{frac:06d}"


class JsonDeserializer(RowBatchingDeserializer):
    """Accumulates JSON objects into columns, flushing by size/linger
    (reference de.rs:402,498). bad_data: "fail" | "drop".
    ``unstructured=True`` puts the raw text into a single "value" column
    (reference Json{unstructured} formats.rs)."""

    def __init__(self, *args, unstructured: bool = False, **kw):
        super().__init__(*args, **kw)
        self.unstructured = unstructured

    def _decode(self, payload) -> list[dict]:
        if self.unstructured:
            text = payload.decode() if isinstance(payload, bytes) else str(payload)
            return [{"value": text}]
        obj = json.loads(payload)
        if not isinstance(obj, dict):
            raise BadDataError(f"expected JSON object, got {type(obj)}")
        return [obj]


def serialize_json_lines(
    batch: Batch, schema: Optional[Schema] = None, include_internal: bool = False
) -> list[str]:
    """Batch -> JSON lines. With a schema, timestamp columns format as ISO
    strings. Updating batches (_is_retract present) serialize as Debezium
    envelopes {"before","after","op"} (reference ser.rs debezium path)."""
    names = [
        n
        for n in batch.columns
        if (include_internal or not n.startswith("_")) and n != IS_RETRACT_FIELD
    ]
    ts_fields = set()
    if schema is not None:
        ts_fields = {f.name for f in schema.fields if f.dtype == "timestamp"}
    cols = [batch.columns[n] for n in names]
    retracts = (
        np.asarray(batch.columns[IS_RETRACT_FIELD], dtype=bool)
        if IS_RETRACT_FIELD in batch.columns
        else None
    )
    out = []
    for i in range(batch.num_rows):
        obj = {}
        for n, c in zip(names, cols):
            v = c[i]
            if isinstance(v, np.generic):
                v = v.item()
            if isinstance(v, float) and v != v:  # NaN -> null
                v = None
            if n in ts_fields and v is not None:
                v = format_iso_micros(v)
            obj[n] = v
        if retracts is not None:
            if retracts[i]:
                obj = {"before": obj, "after": None, "op": "d"}
            else:
                obj = {"before": None, "after": obj, "op": "c"}
        out.append(json.dumps(obj, separators=(",", ":"), default=str))
    return out
