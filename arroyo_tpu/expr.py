"""Scalar expression engine.

Replaces the reference's DataFusion physical-expression evaluation (the
deserialized exec plans of crates/arroyo-planner/src/physical.rs) with a small
AST that evaluates two ways:

  - ``eval_np(cols, n)``  — vectorized NumPy on host batches (sources, formats,
    watermark generators, key calculation).
  - ``eval_jnp(cols)``    — jax.numpy under ``jit``; used inside the device
    window/aggregate step functions so projections and filters fuse with the
    XLA reduction kernels (XLA op fusion plays the role of the
    reference's operator chaining for expressions).

The SQL planner (arroyo_tpu.sql) compiles parsed SQL scalar expressions into
these nodes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import numpy as np


class Expr:
    """Base scalar expression node."""

    def eval_np(self, cols: dict[str, np.ndarray], n: int):
        raise NotImplementedError

    def eval_jnp(self, cols: dict[str, Any]):
        raise NotImplementedError

    def columns(self) -> set[str]:
        """Set of input column names referenced."""
        raise NotImplementedError


@dataclass(frozen=True)
class Col(Expr):
    name: str

    def eval_np(self, cols, n):
        return cols[self.name]

    def eval_jnp(self, cols):
        return cols[self.name]

    def columns(self):
        return {self.name}

    def __repr__(self):
        return f"Col({self.name})"


@dataclass(frozen=True)
class Lit(Expr):
    value: Any  # python scalar (int/float/str/bool/None)

    def eval_np(self, cols, n):
        return self.value

    def eval_jnp(self, cols):
        return self.value

    def columns(self):
        return set()

    def __repr__(self):
        return f"Lit({self.value!r})"


_NP_BINOPS: dict[str, Callable] = {
    "+": np.add,
    "-": np.subtract,
    "*": np.multiply,
    "/": np.divide,
    "%": np.mod,
    "==": np.equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
    "and": np.logical_and,
    "or": np.logical_or,
}


def _div(a, b):
    # SQL integer division truncates toward zero; numpy // floors.
    if _is_integer(a) and _is_integer(b):
        q = np.floor_divide(a, b)
        # nonnegative operands (the hot case: event-time micros / positive
        # window literals): floor == trunc, skip the 4-pass correction
        a_nonneg = (a.size == 0 or np.min(a) >= 0) if np.ndim(a) else a >= 0
        b_nonneg = (b.size == 0 or np.min(b) >= 0) if np.ndim(b) else b >= 0
        if a_nonneg and b_nonneg:
            return q
        r = np.mod(a, b)
        # correct floor -> trunc for mixed signs
        adjust = (r != 0) & ((np.sign(a if np.ndim(a) else np.asarray(a)) < 0) != (np.sign(b if np.ndim(b) else np.asarray(b)) < 0))
        return q + adjust
    return np.divide(a, b)


def _is_integer(x) -> bool:
    if isinstance(x, (bool, np.bool_)):
        return False
    if isinstance(x, (int, np.integer)):
        return True
    return hasattr(x, "dtype") and x.dtype.kind in "iu"


def _div_jnp(a, b):
    """SQL division on device: truncating for integers (lax.div), true
    division otherwise — matches the numpy path's _div semantics."""
    import jax.numpy as jnp
    from jax import lax

    if _is_integer(a) and _is_integer(b):
        a, b = jnp.asarray(a), jnp.asarray(b)
        common = jnp.promote_types(a.dtype, b.dtype)
        return lax.div(a.astype(common), b.astype(common))
    return jnp.divide(a, b)


def _mod_jnp(a, b):
    """Modulo whose traced result is byte-exact with np.mod: when the
    remainder is an exact zero, numpy gives it the DIVISOR's sign while
    XLA keeps the dividend's — patch the measure-zero cells (the parity
    oracle in tests/test_trace_audit.py caught this; nonzero results and
    NaN propagation are untouched)."""
    import jax.numpy as jnp

    r = jnp.mod(a, b)
    if jnp.issubdtype(jnp.asarray(r).dtype, jnp.floating):
        r = jnp.where(r == 0,
                      jnp.copysign(jnp.zeros_like(r), jnp.asarray(b)), r)
    return r


@dataclass(frozen=True)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def eval_np(self, cols, n):
        l = self.left.eval_np(cols, n)
        r = self.right.eval_np(cols, n)
        if self.op == "/":
            return _div(l, r)
        if self.op in ("==", "!=", "<", "<=", ">", ">=") and (
                _is_str(l) or _is_str(r)):
            # object operands (strings / outer-join null padding): SQL
            # three-valued logic — a NULL on either side compares as
            # unknown (NULL), for EVERY comparison op. Projections carry
            # the NULL through to the sink; filter sites coerce with
            # np.asarray(..., dtype=bool), where None lands as False, so
            # WHERE keeps its reject-unknown semantics.
            lo, ro = _as_obj(l, n), _as_obj(r, n)
            null = _null_mask(lo) | _null_mask(ro)
            if null.any():
                out = np.empty(n, dtype=object)
                out[:] = None
                ok = ~null
                if ok.any():
                    fn = _NP_BINOPS[self.op]
                    out[ok] = np.array(
                        [bool(fn(a, b)) for a, b in zip(lo[ok], ro[ok])],
                        dtype=object)
                return out
            l, r = lo, ro
        return _NP_BINOPS[self.op](l, r)

    def eval_jnp(self, cols):
        import jax.numpy as jnp

        l = self.left.eval_jnp(cols)
        r = self.right.eval_jnp(cols)
        return {
            "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
            "/": _div_jnp,
            "%": _mod_jnp,
            "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
            "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
            ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
            "and": jnp.logical_and, "or": jnp.logical_or,
        }[self.op](l, r)

    def columns(self):
        return self.left.columns() | self.right.columns()


def _is_str(x) -> bool:
    return isinstance(x, str) or (hasattr(x, "dtype") and x.dtype == object)


def _as_obj(x, n):
    if isinstance(x, str) or not hasattr(x, "dtype"):
        return np.full(n, x, dtype=object)
    return x


@dataclass(frozen=True)
class Not(Expr):
    inner: Expr

    def eval_np(self, cols, n):
        v = self.inner.eval_np(cols, n)
        if hasattr(v, "dtype") and v.dtype == object:
            # three-valued logic: NOT NULL is NULL, not True
            out = np.empty(len(v), dtype=object)
            out[:] = [None if x is None else not x for x in v]
            return out
        return np.logical_not(v)

    def eval_jnp(self, cols):
        import jax.numpy as jnp

        return jnp.logical_not(self.inner.eval_jnp(cols))

    def columns(self):
        return self.inner.columns()


@dataclass(frozen=True)
class Neg(Expr):
    inner: Expr

    def eval_np(self, cols, n):
        return np.negative(self.inner.eval_np(cols, n))

    def eval_jnp(self, cols):
        return -self.inner.eval_jnp(cols)

    def columns(self):
        return self.inner.columns()


@dataclass(frozen=True)
class Cast(Expr):
    inner: Expr
    dtype: str  # Schema dtype string

    def eval_np(self, cols, n):
        v = self.inner.eval_np(cols, n)
        if self.dtype == "string":
            v = np.asarray(v) if hasattr(v, "dtype") else np.full(n, v)
            # CAST(NULL AS TEXT) is NULL, not 'None'
            return np.array([None if x is None else str(x) for x in v],
                            dtype=object)
        target = {"int32": np.int32, "int64": np.int64, "uint64": np.uint64,
                  "float32": np.float32, "float64": np.float64, "bool": np.bool_}[self.dtype]
        if hasattr(v, "dtype") and v.dtype == object:
            conv = float if target in (np.float32, np.float64) else int
            vals = [None if x is None else conv(x) for x in v]
            if any(x is None for x in vals):
                # nulls survive the cast (outer-join padding): stay object
                out = np.empty(len(vals), dtype=object)
                out[:] = vals
                return out
            return np.array(vals, dtype=target)
        return np.asarray(v).astype(target) if hasattr(v, "dtype") else target(v)

    def eval_jnp(self, cols):
        import jax.numpy as jnp

        v = self.inner.eval_jnp(cols)
        target = {"int32": jnp.int32, "int64": jnp.int64, "uint64": jnp.uint64,
                  "float32": jnp.float32, "float64": jnp.float64, "bool": jnp.bool_}[self.dtype]
        return jnp.asarray(v).astype(target)

    def columns(self):
        return self.inner.columns()


@dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN v1 [WHEN ...] ELSE velse END."""

    branches: tuple[tuple[Expr, Expr], ...]
    otherwise: Optional[Expr]

    def eval_np(self, cols, n):
        result = None
        assigned = np.zeros(n, dtype=bool)
        for cond, val in self.branches:
            # conditions may be three-valued (object arrays with None from
            # NULL comparisons): CASE WHEN NULL takes the branch not
            c = np.broadcast_to(
                np.asarray(cond.eval_np(cols, n), dtype=bool), (n,))
            v = val.eval_np(cols, n)
            v = np.broadcast_to(np.asarray(v), (n,)) if not _is_scalar(v) or True else v
            sel = c & ~assigned
            if result is None:
                result = np.array(v, copy=True) if hasattr(v, "dtype") else np.full(n, v)
            result = np.where(sel, v, result)
            assigned |= c
        if self.otherwise is not None:
            v = self.otherwise.eval_np(cols, n)
            v = np.broadcast_to(np.asarray(v), (n,))
            result = np.where(~assigned, v, result) if result is not None else v
        return result

    def eval_jnp(self, cols):
        import jax.numpy as jnp

        result = self.otherwise.eval_jnp(cols) if self.otherwise is not None else jnp.nan
        for cond, val in reversed(self.branches):
            result = jnp.where(cond.eval_jnp(cols), val.eval_jnp(cols), result)
        return result

    def columns(self):
        out = set()
        for c, v in self.branches:
            out |= c.columns() | v.columns()
        if self.otherwise:
            out |= self.otherwise.columns()
        return out


def _is_scalar(v):
    return not hasattr(v, "shape") or v.shape == ()


def _np_concat(args, n):
    parts = [_as_obj(a if _is_str(a) else np.asarray(a), n) for a in args]
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = "".join(str(p[i]) for p in parts)
    return out


@dataclass(frozen=True)
class Func(Expr):
    """Scalar function call."""

    name: str  # lowercase
    args: tuple[Expr, ...]

    def eval_np(self, cols, n):
        a = [arg.eval_np(cols, n) for arg in self.args]
        name = self.name
        if name == "abs":
            return np.abs(a[0])
        if name == "round":
            return np.round(a[0], int(a[1]) if len(a) > 1 else 0)
        if name == "floor":
            return np.floor(a[0])
        if name == "ceil":
            return np.ceil(a[0])
        if name == "sqrt":
            return np.sqrt(a[0])
        if name == "power":
            return np.power(a[0], a[1])
        if name == "ln":
            return np.log(a[0])
        if name == "log10":
            return np.log10(a[0])
        if name == "exp":
            return np.exp(a[0])
        if name == "coalesce":
            out = _as_obj(a[0], n).copy() if _is_str(a[0]) else np.array(np.broadcast_to(np.asarray(a[0]), (n,)), copy=True)
            for alt in a[1:]:
                isnull = _null_mask(out)
                alt_b = np.broadcast_to(np.asarray(alt), (n,))
                out = np.where(isnull, alt_b, out)
            return out
        if name == "concat":
            return _np_concat(a, n)
        if name == "lower":
            return np.array([s.lower() if s is not None else None for s in _as_obj(a[0], n)], dtype=object)
        if name == "upper":
            return np.array([s.upper() if s is not None else None for s in _as_obj(a[0], n)], dtype=object)
        if name in ("length", "char_length", "character_length"):
            return np.array([len(s) if s is not None else 0 for s in _as_obj(a[0], n)], dtype=np.int64)
        if name == "substring" or name == "substr":
            start = np.broadcast_to(np.asarray(a[1]), (n,))
            if len(a) > 2:
                ln = np.broadcast_to(np.asarray(a[2]), (n,))
                return np.array([s[max(int(st) - 1, 0):max(int(st) - 1, 0) + int(l)] if s is not None else None
                                 for s, st, l in zip(_as_obj(a[0], n), start, ln)], dtype=object)
            return np.array([s[max(int(st) - 1, 0):] if s is not None else None
                             for s, st in zip(_as_obj(a[0], n), start)], dtype=object)
        if name == "md5":
            import hashlib as _h
            return np.array([_h.md5(str(s).encode()).hexdigest() for s in _as_obj(a[0], n)], dtype=object)
        if name == "hash":
            from .hashing import hash_columns
            return hash_columns([np.broadcast_to(np.asarray(x), (n,)) for x in a])
        if name == "extract_epoch":  # seconds since epoch from micros timestamp
            return np.asarray(a[0]) // 1_000_000
        if name == "date_trunc_micros":  # (granularity_micros, ts)
            g = int(a[0]) if _is_scalar(a[0]) else a[0]
            return (np.asarray(a[1]) // g) * g
        if name == "to_timestamp_micros":
            return np.asarray(a[0]).astype(np.int64)
        if name == "is_null":
            return _null_mask(_as_obj(a[0], n) if _is_str(a[0]) else np.broadcast_to(np.asarray(a[0]), (n,)))
        if name == "is_not_null":
            return ~_null_mask(_as_obj(a[0], n) if _is_str(a[0]) else np.broadcast_to(np.asarray(a[0]), (n,)))
        if name == "like":
            import re as _re

            pat = a[1] if isinstance(a[1], str) else str(a[1])
            # SQL LIKE: % = any run, _ = one char; everything else literal
            rx = _re.compile(
                "^" + "".join(
                    ".*" if c == "%" else "." if c == "_" else _re.escape(c)
                    for c in pat
                ) + "$",
                _re.DOTALL,
            )
            vals = _as_obj(a[0], n)
            return np.array(
                [bool(rx.match(s)) if s is not None else False for s in vals],
                dtype=bool,
            )
        if name in ("json_get", "json_get_str"):
            # -> / ->> accessors (reference arroyo-planner json functions):
            # json_get yields the accessed value re-serialized as JSON text
            # ("155", "\"pickup\"", "null"); json_get_str yields bare text
            # (None for missing/null)
            import json as _json

            keys = a[1]
            key_is_scalar = _is_scalar(keys)
            docs = _as_obj(a[0], n)
            out = np.empty(n, dtype=object)
            for i, doc in enumerate(docs):
                k = keys if key_is_scalar else keys[i]
                v = None
                if doc is not None:
                    try:
                        parsed = _json.loads(doc) if isinstance(doc, (str, bytes)) else doc
                    except (ValueError, TypeError):
                        parsed = None
                    if isinstance(parsed, dict):
                        v = parsed.get(k)
                    elif isinstance(parsed, list):
                        try:
                            v = parsed[int(k)]
                        except (IndexError, ValueError, TypeError):
                            v = None
                if name == "json_get":
                    out[i] = _json.dumps(v, separators=(",", ":"))
                else:
                    out[i] = None if v is None else (
                        v if isinstance(v, str) else _json.dumps(v, separators=(",", ":")))
            return out
        raise NotImplementedError(f"scalar function {name}")

    def eval_jnp(self, cols):
        import jax.numpy as jnp

        def as_np_float(x):
            # numpy promotes integer inputs of floor/ceil/sqrt to float64;
            # jnp leaves floor/ceil of ints as ints and computes sqrt(int32)
            # in float32 — promote explicitly so the traced twin matches the
            # interpreted dtype bit for bit (bool stays divergent: numpy
            # computes in float16, XLA has no exact twin — AR009 rejects
            # float functions over bool at plan time for exactly this)
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.integer):
                return x.astype(jnp.float64)
            return x

        a = [arg.eval_jnp(cols) for arg in self.args]
        name = self.name
        table = {
            "abs": jnp.abs, "ln": jnp.log, "log10": jnp.log10, "exp": jnp.exp,
        }
        if name in table:
            return table[name](a[0])
        if name in ("floor", "ceil", "sqrt"):
            fn = {"floor": jnp.floor, "ceil": jnp.ceil, "sqrt": jnp.sqrt}[name]
            return fn(as_np_float(a[0]))
        if name == "round":
            return jnp.round(a[0], int(self.args[1].value) if len(a) > 1 else 0)
        if name == "power":
            return jnp.power(a[0], a[1])
        if name == "extract_epoch":
            return a[0] // 1_000_000
        if name == "date_trunc_micros":
            return (a[1] // a[0]) * a[0]
        if name == "to_timestamp_micros":
            return jnp.asarray(a[0]).astype(jnp.int64)
        raise NotImplementedError(f"device scalar function {name}")

    def columns(self):
        out = set()
        for arg in self.args:
            out |= arg.columns()
        return out


def _null_mask(arr) -> np.ndarray:
    if hasattr(arr, "dtype") and arr.dtype == object:
        return np.array([x is None for x in arr], dtype=bool)
    if hasattr(arr, "dtype") and arr.dtype.kind == "f":
        return np.isnan(arr)
    return np.zeros(len(arr), dtype=bool)


def eval_expr(expr: Expr, batch_cols: dict[str, np.ndarray], n: int) -> np.ndarray:
    """Evaluate to a full-length ndarray (broadcasting scalars)."""
    v = expr.eval_np(batch_cols, n)
    if _is_scalar(v) or (hasattr(v, "shape") and v.shape == ()):
        if isinstance(v, str) or v is None:
            out = np.empty(n, dtype=object)
            out[:] = v
            return out
        return np.full(n, v)
    return np.asarray(v)


# ---------------------------------------------------------------- serde
#
# Expression ASTs serialize to tagged JSON so dataflow graphs can cross
# process boundaries as data (the reference ships protobuf-encoded physical
# plans, api.proto:30-110; this is the same idea over the repo's own AST).
# Python UDF expressions serialize by NAME and re-resolve against the
# registry on load — the function itself never crosses the wire.

import dataclasses as _dc


def _expr_registry() -> dict:
    reg = {c.__name__: c for c in (Col, Lit, BinOp, Not, Neg, Cast, Case, Func)}
    from .udf import UdfExpr

    reg["UdfExpr"] = UdfExpr
    return reg


def _ser(v):
    if isinstance(v, Expr):
        return expr_to_json(v)
    if isinstance(v, (list, tuple)):
        return [_ser(x) for x in v]
    return v


def _deser(v):
    if isinstance(v, dict) and "__e__" in v:
        return expr_from_json(v)
    if isinstance(v, list):
        return tuple(_deser(x) for x in v)
    return v


def expr_to_json(e: Expr) -> dict:
    from .udf import UdfExpr

    if isinstance(e, UdfExpr):
        # by-name: fn/vectorized/return_dtype re-resolve from the registry
        return {"__e__": "UdfExpr", "udf_name": e.udf_name,
                "args": [_ser(a) for a in e.args]}
    out = {"__e__": type(e).__name__}
    for f in _dc.fields(e):
        out[f.name] = _ser(getattr(e, f.name))
    return out


def expr_from_json(d: dict) -> Expr:
    kind = d["__e__"]
    if kind == "UdfExpr":
        from .udf import lookup_udf

        u = lookup_udf(d["udf_name"])
        if u is None:
            raise ValueError(
                f"expression references unregistered UDF {d['udf_name']!r}"
            )
        return u.as_expr(tuple(_deser(a) for a in d["args"]))
    cls = _expr_registry()[kind]
    kwargs = {k: _deser(v) for k, v in d.items() if k != "__e__"}
    return cls(**kwargs)
