"""Tumbling window aggregate operator.

Reference behavior: crates/arroyo-worker/src/arrow/
tumbling_aggregating_window.rs:49 — bin incoming rows by the window width,
feed per-bin partial aggregates incrementally, and on watermark >= bin end
run the finish plan + optional final projection, stamping the window start as
the output timestamp; partials checkpoint into an ExpiringTimeKey table
(:470-483) and are re-binned on restore (:234-248).

TPU-native redesign: partials live in HBM inside a DeviceHashAggregator
keyed by (bin, key-hash); each micro-batch is one fused XLA step (sort ->
segment-reduce -> probing merge); window close is a device-side compaction
(extract) whose packed result is fetched ASYNCHRONOUSLY — emission and the
forwarded watermark are pipelined behind subsequent update steps so the host
never blocks on a device round trip in the hot loop. Numeric group-by key
VALUES ride along as extra max-accumulator lanes in HBM (all rows of a key
agree, so max is the identity function); only string-typed keys fall back to
a host-side hash -> values dictionary.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..config import config
from ..engine.engine import register_operator
from ..expr import Col, Expr, eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks
from ..types import Signal, Watermark

WINDOW_START = "window_start"
WINDOW_END = "window_end"

# in-flight window-close policy: extraction results materialize on the
# shared prefetch thread (ops/prefetch.py) so the hot loop never blocks on a
# device->host round trip; the queue force-drains past _PIPELINE_DEPTH
_PIPELINE_DEPTH = 16


def dtype_of_from_config(cfg: dict):
    """Accumulator-input dtype resolver: the in-process planner hands a live
    callable; graphs that crossed a process boundary (shipped IR) carry the
    declarative "input_dtypes" column map instead and rebuild it here."""
    fn = cfg.get("input_dtype_of")
    if fn is not None:
        return fn
    dtypes = cfg.get("input_dtypes")
    if dtypes:
        from ..batch import Field
        from ..sql.compile import infer_dtype

        dmap = dict(dtypes)
        return lambda e: Field("_", infer_dtype(e, dmap)).numpy_dtype()
    return lambda e: np.dtype(np.float64)


class CollectingAggregator:
    """Wraps the numeric aggregator with host-side object lanes for
    "collect"-kind accumulators (array_agg / UDAF state). Numeric lanes ride
    the wrapped slot tables untouched; list state lives in a host dict keyed
    (rel_bin, key_hash). Positional acc layout is preserved end-to-end so
    the window operators need no index remapping. Synchronous only — the
    planner forces backend="numpy" when a collect accumulator is present."""

    def __init__(self, acc_kinds, acc_dtypes, inner_factory):
        self.kinds = tuple(acc_kinds)
        self.col_idx = [i for i, k in enumerate(acc_kinds) if k == "collect"]
        self.num_idx = [i for i, k in enumerate(acc_kinds) if k != "collect"]
        # the inner aggregator tracks (key, bin) membership; with no numeric
        # user lane a hidden count keeps every group represented
        self._hidden = not self.num_idx
        inner_kinds = tuple(acc_kinds[i] for i in self.num_idx) or ("count",)
        inner_dtypes = (tuple(acc_dtypes[i] for i in self.num_idx)
                        or (np.dtype(np.int64),))
        self.inner = inner_factory(inner_kinds, inner_dtypes)
        # (rel_bin, key_hash) -> [list per collect acc]
        self.store: dict[tuple[int, int], list[list]] = {}

    def update(self, hashes, rel, vals) -> None:
        nvals = [vals[i] for i in self.num_idx]
        if self._hidden:
            nvals = [np.ones(len(hashes), dtype=np.int64)]
        self.inner.update(hashes, rel, nvals)
        # store keys use the SIGNED view of the hash, matching _assemble/
        # restore and the inner aggregator's convention (ops/aggregate.py)
        signed = hashes.astype(np.uint64).view(np.int64)
        order = np.lexsort((signed, rel))
        h_s = signed[order]
        r_s = rel[order]
        brk = np.ones(len(h_s), dtype=bool)
        if len(h_s) > 1:
            brk[1:] = (h_s[1:] != h_s[:-1]) | (r_s[1:] != r_s[:-1])
        starts = np.flatnonzero(brk)
        ends = np.append(starts[1:], len(h_s))
        cvals = [np.asarray(vals[i], dtype=object)[order] for i in self.col_idx]
        for s, e in zip(starts, ends):
            ent = self.store.setdefault(
                (int(r_s[s]), int(h_s[s])), [[] for _ in self.col_idx])
            for j, cv in enumerate(cvals):
                ent[j].extend(cv[s:e].tolist())

    def _assemble(self, keys, bins, naccs, pop: bool):
        """Positionally recombine numeric lanes with collect lists for the
        given (key, bin) rows; pop=True consumes store entries (extract)."""
        from ..batch import object_column

        out: list = [None] * len(self.kinds)
        ni = 0
        for i in self.num_idx:
            out[i] = naccs[ni]
            ni += 1
        if len(keys):
            signed = keys.astype(np.uint64).view(np.int64)
            for j, i in enumerate(self.col_idx):
                if pop and j == len(self.col_idx) - 1:
                    ents = [self.store.pop((int(b), int(k)), None)
                            for k, b in zip(signed, bins)]
                else:
                    ents = [self.store.get((int(b), int(k)))
                            for k, b in zip(signed, bins)]
                out[i] = object_column(
                    (list(e[j]) if e is not None else []) for e in ents)
        else:
            for i in self.col_idx:
                out[i] = np.empty(0, dtype=object)
        return out

    def extract(self, lo, hi, before):
        keys, bins, naccs = self.inner.extract(lo, hi, before)
        return keys, bins, self._assemble(keys, bins, naccs, pop=True)

    def snapshot(self):
        keys, bins, naccs = self.inner.snapshot()
        return keys, bins, self._assemble(keys, bins, naccs, pop=False)

    def restore(self, hashes, rel, accs) -> None:
        naccs = [accs[i] for i in self.num_idx]
        if self._hidden:
            # rebuild the hidden count lane from the collect list lengths
            naccs = [np.array([len(l) for l in accs[self.col_idx[0]]],
                              dtype=np.int64)]
        self.inner.restore(hashes, rel, naccs)
        signed = hashes.astype(np.uint64).view(np.int64)
        for row, (k, b) in enumerate(zip(signed, rel)):
            ent = self.store.setdefault((int(b), int(k)), [[] for _ in self.col_idx])
            for j, i in enumerate(self.col_idx):
                ent[j] = list(accs[i][row])


def record_mesh_overflow(op, ctx) -> int:
    """Throttled MESH_OVERFLOW WARN, called from the window operators'
    handle_checkpoint right after the snapshot (which refreshes the sharded
    store's spill residency with no extra device sync). Key skew past a
    fixed-capacity exchange lane parks rows in the per-shard HBM spill
    buffer — correct but slower, and the operator should hear about it
    before the buffer itself fills (which IS an error). The doubling
    high-water mark keeps a steadily-skewed job from flooding the feed."""
    stats_fn = getattr(op._agg, "mesh_stats", None)
    if stats_fn is None:
        return 0
    rows = int(stats_fn().get("overflow_rows", 0))
    if rows > op._mesh_oflow_hwm:
        op._mesh_oflow_hwm = rows * 2
        from ..obs.events import recorder

        ti = ctx.task_info
        recorder.record(
            ti.job_id, "WARN", "MESH_OVERFLOW",
            message=(f"{rows} rows resident in the sharded aggregate's "
                     f"per-shard HBM spill buffer (key skew past a "
                     f"fixed-capacity exchange lane; raise "
                     f"device.spill-capacity before it exhausts)"),
            node=ti.node_id, subtask=ti.subtask_index,
            data={"overflow_rows": rows})
    return rows


def make_window_aggregator(acc_kinds, acc_dtypes, backend: str):
    """Single-chip SlotAggregator or (device.mesh-devices > 1) the
    key-space-sharded ShardedAggregator — one construction path shared by
    every window operator so capacity knobs cannot drift between them.
    collect-kind accumulators (array_agg / UDAF state) wrap the numeric
    aggregator with host-side object lanes."""
    if "collect" in acc_kinds:
        return CollectingAggregator(
            acc_kinds, acc_dtypes,
            lambda ks, ds: make_window_aggregator(ks, ds, "numpy"))
    dev = config().section("device")
    mesh_n = int(dev.get("mesh-devices", 0) or 0)
    if backend == "jax" and mesh_n > 1:
        from ..parallel import ShardedAggregator, make_mesh

        return ShardedAggregator(
            make_mesh(mesh_n),
            acc_kinds,
            acc_dtypes,
            cap=dev.get("table-capacity", 65536),
            batch_cap=dev.get("batch-capacity", 8192),
            max_probes=dev.get("max-probes", 64),
            emit_cap=dev.get("emit-capacity", 8192),
            spill_cap=dev.get("spill-capacity", 2048),
        )
    from ..ops.slot_agg import SlotAggregator

    return SlotAggregator(
        acc_kinds,
        acc_dtypes,
        cap=dev.get("table-capacity", 65536),
        batch_cap=dev.get("batch-capacity", 8192),
        emit_cap=dev.get("emit-capacity", 8192),
        backend=backend,
        region_size=dev.get("region-size", 2048),
    )


def acc_plan(aggregates: list[tuple[str, str, Optional[Expr]]], schema_dtype_of) -> tuple:
    """Flatten SQL aggregates into accumulator (kind, dtype, input) triples.

    aggregates: [(out_name, kind, input_expr|None)]; count has no input.
    Returns (acc_kinds, acc_dtypes, input_specs) where input_specs[i] is the
    Expr for that accumulator or None for a count-style all-ones input.
    """
    kinds, dtypes, inputs = [], [], []
    for _name, kind, expr in aggregates:
        if kind == "count":
            kinds.append("count")
            dtypes.append(np.dtype(np.int64))
            inputs.append(None)
        elif kind == "avg":
            kinds.extend(["sum", "count"])
            dtypes.extend([np.dtype(np.float64), np.dtype(np.int64)])
            inputs.extend([expr, None])
        elif kind.startswith("udaf:") or kind in ("collect", "count_distinct"):
            # UDAF state / array_agg / COUNT(DISTINCT) = collected input
            # values (host-resident python lists; planner allows session +
            # tumbling windows)
            kinds.append("collect")
            dtypes.append(np.dtype(object))
            inputs.append(expr)
        else:
            kinds.append(kind)
            dtypes.append(schema_dtype_of(expr))
            inputs.append(expr)
    return tuple(kinds), tuple(dtypes), tuple(inputs)


class KeyDictionary:
    """hash -> key-column values, for reconstructing group-by columns at
    emission (device state stores only the 64-bit hash). Entries are evicted
    once every bin that saw the key has closed, bounding host memory. Used
    only for non-numeric key columns; numeric keys travel through HBM."""

    def __init__(self, key_fields: list[str]):
        self.key_fields = key_fields
        self.values: dict[int, tuple] = {}
        self.last_bin: dict[int, int] = {}

    def observe(self, hashes: np.ndarray, bins: np.ndarray, batch: Batch) -> None:
        if not self.key_fields:
            return
        u, first = np.unique(hashes, return_index=True)
        u_list = u.tolist()
        # conservative liveness: every key seen in this batch is live through
        # the batch's max bin. The update must be monotone — out-of-order
        # batches (normal after a keyed shuffle at parallelism>1) may carry a
        # lower max bin, and lowering a key's horizon would let evict_closed
        # delete values still resident on device.
        mx = int(bins.max()) if len(bins) else 0
        lb = self.last_bin
        for h in u_list:
            v = lb.get(h)
            if v is None or v < mx:  # rel bins can be negative: no sentinel
                lb[h] = mx
        vals = self.values
        new = [h for h in u_list if h not in vals]
        if new:
            cols = [batch[f] for f in self.key_fields]
            idx_of = dict(zip(u_list, first.tolist()))
            for h in new:
                i = idx_of[h]
                vals[h] = tuple(c[i] for c in cols)

    def evict_closed(self, rel_before: int) -> None:
        dead = [h for h, b in self.last_bin.items() if b < rel_before]
        for h in dead:
            del self.values[h]
            del self.last_bin[h]

    def lookup_columns(self, hashes: np.ndarray) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        if not self.key_fields:
            return out
        rows = [self.values[int(h)] for h in hashes]
        for j, f in enumerate(self.key_fields):
            vals = [r[j] for r in rows]
            sample = vals[0] if vals else None
            if isinstance(sample, (str, type(None))):
                out[f] = np.array(vals, dtype=object)
            else:
                out[f] = np.array(vals)
        return out


class TumblingAggregate(Operator):
    """config: width_micros, key_fields: list[str], aggregates:
    [(name, kind, Expr|None)], final_projection: [(name, Expr)]|None,
    input_dtype_of: callable Expr -> np.dtype (planner-provided), backend
    override "jax"|"numpy"|None."""

    def __init__(self, cfg: dict):
        self.width = int(cfg["width_micros"])
        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        self.final_projection = cfg.get("final_projection")
        dtype_of = dtype_of_from_config(cfg)
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        self.n_user_accs = len(self.acc_kinds)
        self.backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        self._agg = None
        # key transport split, decided from the first batch's column dtypes
        self.lane_key_fields: Optional[list[str]] = None  # numeric: HBM lanes
        self.dict_key_fields: list[str] = []  # strings: host dictionary
        self.key_dict = KeyDictionary([])
        self.base_bin: Optional[int] = None  # micros bin offset for int32 device bins
        self.open_bins: set[int] = set()  # relative bins resident on device
        # late-data boundary; checkpointed into the "e" global table at
        # every barrier and restored in on_start (replay must drop exactly
        # the rows the original run dropped)
        self.emitted_before_rel: Optional[int] = None
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data
        # in-flight closes: (ExtractHandle|None, rel_before|None, Watermark|None)
        self._pending: deque = deque()  # state: ephemeral — force-drained at every barrier (handle_checkpoint) before the snapshot
        self._batch_seq = 0  # state: ephemeral — orders in-flight closes within one incarnation; the queue is empty at every barrier
        self._mesh_oflow_hwm = 0  # state: ephemeral — MESH_OVERFLOW event throttle high-water mark

    # ------------------------------------------------------------------

    def tables(self):
        # retention = width: a bin's partials live until its window closes;
        # "e" holds the late-data barrier (same convention as session/
        # window_fn/InstantJoin) — global, so it survives an EMPTY partial
        # snapshot (every window closed at the barrier) where a column on
        # the "t" batch would be silently dropped
        return [TableSpec("t", "expiring_time_key", retention_micros=self.width),
                TableSpec("e", "global_keyed")]

    def _setup_key_transport(self, batch: Batch) -> None:
        """Split group-by columns by dtype: numeric values are carried in HBM
        as extra max-lanes (every row of a key holds the same value); the
        rest go through the host KeyDictionary."""
        lane, dicty = [], []
        for f in self.key_fields:
            col = np.asarray(batch[f])
            if np.issubdtype(col.dtype, np.integer) or np.issubdtype(col.dtype, np.floating):
                lane.append((f, col.dtype))
            else:
                dicty.append(f)
        self.lane_key_fields = [f for f, _ in lane]
        self.dict_key_fields = dicty
        self.key_dict = KeyDictionary(dicty)
        self.acc_kinds = self.acc_kinds + tuple("max" for _ in lane)
        self.acc_dtypes = self.acc_dtypes + tuple(np.dtype(d) for _, d in lane)
        self.acc_inputs = self.acc_inputs + tuple(Col(f) for f, _ in lane)

    def _aggregator(self):
        if self._agg is None:
            # mesh execution mode (device.mesh-devices > 1): key-space-
            # sharded state, keyed exchange = in-program all_to_all over ICI
            # (replaces the reference's repartition shuffle,
            # crates/arroyo-operator/src/context.rs:502-556)
            self._agg = make_window_aggregator(
                self.acc_kinds, self.acc_dtypes, self.backend)
        return self._agg

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        batches = tbl.all_batches()
        if batches:
            restored = Batch.concat(batches)
            self._restore_from_batch(restored)
            tbl.replace_all([])
        # late-data boundary (ABSOLUTE bin): replay must drop exactly the
        # rows the original run dropped, or window contents diverge after a
        # restore. Watermark-aligned, so max merges subtasks/rescales.
        barriers = restore_marks(ctx, "e")
        if barriers:
            eb_abs = max(barriers)
            if self.base_bin is None:
                # empty partial snapshot (every window closed at the
                # barrier): anchor the bin space at the boundary itself
                self.base_bin = eb_abs
            self.emitted_before_rel = eb_abs - self.base_bin

    def _restore_from_batch(self, b: Batch) -> None:
        # checkpoints carry every key field as a named column, so the
        # transport split can be re-derived from the checkpoint batch itself
        if self.lane_key_fields is None:
            self._setup_key_transport(b)
        hashes = b.keys.astype(np.uint64)
        starts = b.timestamps
        bins_abs = starts // self.width
        self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        accs = [b[f"__acc_{i}"].astype(d)
                for i, d in enumerate(self.acc_dtypes[: self.n_user_accs])]
        accs += [np.asarray(b[f]).astype(d)
                 for f, d in zip(self.lane_key_fields,
                                 self.acc_dtypes[self.n_user_accs:])]
        self._aggregator().restore(hashes, rel, accs)
        self.open_bins = set(np.unique(rel).tolist())
        if self.dict_key_fields:
            self.key_dict.observe(hashes, rel, b)

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        # NOTE: insert_arrays below is this method's compiled-segment twin;
        # any change to the drain/base-bin/late-filter/update sequence here
        # must be mirrored there (the first-batch verification only covers
        # the traced PREFIX outputs, not this state logic)
        self._batch_seq += 1
        if self._pending:
            self._drain_pending(collector)
        if self.lane_key_fields is None:
            self._setup_key_transport(batch)
        ts = batch.timestamps
        bins_abs = ts // self.width
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        if self.emitted_before_rel is not None:
            # drop rows behind already-emitted windows (reference drops
            # late data rather than re-opening closed windows)
            late = rel < self.emitted_before_rel
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
                rel = rel[~late]
        n = batch.num_rows
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64)
        else:
            hashes = np.zeros(n, dtype=np.uint64)
        if self.dict_key_fields:
            self.key_dict.observe(hashes, rel, batch)
        vals = []
        for inp, dt in zip(self.acc_inputs, self.acc_dtypes):
            if inp is None:
                vals.append(np.ones(n, dtype=dt))
            else:
                vals.append(np.asarray(eval_expr(inp, batch.columns, n)).astype(dt))
        self._aggregator().update(hashes, rel, vals)
        self.open_bins.update(np.unique(rel).tolist())

    def insert_arrays(self, hashes, bins_abs, vals, collector) -> None:
        """Compiled-segment twin of process_batch (engine/segment.py): the
        traced prefix already evaluated the routing hashes, absolute bins,
        and accumulator inputs; this applies the member's mutable-state
        logic — pending-close drain, late-data filter, aggregator update —
        exactly as process_batch does. State lives HERE either way, so
        checkpoints and the late boundary are byte-identical across the
        compiled and interpreted paths. Only reached when the compile gate
        proved there are no host key dictionary fields and no collect
        accumulators."""
        self._batch_seq += 1
        if self._pending:
            self._drain_pending(collector)
        if len(hashes) == 0:
            return
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        if self.emitted_before_rel is not None:
            late = rel < self.emitted_before_rel
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                keep = ~late
                rel = rel[keep]
                hashes = hashes[keep]
                vals = [v[keep] for v in vals]
        self._aggregator().update(hashes, rel, vals)
        self.open_bins.update(np.unique(rel).tolist())

    def mesh_insert_begin(self, bins_abs, collector):
        """Host half of the FUSED mesh step (engine/segment.py
        _mesh_execute): the member's mutable-state prologue — pending-close
        drain, base-bin anchoring, late-data split, open-bin bookkeeping —
        WITHOUT the aggregator update, which the shard_map'd program
        performs in-program. Returns the on-time row mask (None = every
        row inserts). Mirrors insert_arrays statement for statement so
        checkpoints and the late boundary stay byte-identical across the
        fused, compiled-host, and interpreted paths."""
        self._batch_seq += 1
        if self._pending:
            self._drain_pending(collector)
        if len(bins_abs) == 0:
            return None
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        ontime = None
        if self.emitted_before_rel is not None:
            late = rel < self.emitted_before_rel
            if late.any():
                self.late_rows += int(late.sum())
                ontime = ~late
                rel = rel[ontime]
        if len(rel):
            self.open_bins.update(np.unique(rel).tolist())
        return ontime

    def mesh_stats(self):
        """Mesh-execution residency counters (None off the sharded path);
        obs/profile.py exports them as the arroyo_mesh_* series."""
        stats = getattr(self._agg, "mesh_stats", None)
        return stats() if stats is not None else None

    # ------------------------------------------------------------- emission

    def _drain_pending(self, collector, force: bool = False) -> None:
        """Emit completed in-flight closes in order; each close's watermark
        broadcasts only after its rows, preserving downstream lateness
        semantics."""
        while self._pending:
            fut, rel_before, wm, _seq = self._pending[0]
            if fut is not None and not force and not fut.is_ready():
                return
            self._pending.popleft()
            if fut is not None:
                keys, bins, accs = fut.result()
                if len(keys):
                    self._emit_entries(keys, bins, accs, collector)
                if self.dict_key_fields:
                    self.key_dict.evict_closed(rel_before)
            if wm is not None:
                collector.broadcast(Signal.watermark_of(wm))

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            self._drain_pending(collector, force=True)
            return watermark
        if self._pending:
            # during a data gap watermarks keep arriving with no batches to
            # trigger draining; drain ripe closes here so the pending queue
            # stays bounded and rows are not held indefinitely
            self._drain_pending(collector)
        closed_before_abs = watermark.value // self.width
        # Future emissions are stamped with a window start >= bin_start(w);
        # forward that instead of w so downstream operators (e.g. windowed
        # joins) never see our output as late. The reference forwards w
        # unchanged and relies on sparse watermarks; with dense per-batch
        # watermarks the adjusted value is required for correctness.
        out_wm = Watermark.event_time(closed_before_abs * self.width)
        scheduled = self._schedule_close(closed_before_abs, out_wm, collector)
        if scheduled or self._pending:
            return None  # watermark rides the pending queue, in order
        return out_wm

    def on_close(self, ctx, collector):
        self._schedule_close(None, None, collector)
        self._drain_pending(collector, force=True)

    def _hold_watermark(self, out_wm: Optional[Watermark], collector) -> bool:
        """No bins are closing: if earlier closes are still in flight, queue
        the watermark behind them (bounded by the pipeline depth); returns
        True when held, False when the caller should forward it."""
        if out_wm is None or not self._pending:
            return False
        tail = self._pending[-1]
        if tail[0] is None and tail[2] is not None:
            # consecutive watermarks with no rows between them collapse to
            # the newest — only the latest matters downstream, and appending
            # each would churn the depth bound into needless force-drains
            self._pending[-1] = (None, None, out_wm, tail[3])
            return True
        if len(self._pending) >= _PIPELINE_DEPTH:
            self._drain_pending(collector, force=True)
            return False
        self._pending.append((None, None, out_wm, self._batch_seq))
        return True

    def _schedule_close(self, closed_before_abs: Optional[int],
                        out_wm: Optional[Watermark], collector) -> bool:
        """Dispatch the device extraction for every bin closed by the
        watermark; returns True if a close (or watermark hold) was queued."""
        if self.base_bin is None or not self.open_bins:
            return self._hold_watermark(out_wm, collector)
        if closed_before_abs is None:
            rel_before = max(self.open_bins) + 1
        else:
            rel_before = int(closed_before_abs - self.base_bin)
        if self.emitted_before_rel is None or rel_before > self.emitted_before_rel:
            self.emitted_before_rel = rel_before
        closing = sorted(b for b in self.open_bins if b < rel_before)
        if not closing:
            return self._hold_watermark(out_wm, collector)
        agg = self._aggregator()
        self.open_bins -= set(closing)
        if self.backend == "numpy":
            keys, bins, accs = agg.extract(min(closing), rel_before, rel_before)
            if len(keys):
                self._emit_entries(keys, bins, accs, collector)
            if self.dict_key_fields:
                self.key_dict.evict_closed(rel_before)
            return False  # synchronous: caller forwards the watermark itself
        if len(self._pending) >= _PIPELINE_DEPTH:
            self._drain_pending(collector, force=True)
        handle = agg.extract_start(min(closing), rel_before, rel_before)
        from ..ops.prefetch import shared_prefetcher

        fut = shared_prefetcher().submit(handle.result)
        self._pending.append((fut, rel_before, out_wm, self._batch_seq))
        return True

    def _emit_entries(self, keys, bins, accs, collector) -> None:
        from ..ops.aggregate import finalize_aggs

        starts = (bins.astype(np.int64) + self.base_bin) * self.width
        cols: dict[str, np.ndarray] = {}
        if self.dict_key_fields:
            cols.update(self.key_dict.lookup_columns(keys))
        for f, lane in zip(self.lane_key_fields, accs[self.n_user_accs:]):
            cols[f] = lane
        cols[WINDOW_START] = starts
        cols[WINDOW_END] = starts + self.width
        finals = finalize_aggs([a[1] for a in self.aggregates], accs[: self.n_user_accs])
        for (name, _k, _e), arr in zip(self.aggregates, finals):
            cols[name] = arr
        # reference stamps the window start as the output event time
        cols[TIMESTAMP_FIELD] = starts
        out = Batch(cols)
        if self.final_projection is not None:
            n = out.num_rows
            proj = {name: eval_expr(e, out.columns, n) for name, e in self.final_projection}
            if TIMESTAMP_FIELD not in proj:
                proj[TIMESTAMP_FIELD] = out.timestamps
            out = Batch(proj)
        collector.collect(out)

    # ------------------------------------------------------------------

    def handle_checkpoint(self, barrier, ctx, collector):
        # flush in-flight emissions first: their rows/watermarks must precede
        # the barrier, and the snapshot must not race follow-up extractions
        self._drain_pending(collector, force=True)
        # the late-data barrier persists UNCONDITIONALLY — an empty partial
        # snapshot (all windows closed) must not lose the boundary
        persist_mark(ctx, "e",
                     None if self.emitted_before_rel is None
                     else self.emitted_before_rel + (self.base_bin or 0))
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        if self._agg is None:
            # no data yet: building the aggregator here would freeze acc_kinds
            # before _setup_key_transport appends the numeric key lanes, so
            # later updates would silently drop lane values (zip truncation)
            tbl.replace_all([])
            return
        keys, bins, accs = self._agg.snapshot()
        record_mesh_overflow(self, ctx)
        if len(keys) == 0:
            tbl.replace_all([])
            return
        starts = (bins.astype(np.int64) + (self.base_bin or 0)) * self.width
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: starts,
            KEY_FIELD: keys,
        }
        if self.dict_key_fields:
            cols.update(self.key_dict.lookup_columns(keys))
        for f, lane in zip(self.lane_key_fields or [], accs[self.n_user_accs:]):
            cols[f] = lane
        for i, a in enumerate(accs[: self.n_user_accs]):
            cols[f"__acc_{i}"] = a
        tbl.replace_all([Batch(cols)])


@register_operator(OpName.TUMBLING_AGGREGATE)
def _make_tumbling(cfg: dict):
    return TumblingAggregate(cfg)
