"""Sliding (hop) window aggregate operator.

Reference behavior: crates/arroyo-worker/src/arrow/
sliding_aggregating_window.rs:45 — bin incoming rows by the *slide*; keep
per-bin partial aggregates; at each slide boundary the watermark passes,
combine the partials of the ``width/slide`` bins in [end-width, end) and emit
one row per key, stamping the window start as the output timestamp (:194,
:217-225); partials are retained until the last window containing them closes
(:161-162 flush/expire at ``bin_end - width + slide``).

TPU-native redesign: the per-bin partials live in HBM inside the same
DeviceHashAggregator the tumbling operator uses (bin = slide index); the
window-close combine is a non-destructive device range-scan of the
contributing bins (position-chunked so ranges larger than the emit buffer are
never truncated) followed by a vectorized host combine-by-key — the scanned
data is already reduced to distinct (bin, key) pairs, so it is tiny relative
to the event stream the device reduced.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..config import config
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks
from ..types import Watermark
from .tumbling import (WINDOW_END, WINDOW_START, KeyDictionary, acc_plan,
                       dtype_of_from_config, make_window_aggregator,
                       record_mesh_overflow)


class SlidingAggregate(Operator):
    """config: width_micros, slide_micros, key_fields: list[str], aggregates:
    [(name, kind, Expr|None)], final_projection: [(name, Expr)]|None,
    input_dtype_of, backend override."""

    def __init__(self, cfg: dict):
        self.width = int(cfg["width_micros"])
        self.slide = int(cfg["slide_micros"])
        if self.width % self.slide != 0 or self.width <= 0 or self.slide <= 0:
            raise ValueError(
                f"hop window width ({self.width}us) must be a positive multiple "
                f"of the slide ({self.slide}us)"
            )
        self.nb = self.width // self.slide  # bins per window
        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        self.final_projection = cfg.get("final_projection")
        dtype_of = dtype_of_from_config(cfg)
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        self.n_user_accs = len(self.acc_kinds)
        self.backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        self._agg = None
        # key transport split (same as tumbling): numeric group-by columns
        # ride the aggregate store as extra max-lanes — every row of a key
        # holds the same value — so only non-numeric keys pay the host
        # KeyDictionary's per-key Python cost
        self.lane_key_fields: Optional[list[str]] = None
        self.dict_key_fields: list[str] = []
        self.key_dict = KeyDictionary([])
        self.base_bin: Optional[int] = None  # abs slide-bin offset
        self.min_bin: Optional[int] = None  # earliest live rel bin
        self.max_bin: Optional[int] = None  # latest rel bin seen
        self.next_window: Optional[int] = None  # rel start-bin of next window to emit
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data
        self._mesh_oflow_hwm = 0  # state: ephemeral — MESH_OVERFLOW event throttle high-water mark
        # device-path incremental extraction: each slide bin is fetched from
        # the device EXACTLY ONCE (destructively) when the watermark completes
        # it, asynchronously via the shared prefetcher; windows combine the
        # host-cached bins. This replaces the nb-way-redundant synchronous
        # scan-per-window (measured 38s for 1M events on the remote device
        # link — one ~70ms fetch sync per window close).
        self.open_bins: set[int] = set()  # rel bins with device-resident data
        self._bin_cache: dict[int, tuple] = {}  # rel bin -> (keys_u64, accs)  # state: ephemeral — folded into the 't' snapshot at every barrier; restore returns those bins to the device store
        self._bin_pending: dict = {}  # rel bin -> Future[(keys, bins, accs)]  # state: ephemeral — force-resolved at every barrier (handle_checkpoint) before the snapshot
        # extraction progress (NOT the late boundary): reset on restore so
        # bins folded back into the device store are re-extracted
        self._extracted_before: Optional[int] = None  # state: ephemeral — restored bins return to the device store and must re-extract; the late boundary persists separately
        # late-drop boundary; checkpointed into the "e" global table at
        # every barrier and restored in on_start, so replay drops exactly
        # the rows the original run dropped
        self._late_before: Optional[int] = None
        self._target_window: Optional[int] = None  # emit windows <= this  # state: ephemeral — re-derived from the first post-restore watermark; emission only reorders against input batches, never against forwarded watermarks
        self._wm_queue: list = []  # (target_window, Watermark) held in order  # state: ephemeral — fully drained by the forced _drain at every barrier

    # ------------------------------------------------------------------

    def tables(self):
        # a bin's partials live until the last window containing it closes;
        # "e" holds the late-drop boundary (global: survives an empty
        # partial snapshot, where a column on the "t" batch would vanish)
        return [TableSpec("t", "expiring_time_key", retention_micros=self.width),
                TableSpec("e", "global_keyed")]

    def _aggregator(self):
        if self._agg is None:
            # mesh mode shares tumbling's construction path: per-bin partials
            # sharded over the key space; the incremental per-bin extraction
            # drives extract_start(b, b+1, b+1), which the sharded store
            # serves synchronously
            self._agg = make_window_aggregator(
                self.acc_kinds, self.acc_dtypes, self.backend)
        return self._agg

    def _setup_key_transport(self, batch: Batch) -> None:
        lane, dicty = [], []
        for f in self.key_fields:
            col = np.asarray(batch[f])
            if np.issubdtype(col.dtype, np.integer) or np.issubdtype(col.dtype, np.floating):
                lane.append((f, col.dtype))
            else:
                dicty.append(f)
        self.lane_key_fields = [f for f, _ in lane]
        self.dict_key_fields = dicty
        self.key_dict = KeyDictionary(dicty)
        from ..expr import Col

        self.acc_kinds = self.acc_kinds + tuple("max" for _ in lane)
        self.acc_dtypes = self.acc_dtypes + tuple(np.dtype(d) for _, d in lane)
        self.acc_inputs = self.acc_inputs + tuple(Col(f) for f, _ in lane)

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        batches = tbl.all_batches()
        if batches:
            self._restore_from_batch(Batch.concat(batches))
            tbl.replace_all([])
        # late-drop boundary (ABSOLUTE slide bin): replay must drop exactly
        # the rows the original run dropped; max merges subtasks/rescales
        barriers = restore_marks(ctx, "e")
        if barriers:
            lb_abs = max(barriers)
            if self.base_bin is None:
                # empty partial snapshot: anchor the bin space at the boundary
                self.base_bin = lb_abs
            self._late_before = lb_abs - self.base_bin

    def _restore_from_batch(self, b: Batch) -> None:
        if self.lane_key_fields is None:
            self._setup_key_transport(b)
        hashes = b.keys.astype(np.uint64)
        bins_abs = b.timestamps // self.slide
        self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        accs = [b[f"__acc_{i}"].astype(d)
                for i, d in enumerate(self.acc_dtypes[: self.n_user_accs])]
        accs += [np.asarray(b[f]).astype(d)
                 for f, d in zip(self.lane_key_fields,
                                 self.acc_dtypes[self.n_user_accs:])]
        self._aggregator().restore(hashes, rel, accs)
        self.open_bins = set(np.unique(rel).tolist())
        self.min_bin = int(rel.min())
        self.max_bin = int(rel.max())
        if "__next_window" in b:
            # stored absolute; aligned barriers mean all prior subtasks saw the
            # same watermark, so max is a safe merge across rescaled inputs
            self.next_window = int(b["__next_window"].max()) - self.base_bin
        else:
            self.next_window = self.min_bin - self.nb + 1
        if self.key_fields:
            self.key_dict.observe(hashes, rel, b)

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        # NOTE: insert_arrays below is this method's compiled-segment twin;
        # any change to the drain/late-boundary/update/bin-bookkeeping
        # sequence here must be mirrored there
        if self._bin_pending or self._wm_queue:
            self._drain(collector)
        if self.lane_key_fields is None:
            self._setup_key_transport(batch)
        ts = batch.timestamps
        bins_abs = ts // self.slide
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int64)
        # a row is late if its bin's last window already fired, or (device
        # path) the bin was already destructively extracted — both are
        # watermark-contract violations by the producer
        late_before = self.next_window
        if self._late_before is not None:
            late_before = (self._late_before if late_before is None
                           else max(late_before, self._late_before))
        if late_before is not None:
            late = rel < late_before
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
                rel = rel[~late]
        rel = rel.astype(np.int32)
        n = batch.num_rows
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64)
        else:
            hashes = np.zeros(n, dtype=np.uint64)
        self.key_dict.observe(hashes, rel, batch)
        vals = []
        for inp, dt in zip(self.acc_inputs, self.acc_dtypes):
            if inp is None:
                vals.append(np.ones(n, dtype=dt))
            else:
                vals.append(np.asarray(eval_expr(inp, batch.columns, n)).astype(dt))
        self._aggregator().update(hashes, rel, vals)
        if self.backend != "numpy":  # numpy path never reads the set
            self.open_bins.update(np.unique(rel).tolist())
        lo, hi = int(rel.min()), int(rel.max())
        self.min_bin = lo if self.min_bin is None else min(self.min_bin, lo)
        self.max_bin = hi if self.max_bin is None else max(self.max_bin, hi)
        if self.next_window is None:
            self.next_window = self.min_bin - self.nb + 1

    def insert_arrays(self, hashes, bins_abs, vals, collector) -> None:
        """Compiled-segment twin of process_batch (engine/segment.py, same
        contract as TumblingAggregate.insert_arrays): apply this member's
        mutable-state logic — drain, late filter, aggregator update, bin
        bookkeeping — to prefix-traced arrays. State lives here either way,
        so checkpoints and the late boundary are byte-identical. Only
        reached when the compile gate proved there are no host key
        dictionary fields and no collect accumulators."""
        if self._bin_pending or self._wm_queue:
            self._drain(collector)
        if len(hashes) == 0:
            return
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = bins_abs - self.base_bin
        late_before = self.next_window
        if self._late_before is not None:
            late_before = (self._late_before if late_before is None
                           else max(late_before, self._late_before))
        if late_before is not None:
            late = rel < late_before
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                keep = ~late
                rel = rel[keep]
                hashes = hashes[keep]
                vals = [v[keep] for v in vals]
        rel = rel.astype(np.int32)
        self._aggregator().update(hashes, rel, vals)
        if self.backend != "numpy":  # numpy path never reads the set
            self.open_bins.update(np.unique(rel).tolist())
        lo, hi = int(rel.min()), int(rel.max())
        self.min_bin = lo if self.min_bin is None else min(self.min_bin, lo)
        self.max_bin = hi if self.max_bin is None else max(self.max_bin, hi)
        if self.next_window is None:
            self.next_window = self.min_bin - self.nb + 1

    def mesh_insert_begin(self, bins_abs, collector):
        """Host half of the FUSED mesh step (same contract as
        TumblingAggregate.mesh_insert_begin): drain, base-bin anchor, late
        split, bin bookkeeping — the aggregator update itself runs inside
        the shard_map'd program. Mirrors insert_arrays statement for
        statement (late compare in int64 BEFORE the int32 cast) so the
        late boundary and checkpoints stay byte-identical."""
        if self._bin_pending or self._wm_queue:
            self._drain(collector)
        if len(bins_abs) == 0:
            return None
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = bins_abs - self.base_bin
        late_before = self.next_window
        if self._late_before is not None:
            late_before = (self._late_before if late_before is None
                           else max(late_before, self._late_before))
        ontime = None
        if late_before is not None:
            late = rel < late_before
            if late.any():
                self.late_rows += int(late.sum())
                ontime = ~late
                rel = rel[ontime]
        if len(rel) == 0:
            return ontime
        rel = rel.astype(np.int32)
        self.open_bins.update(np.unique(rel).tolist())
        lo, hi = int(rel.min()), int(rel.max())
        self.min_bin = lo if self.min_bin is None else min(self.min_bin, lo)
        self.max_bin = hi if self.max_bin is None else max(self.max_bin, hi)
        if self.next_window is None:
            self.next_window = self.min_bin - self.nb + 1
        return ontime

    def mesh_stats(self):
        """Mesh-execution residency counters (None off the sharded path)."""
        stats = getattr(self._agg, "mesh_stats", None)
        return stats() if stats is not None else None

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            self._drain(collector, force=True)
            return watermark
        # future emissions are stamped with window starts strictly after the
        # last closed boundary; forward that lower bound (see tumbling)
        held = ((watermark.value - self.width) // self.slide + 1) * self.slide
        out_wm = Watermark.event_time(min(watermark.value, held))
        if self.base_bin is None:
            return out_wm
        if self.backend == "numpy":
            last_closed = (watermark.value - self.width) // self.slide - self.base_bin
            self._emit_through(int(last_closed), collector)
            return out_wm
        # device path: bins complete once the watermark passes their end;
        # dispatch their (destructive) extraction, then emit whatever windows
        # have all bins resolved — later watermarks/batches drain the rest
        complete_before = int(watermark.value // self.slide - self.base_bin)
        self._dispatch_extracts(complete_before)
        last_closed = int((watermark.value - self.width) // self.slide - self.base_bin)
        if self._target_window is None or last_closed > self._target_window:
            self._target_window = last_closed
        self._drain(collector)
        if self._caught_up() and not self._wm_queue:
            return out_wm
        self._wm_queue.append((self._target_window, out_wm))
        return None

    def on_close(self, ctx, collector):
        if self.max_bin is None:
            return
        if self.backend == "numpy":
            self._emit_through(self.max_bin, collector)
            return
        self._dispatch_extracts(self.max_bin + 1)
        self._target_window = max(self._target_window or self.max_bin, self.max_bin)
        self._drain(collector, force=True)

    def _caught_up(self) -> bool:
        return (self.next_window is None or self._target_window is None
                or self.next_window > self._target_window)

    def _dispatch_extracts(self, complete_before: int) -> None:
        """Start the one-time extraction of every complete data-carrying bin
        below complete_before (ascending, so the slot directory's monotone
        close boundary is respected)."""
        if self._extracted_before is not None and complete_before <= self._extracted_before:
            return
        ready = sorted(b for b in self.open_bins if b < complete_before)
        if ready:
            agg = self._aggregator()
            from ..ops.prefetch import shared_prefetcher

            pf = shared_prefetcher()
            for b in ready:
                handle = agg.extract_start(b, b + 1, b + 1)
                self._bin_pending[b] = pf.submit(handle.result)
                self.open_bins.discard(b)
        self._extracted_before = complete_before
        if self._late_before is None or complete_before > self._late_before:
            self._late_before = complete_before

    def _resolve_bins(self, bins: list[int], force: bool) -> bool:
        """Move resolved futures into the cache; True when every requested
        bin is available (cached or known-empty)."""
        ok = True
        for b in bins:
            fut = self._bin_pending.get(b)
            if fut is None:
                continue
            if force or fut.is_ready():
                keys, _bins, accs = fut.result()
                if len(keys):
                    self._bin_cache[b] = (keys, accs)
                del self._bin_pending[b]
            else:
                ok = False
        return ok

    def _drain(self, collector, force: bool = False) -> None:
        """Emit in-order every window whose bins are all resolved — fused
        into ONE output batch per drain (tail closes and catch-up used to
        emit one tiny batch per window) — then forward watermarks whose
        windows are out."""
        from ..ops.aggregate import combine_by_key

        fused: list[dict] = []
        while not self._caught_up():
            w = self.next_window
            # event-time gap fast-forward: if no bin anywhere could feed a
            # window starting at w, jump straight to the earliest window the
            # live data can touch (a clock jump would otherwise make this
            # loop iterate once per empty slide bin across the gap)
            live = [b for src in (self._bin_cache, self._bin_pending, self.open_bins)
                    for b in src if b >= w]
            if not live:
                self.next_window = self._target_window + 1
                self.key_dict.evict_closed(self.next_window)
                break
            earliest = min(live)
            if earliest >= w + self.nb:
                self.next_window = min(earliest - self.nb + 1, self._target_window + 1)
                self.key_dict.evict_closed(self.next_window)
                continue
            needed = list(range(w, w + self.nb))
            if not self._resolve_bins(needed, force):
                break
            parts = [self._bin_cache[b] for b in needed if b in self._bin_cache]
            if parts:
                keys = np.concatenate([p[0] for p in parts])
                accs = [np.concatenate([p[1][i] for p in parts])
                        for i in range(len(self.acc_kinds))]
                keys_c, accs_c = combine_by_key(self.acc_kinds, keys, accs)
                fused.append(self._window_cols(w, keys_c, accs_c))
            self.next_window = w + 1
            # lint: waive LR204 — eviction only: deletes closed cache bins; no row is built or emitted from this loop
            for b in [b for b in self._bin_cache if b < self.next_window]:
                del self._bin_cache[b]
            self.key_dict.evict_closed(self.next_window)
        self._emit_fused(fused, collector)
        while self._wm_queue and (self.next_window is None
                                  or self._wm_queue[0][0] < self.next_window):
            _t, wm = self._wm_queue.pop(0)
            from ..types import Signal

            collector.broadcast(Signal.watermark_of(wm))

    def _emit_through(self, last_start_rel: int, collector) -> None:
        """numpy-backend path: synchronous scan per window (the dict store
        has no fetch latency to hide); all closing windows fuse into one
        emitted batch."""
        if self.next_window is None:
            return
        agg = self._aggregator()
        fused: list[dict] = []
        while self.next_window <= last_start_rel:
            b = self.next_window
            if self.max_bin is not None and b > self.max_bin:
                # nothing at or after this window's start; fast-forward
                self.next_window = last_start_rel + 1
                break
            if self.min_bin is not None and b + self.nb <= self.min_bin:
                # gap: window lies entirely before the earliest live bin
                nw = min(last_start_rel + 1, self.min_bin - self.nb + 1)
                self.next_window = max(nw, b + 1)
                agg.free_bins_below(self.next_window)
                self.key_dict.evict_closed(self.next_window)
                continue
            keys, _bins, accs = agg.scan_range(b, b + self.nb)
            if len(keys) == 0:
                # bins < b are freed, so an empty scan proves every live bin
                # is >= b + nb: re-arm the gap fast-forward above
                self.min_bin = b + self.nb
            if len(keys):
                from ..ops.aggregate import combine_by_key

                keys_c, accs_c = combine_by_key(self.acc_kinds, keys, accs)
                fused.append(self._window_cols(b, keys_c, accs_c))
            self.next_window = b + 1
            # bins below the next window's range are done
            agg.free_bins_below(self.next_window)
            self.key_dict.evict_closed(self.next_window)
            if self.min_bin is not None:
                self.min_bin = max(self.min_bin, self.next_window)
        self._emit_fused(fused, collector)

    def _window_cols(self, start_rel: int, keys, accs) -> dict:
        """Pre-projection output columns for one closed window (key lookups
        resolved eagerly, BEFORE the caller evicts the window's keys)."""
        from ..ops.aggregate import finalize_aggs

        start = (start_rel + self.base_bin) * self.slide
        n = len(keys)
        cols: dict[str, np.ndarray] = {}
        if self.dict_key_fields:
            cols.update(self.key_dict.lookup_columns(keys))
        for f, lane in zip(self.lane_key_fields or [], accs[self.n_user_accs:]):
            cols[f] = lane
        cols[WINDOW_START] = np.full(n, start, dtype=np.int64)
        cols[WINDOW_END] = np.full(n, start + self.width, dtype=np.int64)
        finals = finalize_aggs([a[1] for a in self.aggregates],
                               accs[: self.n_user_accs])
        for (name, _k, _e), arr in zip(self.aggregates, finals):
            cols[name] = arr
        # reference stamps the window start as the output event time (:217)
        cols[TIMESTAMP_FIELD] = np.full(n, start, dtype=np.int64)
        return cols

    def _emit_fused(self, fused: list[dict], collector) -> None:
        """One collect for ALL windows closed in this drain: concatenate the
        per-window columns, apply the final projection once (row-wise, so
        fusing cannot change its values)."""
        if not fused:
            return
        if len(fused) == 1:
            cols = fused[0]
        else:
            names = fused[0].keys()
            cols = {f: np.concatenate([c[f] for c in fused]) for f in names}
        out = Batch(cols)
        if self.final_projection is not None:
            n = out.num_rows
            proj = {name: eval_expr(e, out.columns, n)
                    for name, e in self.final_projection}
            if TIMESTAMP_FIELD not in proj:
                proj[TIMESTAMP_FIELD] = out.timestamps
            out = Batch(proj)
        collector.collect(out)

    # ------------------------------------------------------------------

    def handle_checkpoint(self, barrier, ctx, collector):
        # flush every emittable window first (rows precede the barrier), then
        # fold host-cached bins — destructively extracted off the device but
        # still feeding future windows — into the snapshot
        self._drain(collector, force=True)
        self._resolve_bins(sorted(self._bin_pending), force=True)
        # the late-drop boundary persists UNCONDITIONALLY — an empty
        # partial snapshot must not lose it. Fold in next_window: on the
        # numpy backend the live late filter is next_window itself
        # (_late_before is device-path-only), and its __next_window column
        # vanishes with an empty snapshot
        rel_marks = [m for m in (self._late_before, self.next_window)
                     if m is not None]
        persist_mark(ctx, "e",
                     None if not rel_marks
                     else max(rel_marks) + (self.base_bin or 0))
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        if self._agg is None:
            # no data yet: building the aggregator now would freeze
            # acc_kinds before _setup_key_transport appends the key lanes
            tbl.replace_all([])
            return
        keys, bins, accs = self._aggregator().snapshot()
        record_mesh_overflow(self, ctx)
        cached = sorted(self._bin_cache)
        if cached:
            keys = np.concatenate([keys] + [self._bin_cache[b][0] for b in cached])
            bins = np.concatenate(
                [bins] + [np.full(len(self._bin_cache[b][0]), b, dtype=np.int32)
                          for b in cached])
            accs = [np.concatenate([a] + [self._bin_cache[b][1][i] for b in cached])
                    for i, a in enumerate(accs)]
        if len(keys) == 0:
            tbl.replace_all([])
            return
        starts = (bins.astype(np.int64) + (self.base_bin or 0)) * self.slide
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: starts,
            KEY_FIELD: keys,
            "__next_window": np.full(
                len(keys), (self.next_window or 0) + (self.base_bin or 0), dtype=np.int64
            ),
        }
        if self.dict_key_fields:
            cols.update(self.key_dict.lookup_columns(keys))
        for f, lane in zip(self.lane_key_fields or [], accs[self.n_user_accs:]):
            cols[f] = lane
        for i, a in enumerate(accs[: self.n_user_accs]):
            cols[f"__acc_{i}"] = a
        tbl.replace_all([Batch(cols)])


@register_operator(OpName.SLIDING_AGGREGATE)
def _make_sliding(cfg: dict):
    return SlidingAggregate(cfg)
