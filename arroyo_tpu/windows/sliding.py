"""Sliding (hop) window aggregate operator.

Reference behavior: crates/arroyo-worker/src/arrow/
sliding_aggregating_window.rs:45 — bin incoming rows by the *slide*; keep
per-bin partial aggregates; at each slide boundary the watermark passes,
combine the partials of the ``width/slide`` bins in [end-width, end) and emit
one row per key, stamping the window start as the output timestamp (:194,
:217-225); partials are retained until the last window containing them closes
(:161-162 flush/expire at ``bin_end - width + slide``).

TPU-native redesign: the per-bin partials live in HBM inside the same
DeviceHashAggregator the tumbling operator uses (bin = slide index); the
window-close combine is a non-destructive device range-scan of the
contributing bins (position-chunked so ranges larger than the emit buffer are
never truncated) followed by a vectorized host combine-by-key — the scanned
data is already reduced to distinct (bin, key) pairs, so it is tiny relative
to the event stream the device reduced.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..config import config
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec
from ..types import Watermark
from .tumbling import WINDOW_END, WINDOW_START, KeyDictionary, acc_plan


class SlidingAggregate(Operator):
    """config: width_micros, slide_micros, key_fields: list[str], aggregates:
    [(name, kind, Expr|None)], final_projection: [(name, Expr)]|None,
    input_dtype_of, backend override."""

    def __init__(self, cfg: dict):
        self.width = int(cfg["width_micros"])
        self.slide = int(cfg["slide_micros"])
        if self.width % self.slide != 0 or self.width <= 0 or self.slide <= 0:
            raise ValueError(
                f"hop window width ({self.width}us) must be a positive multiple "
                f"of the slide ({self.slide}us)"
            )
        self.nb = self.width // self.slide  # bins per window
        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        self.final_projection = cfg.get("final_projection")
        dtype_of = cfg.get("input_dtype_of") or (lambda e: np.dtype(np.float64))
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        self.backend = cfg.get("backend") or (
            "jax" if config().get("device.enabled") else "numpy"
        )
        self._agg = None
        self.key_dict = KeyDictionary(self.key_fields)
        self.base_bin: Optional[int] = None  # abs slide-bin offset
        self.min_bin: Optional[int] = None  # earliest live rel bin
        self.max_bin: Optional[int] = None  # latest rel bin seen
        self.next_window: Optional[int] = None  # rel start-bin of next window to emit
        self.late_rows = 0

    # ------------------------------------------------------------------

    def tables(self):
        # a bin's partials live until the last window containing it closes
        return [TableSpec("t", "expiring_time_key", retention_micros=self.width)]

    def _aggregator(self):
        if self._agg is None:
            from ..ops.slot_agg import SlotAggregator

            dev = config().section("device")
            self._agg = SlotAggregator(
                self.acc_kinds,
                self.acc_dtypes,
                cap=dev.get("table-capacity", 65536),
                batch_cap=dev.get("batch-capacity", 8192),
                emit_cap=dev.get("emit-capacity", 8192),
                backend=self.backend,
                region_size=dev.get("region-size", 2048),
            )
        return self._agg

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        batches = tbl.all_batches()
        if batches:
            self._restore_from_batch(Batch.concat(batches))
            tbl.replace_all([])

    def _restore_from_batch(self, b: Batch) -> None:
        hashes = b.keys.astype(np.uint64)
        bins_abs = b.timestamps // self.slide
        self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int32)
        accs = [b[f"__acc_{i}"].astype(d) for i, d in enumerate(self.acc_dtypes)]
        self._aggregator().restore(hashes, rel, accs)
        self.min_bin = int(rel.min())
        self.max_bin = int(rel.max())
        if "__next_window" in b:
            # stored absolute; aligned barriers mean all prior subtasks saw the
            # same watermark, so max is a safe merge across rescaled inputs
            self.next_window = int(b["__next_window"].max()) - self.base_bin
        else:
            self.next_window = self.min_bin - self.nb + 1
        if self.key_fields:
            self.key_dict.observe(hashes, rel, b)

    # ------------------------------------------------------------------

    def process_batch(self, batch, ctx, collector, input_index=0):
        ts = batch.timestamps
        bins_abs = ts // self.slide
        if self.base_bin is None:
            self.base_bin = int(bins_abs.min())
        rel = (bins_abs - self.base_bin).astype(np.int64)
        if self.next_window is not None:
            # a row whose own bin's last window already fired is late
            late = rel < self.next_window
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
                rel = rel[~late]
        rel = rel.astype(np.int32)
        n = batch.num_rows
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64)
        else:
            hashes = np.zeros(n, dtype=np.uint64)
        self.key_dict.observe(hashes, rel, batch)
        vals = []
        for inp, dt in zip(self.acc_inputs, self.acc_dtypes):
            if inp is None:
                vals.append(np.ones(n, dtype=dt))
            else:
                vals.append(np.asarray(eval_expr(inp, batch.columns, n)).astype(dt))
        self._aggregator().update(hashes, rel, vals)
        lo, hi = int(rel.min()), int(rel.max())
        self.min_bin = lo if self.min_bin is None else min(self.min_bin, lo)
        self.max_bin = hi if self.max_bin is None else max(self.max_bin, hi)
        if self.next_window is None:
            self.next_window = self.min_bin - self.nb + 1

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            return watermark
        if self.base_bin is not None:
            # window starting at rel bin B closes when wm >= (base+B)*slide + width
            last_closed = (watermark.value - self.width) // self.slide - self.base_bin
            self._emit_through(int(last_closed), collector)
        # future emissions are stamped with window starts strictly after the
        # last closed boundary; forward that lower bound (see tumbling)
        held = ((watermark.value - self.width) // self.slide + 1) * self.slide
        return Watermark.event_time(min(watermark.value, held))

    def on_close(self, ctx, collector):
        if self.max_bin is not None:
            self._emit_through(self.max_bin, collector)

    def _emit_through(self, last_start_rel: int, collector) -> None:
        """Emit every unfired window whose start bin is <= last_start_rel."""
        if self.next_window is None:
            return
        agg = self._aggregator()
        while self.next_window <= last_start_rel:
            b = self.next_window
            if self.max_bin is not None and b > self.max_bin:
                # nothing at or after this window's start; fast-forward
                self.next_window = last_start_rel + 1
                break
            if self.min_bin is not None and b + self.nb <= self.min_bin:
                # gap: window lies entirely before the earliest live bin
                nw = min(last_start_rel + 1, self.min_bin - self.nb + 1)
                self.next_window = max(nw, b + 1)
                agg.free_bins_below(self.next_window)
                self.key_dict.evict_closed(self.next_window)
                continue
            keys, _bins, accs = agg.scan_range(b, b + self.nb)
            if len(keys) == 0:
                # bins < b are freed, so an empty scan proves every live bin
                # is >= b + nb: re-arm the gap fast-forward above
                self.min_bin = b + self.nb
            if len(keys):
                from ..ops.aggregate import combine_by_key

                keys_c, accs_c = combine_by_key(self.acc_kinds, keys, accs)
                self._emit_window(b, keys_c, accs_c, collector)
            self.next_window = b + 1
            # bins below the next window's range are done
            agg.free_bins_below(self.next_window)
            self.key_dict.evict_closed(self.next_window)
            if self.min_bin is not None:
                self.min_bin = max(self.min_bin, self.next_window)

    def _emit_window(self, start_rel: int, keys, accs, collector) -> None:
        from ..ops.aggregate import finalize_aggs

        start = (start_rel + self.base_bin) * self.slide
        n = len(keys)
        cols: dict[str, np.ndarray] = {}
        cols.update(self.key_dict.lookup_columns(keys))
        cols[WINDOW_START] = np.full(n, start, dtype=np.int64)
        cols[WINDOW_END] = np.full(n, start + self.width, dtype=np.int64)
        finals = finalize_aggs([a[1] for a in self.aggregates], accs)
        for (name, _k, _e), arr in zip(self.aggregates, finals):
            cols[name] = arr
        # reference stamps the window start as the output event time (:217)
        cols[TIMESTAMP_FIELD] = np.full(n, start, dtype=np.int64)
        out = Batch(cols)
        if self.final_projection is not None:
            proj = {name: eval_expr(e, out.columns, n) for name, e in self.final_projection}
            if TIMESTAMP_FIELD not in proj:
                proj[TIMESTAMP_FIELD] = out.timestamps
            out = Batch(proj)
        collector.collect(out)

    # ------------------------------------------------------------------

    def handle_checkpoint(self, barrier, ctx, collector):
        keys, bins, accs = self._aggregator().snapshot()
        tbl = ctx.table_manager.expiring_time_key("t", self.width)
        if len(keys) == 0:
            tbl.replace_all([])
            return
        starts = (bins.astype(np.int64) + (self.base_bin or 0)) * self.slide
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: starts,
            KEY_FIELD: keys,
            "__next_window": np.full(
                len(keys), (self.next_window or 0) + (self.base_bin or 0), dtype=np.int64
            ),
        }
        cols.update(self.key_dict.lookup_columns(keys))
        for i, a in enumerate(accs):
            cols[f"__acc_{i}"] = a
        tbl.replace_all([Batch(cols)])


@register_operator(OpName.SLIDING_AGGREGATE)
def _make_sliding(cfg: dict):
    return SlidingAggregate(cfg)
