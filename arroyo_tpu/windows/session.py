"""Session window aggregate operator.

Reference behavior: crates/arroyo-worker/src/arrow/
session_aggregating_window.rs:51 — per-key session tracking with gap merges
(data-dependent windows); input buffered until the watermark passes
``session_end = last_event + gap``; per-key session metadata in a global
table (:763-897).

TPU-native redesign (SURVEY.md §7 hard-part 4): data-dependent session merges
are hostile to static shapes, so session bookkeeping stays host-side — but
instead of buffering raw rows like the reference (whose DataFusion plans need
them), we exploit that every supported aggregate (sum/count/min/max/avg) is
mergeable: each batch is collapsed to provisional per-(key, run) partial
accumulators with one vectorized sort + segment-reduce, and only those
partials (a few per key per batch) hit the Python merge loop. Session merges
combine accumulators, never rows.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec
from ..types import Watermark
from .tumbling import WINDOW_END, WINDOW_START, acc_plan, dtype_of_from_config


def _combine(kind: str, a, b):
    if kind in ("sum", "count"):
        return a + b
    if kind == "collect":  # UDAF state: collected values
        return list(a) + list(b)
    if kind == "min":
        return min(a, b)
    return max(a, b)


class _Session:
    __slots__ = ("min_ts", "max_ts", "accs")

    def __init__(self, min_ts: int, max_ts: int, accs: list):
        self.min_ts = min_ts
        self.max_ts = max_ts
        self.accs = accs


class SessionAggregate(Operator):
    """config: gap_micros, key_fields, aggregates: [(name, kind, Expr|None)],
    final_projection, input_dtype_of."""

    def __init__(self, cfg: dict):
        self.gap = int(cfg["gap_micros"])
        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        self.final_projection = cfg.get("final_projection")
        dtype_of = dtype_of_from_config(cfg)
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        # key-hash -> sorted-by-min_ts list of open sessions
        self.sessions: dict[int, list[_Session]] = {}
        self.key_values: dict[int, tuple] = {}
        self.emitted_watermark: Optional[int] = None
        self.late_rows = 0

    # ------------------------------------------------------------------

    def tables(self):
        # row timestamp = session max_ts; a session is live while
        # max_ts >= watermark - gap, so retention = gap filters on restore;
        # "e" persists the late-data barrier (reference keeps session
        # metadata in a global table too, session_aggregating_window.rs:763)
        return [
            TableSpec("s", "expiring_time_key", retention_micros=self.gap),
            TableSpec("e", "global_keyed"),
        ]

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("s", self.gap)
        batches = tbl.all_batches()
        if batches:
            self._restore_from_batch(Batch.concat(batches))
            tbl.replace_all([])
        wms = [
            v["emitted_watermark"]
            for _k, v in ctx.table_manager.global_keyed("e").items()
            if v.get("emitted_watermark") is not None
        ]
        if wms:
            # aligned barriers: every prior subtask saw the same watermark
            self.emitted_watermark = max(wms)

    def _restore_from_batch(self, b: Batch) -> None:
        # session dict keys are the SIGNED view of the routing hash (matching
        # process_batch's lexsort path)
        hashes = b.keys.astype(np.uint64).view(np.int64)
        key_cols = [b[f] for f in self.key_fields]
        for j in range(b.num_rows):
            h = int(hashes[j])
            accs = [list(b[f"__acc_{i}"][j]) if self.acc_kinds[i] == "collect"
                    else d.type(b[f"__acc_{i}"][j])
                    for i, d in enumerate(self.acc_dtypes)]
            self._merge_session(
                h, int(b["__min_ts"][j]), int(b["__max_ts"][j]), accs
            )
            if self.key_fields and h not in self.key_values:
                self.key_values[h] = tuple(c[j] for c in key_cols)

    # ------------------------------------------------------------------

    def _merge_session(self, h: int, min_ts: int, max_ts: int, accs: list) -> None:
        """Insert [min_ts, max_ts] into key h's session list, merging every
        existing session within ``gap`` of it."""
        lst = self.sessions.get(h)
        if lst is None:
            self.sessions[h] = [_Session(min_ts, max_ts, accs)]
            return
        merged_min, merged_max, merged_accs = min_ts, max_ts, accs
        kept: list[_Session] = []
        for s in lst:
            if s.max_ts + self.gap >= merged_min and s.min_ts - self.gap <= merged_max:
                merged_min = min(merged_min, s.min_ts)
                merged_max = max(merged_max, s.max_ts)
                merged_accs = [
                    _combine(k, a, b)
                    for k, a, b in zip(self.acc_kinds, merged_accs, s.accs)
                ]
            else:
                kept.append(s)
        kept.append(_Session(merged_min, merged_max, merged_accs))
        kept.sort(key=lambda s: s.min_ts)
        self.sessions[h] = kept

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        ts = batch.timestamps
        if self.emitted_watermark is not None:
            # a row re-opens an already-emitted session iff the session it
            # would form has max_ts + gap <= emitted watermark, i.e. ts <= wm - gap
            late = ts <= self.emitted_watermark - self.gap
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
                ts = batch.timestamps
                n = batch.num_rows
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64)
        else:
            hashes = np.zeros(n, dtype=np.uint64)
        signed = hashes.view(np.int64)
        order = np.lexsort((ts, signed))
        k_s = signed[order]
        t_s = np.asarray(ts)[order]
        # provisional run breaks: key change or time gap > gap
        brk = np.ones(n, dtype=bool)
        if n > 1:
            brk[1:] = (k_s[1:] != k_s[:-1]) | ((t_s[1:] - t_s[:-1]) > self.gap)
        starts = np.flatnonzero(brk)
        ends = np.append(starts[1:], n)
        # per-accumulator values, segment-reduced per provisional run
        vals = []
        for inp, dt, kind in zip(self.acc_inputs, self.acc_dtypes, self.acc_kinds):
            if kind == "collect":
                v = np.asarray(eval_expr(inp, batch.columns, n))[order]
                vals.append([v[si:ei].tolist() for si, ei in zip(starts, ends)])
                continue
            if inp is None:
                v = np.ones(n, dtype=dt)
            else:
                v = np.asarray(eval_expr(inp, batch.columns, n)).astype(dt)
            v = v[order]
            if kind in ("sum", "count"):
                vals.append(np.add.reduceat(v, starts))
            elif kind == "min":
                vals.append(np.minimum.reduceat(v, starts))
            else:
                vals.append(np.maximum.reduceat(v, starts))
        if self.key_fields:
            cols = [np.asarray(batch[f])[order] for f in self.key_fields]
            for si in starts:
                h = int(k_s[si])
                if h not in self.key_values:
                    self.key_values[h] = tuple(c[si] for c in cols)
        for i, (si, ei) in enumerate(zip(starts, ends)):
            accs = [vals[j][i] if self.acc_kinds[j] == "collect"
                    else self.acc_dtypes[j].type(vals[j][i])
                    for j in range(len(vals))]
            self._merge_session(int(k_s[si]), int(t_s[si]), int(t_s[ei - 1]), accs)

    # ------------------------------------------------------------------

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            return watermark
        self._emit_closed(watermark.value, collector)
        self.emitted_watermark = watermark.value
        # future emissions are stamped window_start = session min_ts: open
        # sessions may hold arbitrarily old starts, and brand-new sessions
        # can begin at ts > w - gap; forward the lower bound (see tumbling)
        held = watermark.value - self.gap
        for lst in self.sessions.values():
            for s in lst:
                if s.min_ts < held:
                    held = s.min_ts
        return Watermark.event_time(held)

    def on_close(self, ctx, collector):
        self._emit_closed(None, collector)

    def _emit_closed(self, watermark: Optional[int], collector) -> None:
        rows: list[tuple[int, _Session]] = []
        dead_keys = []
        for h, lst in self.sessions.items():
            if watermark is None:
                closed, kept = lst, []
            else:
                closed = [s for s in lst if s.max_ts + self.gap <= watermark]
                kept = [s for s in lst if s.max_ts + self.gap > watermark]
            rows.extend((h, s) for s in closed)
            if kept:
                self.sessions[h] = kept
            else:
                dead_keys.append(h)
        if rows:
            self._emit_rows(rows, collector)
        for h in dead_keys:
            del self.sessions[h]
            self.key_values.pop(h, None)

    def _emit_rows(self, rows, collector) -> None:
        from ..ops.aggregate import finalize_aggs

        n = len(rows)
        starts = np.array([s.min_ts for _h, s in rows], dtype=np.int64)
        ends = np.array([s.max_ts + self.gap for _h, s in rows], dtype=np.int64)
        cols: dict[str, np.ndarray] = {}
        if self.key_fields:
            for j, f in enumerate(self.key_fields):
                sample = next(
                    (self.key_values[h][j] for h, _s in rows if h in self.key_values),
                    None,
                )
                vals = [
                    self.key_values.get(h, (None,) * len(self.key_fields))[j]
                    for h, _s in rows
                ]
                if isinstance(sample, (str, type(None))):
                    cols[f] = np.array(vals, dtype=object)
                else:
                    cols[f] = np.array(vals)
        cols[WINDOW_START] = starts
        cols[WINDOW_END] = ends
        from ..batch import object_column

        acc_arrays = [
            object_column(s.accs[i] for _h, s in rows)
            if self.acc_kinds[i] == "collect"
            else np.array([s.accs[i] for _h, s in rows], dtype=d)
            for i, d in enumerate(self.acc_dtypes)
        ]
        finals = finalize_aggs([a[1] for a in self.aggregates], acc_arrays)
        for (name, _k, _e), arr in zip(self.aggregates, finals):
            cols[name] = arr
        cols[TIMESTAMP_FIELD] = starts
        out = Batch(cols)
        if self.final_projection is not None:
            proj = {
                name: eval_expr(e, out.columns, n) for name, e in self.final_projection
            }
            if TIMESTAMP_FIELD not in proj:
                proj[TIMESTAMP_FIELD] = out.timestamps
            out = Batch(proj)
        collector.collect(out)

    # ------------------------------------------------------------------

    def handle_checkpoint(self, barrier, ctx, collector):
        ctx.table_manager.global_keyed("e").insert(
            ctx.task_info.subtask_index,
            {"emitted_watermark": self.emitted_watermark},
        )
        tbl = ctx.table_manager.expiring_time_key("s", self.gap)
        items = [(h, s) for h, lst in self.sessions.items() for s in lst]
        if not items:
            tbl.replace_all([])
            return
        n = len(items)
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: np.array([s.max_ts for _h, s in items], dtype=np.int64),
            KEY_FIELD: np.array([h for h, _s in items], dtype=np.int64).view(np.uint64),
            "__min_ts": np.array([s.min_ts for _h, s in items], dtype=np.int64),
            "__max_ts": np.array([s.max_ts for _h, s in items], dtype=np.int64),
        }
        from ..batch import object_column

        for i, d in enumerate(self.acc_dtypes):
            if self.acc_kinds[i] == "collect":
                cols[f"__acc_{i}"] = object_column(list(s.accs[i]) for _h, s in items)
            else:
                cols[f"__acc_{i}"] = np.array([s.accs[i] for _h, s in items], dtype=d)
        if self.key_fields:
            for j, f in enumerate(self.key_fields):
                vals = [
                    self.key_values.get(h, (None,) * len(self.key_fields))[j]
                    for h, _s in items
                ]
                sample = next((v for v in vals if v is not None), None)
                if isinstance(sample, (str, type(None))):
                    cols[f] = np.array(vals, dtype=object)
                else:
                    cols[f] = np.array(vals)
        tbl.replace_all([Batch(cols)])


@register_operator(OpName.SESSION_AGGREGATE)
def _make_session(cfg: dict):
    return SessionAggregate(cfg)
