"""Session window aggregate operator.

Reference behavior: crates/arroyo-worker/src/arrow/
session_aggregating_window.rs:51 — per-key session tracking with gap merges
(data-dependent windows); input buffered until the watermark passes
``session_end = last_event + gap``; per-key session metadata in a global
table (:763-897).

TPU-native redesign (SURVEY.md §7 hard-part 4): data-dependent session merges
are hostile to static shapes, so session bookkeeping stays host-side — but
instead of buffering raw rows like the reference (whose DataFusion plans need
them), we exploit that every supported aggregate (sum/count/min/max/avg) is
mergeable: each batch is collapsed to provisional per-(key, run) partial
accumulators with one vectorized sort + segment-reduce, and only those
partials hit the session merge. The merge itself is array-resident too: open
sessions live in parallel numpy columns (key, min_ts, max_ts, acc...) and
gap-merging is one lexsort + segmented running-max scan per batch — no
per-key Python objects, so key cardinality is bounded by memory, not by
interpreter speed.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Batch, object_column
from ..engine.engine import register_operator
from ..expr import eval_expr
from ..graph import OpName
from ..operators.base import Operator, TableSpec, persist_mark, restore_marks
from ..types import Watermark
from .tumbling import WINDOW_END, WINDOW_START, acc_plan, dtype_of_from_config

# base for the exclusive running max: low enough that +gap never overflows
_REACH_MIN = np.iinfo(np.int64).min // 4


def _seg_cummax_excl(seg_new: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Exclusive segmented running max: out[i] = max(vals[j]) over j < i
    within i's segment (segments start where seg_new is True); _REACH_MIN at
    segment starts. Hillis-Steele segmented scan — O(n log n) in vectorized
    passes, no Python per-element work."""
    n = len(vals)
    out = np.empty(n, dtype=np.int64)
    out[0] = _REACH_MIN
    if n > 1:
        out[1:] = np.where(seg_new[1:], _REACH_MIN, vals[:-1])
    flag = seg_new.copy()
    d = 1
    while d < n:
        nxt = out.copy()
        np.maximum(out[d:], out[:-d], out=nxt[d:], where=~flag[d:])
        nflag = flag.copy()
        nflag[d:] |= flag[:-d]
        out, flag = nxt, nflag
        d *= 2
    return out


class SessionAggregate(Operator):
    """config: gap_micros, key_fields, aggregates: [(name, kind, Expr|None)],
    final_projection, input_dtype_of."""

    def __init__(self, cfg: dict):
        self.gap = int(cfg["gap_micros"])
        self.key_fields: list[str] = list(cfg.get("key_fields", ()))
        self.aggregates = cfg["aggregates"]
        self.final_projection = cfg.get("final_projection")
        dtype_of = dtype_of_from_config(cfg)
        self.acc_kinds, self.acc_dtypes, self.acc_inputs = acc_plan(self.aggregates, dtype_of)
        # open sessions as parallel columns (sorted within each merge group)
        self.s_key = np.empty(0, dtype=np.int64)   # signed view of routing hash
        self.s_min = np.empty(0, dtype=np.int64)
        self.s_max = np.empty(0, dtype=np.int64)
        self.s_accs: list[np.ndarray] = [np.empty(0, dtype=d) for d in self.acc_dtypes]
        # per-key-field value columns; created lazily with the input's dtype
        self.s_keycols: Optional[list[np.ndarray]] = None
        self.emitted_watermark: Optional[int] = None
        self.late_rows = 0  # state: ephemeral — observability counter (obs/profile.py export); never read into emitted data

    # ------------------------------------------------------------------

    def tables(self):
        # row timestamp = session max_ts; a session is live while
        # max_ts >= watermark - gap, so retention = gap filters on restore;
        # "e" persists the late-data barrier (reference keeps session
        # metadata in a global table too, session_aggregating_window.rs:763)
        return [
            TableSpec("s", "expiring_time_key", retention_micros=self.gap),
            TableSpec("e", "global_keyed"),
        ]

    def on_start(self, ctx):
        tbl = ctx.table_manager.expiring_time_key("s", self.gap)
        batches = tbl.all_batches()
        if batches:
            self._restore_from_batch(Batch.concat(batches))
            tbl.replace_all([])
        wms = [v["emitted_watermark"] for v in restore_marks(ctx, "e")
               if v.get("emitted_watermark") is not None]
        if wms:
            # aligned barriers: every prior subtask saw the same watermark
            self.emitted_watermark = max(wms)

    def _restore_from_batch(self, b: Batch) -> None:
        # session columns use the SIGNED view of the routing hash (matching
        # process_batch's lexsort path); rescale restore can bring the same
        # key's sessions from several prior subtasks -> coalesce merges them
        key = b.keys.astype(np.uint64).view(np.int64)
        accs = []
        for i, d in enumerate(self.acc_dtypes):
            col = b[f"__acc_{i}"]
            if self.acc_kinds[i] == "collect":
                accs.append(object_column(list(v) for v in col))
            else:
                accs.append(np.asarray(col).astype(d, copy=True))
        keycols = [np.asarray(b[f]).copy() for f in self.key_fields]
        (self.s_key, self.s_min, self.s_max, self.s_accs, kc) = self._coalesce(
            key, np.asarray(b["__min_ts"], dtype=np.int64),
            np.asarray(b["__max_ts"], dtype=np.int64), accs, keycols)
        self.s_keycols = kc if self.key_fields else []

    # ------------------------------------------------------------------

    def _coalesce(self, key, mn, mx, accs, keycols):
        """Gap-merge candidate sessions (existing + new runs): one lexsort
        by (key, min_ts), an exclusive segmented running max of max_ts, and
        segment reduces for the accumulators."""
        order = np.lexsort((mn, key))
        key, mn, mx = key[order], mn[order], mx[order]
        n = len(key)
        seg_new = np.empty(n, dtype=bool)
        seg_new[0] = True
        seg_new[1:] = key[1:] != key[:-1]
        reach = _seg_cummax_excl(seg_new, mx)
        starts_new = seg_new | (mn > reach + self.gap)
        g0 = np.flatnonzero(starts_new)
        out_accs = []
        for kind, a in zip(self.acc_kinds, accs):
            a = a[order]
            if kind == "collect":
                ends = np.append(g0[1:], n)
                merged = []
                for s, e in zip(g0, ends):
                    if e - s == 1:
                        merged.append(a[s])
                    else:
                        acc: list = []
                        for lst in a[s:e]:
                            acc.extend(lst)
                        merged.append(acc)
                out_accs.append(object_column(merged))
            elif kind in ("sum", "count"):
                out_accs.append(np.add.reduceat(a, g0))
            elif kind == "min":
                out_accs.append(np.minimum.reduceat(a, g0))
            else:
                out_accs.append(np.maximum.reduceat(a, g0))
        # sorted by min_ts within each key: the group start holds the min
        return (key[g0], mn[g0], np.maximum.reduceat(mx, g0), out_accs,
                [c[order][g0] for c in keycols])

    def process_batch(self, batch, ctx, collector, input_index=0):
        n = batch.num_rows
        if n == 0:
            return
        ts = batch.timestamps
        if self.emitted_watermark is not None:
            # a row re-opens an already-emitted session iff the session it
            # would form has max_ts + gap <= emitted watermark, i.e. ts <= wm - gap
            late = ts <= self.emitted_watermark - self.gap
            if late.any():
                self.late_rows += int(late.sum())
                if late.all():
                    return
                batch = batch.filter(~late)
                ts = batch.timestamps
                n = batch.num_rows
        if KEY_FIELD in batch:
            hashes = batch.keys.astype(np.uint64)
        else:
            hashes = np.zeros(n, dtype=np.uint64)
        signed = hashes.view(np.int64)
        order = np.lexsort((ts, signed))
        k_s = signed[order]
        t_s = np.asarray(ts)[order]
        # provisional run breaks: key change or time gap > gap
        brk = np.ones(n, dtype=bool)
        if n > 1:
            brk[1:] = (k_s[1:] != k_s[:-1]) | ((t_s[1:] - t_s[:-1]) > self.gap)
        starts = np.flatnonzero(brk)
        ends = np.append(starts[1:], n)
        # per-accumulator values, segment-reduced per provisional run
        run_accs: list[np.ndarray] = []
        for inp, dt, kind in zip(self.acc_inputs, self.acc_dtypes, self.acc_kinds):
            if kind == "collect":
                v = np.asarray(eval_expr(inp, batch.columns, n))[order]
                run_accs.append(object_column(
                    v[si:ei].tolist() for si, ei in zip(starts, ends)))
                continue
            if inp is None:
                v = np.ones(n, dtype=dt)
            else:
                v = np.asarray(eval_expr(inp, batch.columns, n)).astype(dt)
            v = v[order]
            if kind in ("sum", "count"):
                run_accs.append(np.add.reduceat(v, starts))
            elif kind == "min":
                run_accs.append(np.minimum.reduceat(v, starts))
            else:
                run_accs.append(np.maximum.reduceat(v, starts))
        run_keycols = [np.asarray(batch[f])[order][starts] for f in self.key_fields]
        run_key, run_min, run_max = k_s[starts], t_s[starts], t_s[ends - 1]
        self._merge_runs(run_key, run_min, run_max, run_accs, run_keycols)

    def _merge_runs(self, r_key, r_min, r_max, r_accs, r_keycols) -> None:
        if self.s_keycols is None:
            self.s_keycols = [c[:0] for c in r_keycols]
        if len(self.s_key) == 0:
            # runs from one batch are already gap-separated per key
            self.s_key, self.s_min, self.s_max = r_key, r_min, r_max
            self.s_accs, self.s_keycols = list(r_accs), list(r_keycols)
            return
        # only sessions whose key appears in this batch can merge; leave the
        # (potentially much larger) untouched remainder alone
        touched = np.isin(self.s_key, r_key)
        if touched.any():
            t = touched
            key = np.concatenate([self.s_key[t], r_key])
            mn = np.concatenate([self.s_min[t], r_min])
            mx = np.concatenate([self.s_max[t], r_max])
            accs = [np.concatenate([sa[t], ra]) for sa, ra in zip(self.s_accs, r_accs)]
            kcs = [np.concatenate([sc[t], rc])
                   for sc, rc in zip(self.s_keycols, r_keycols)]
            m_key, m_min, m_max, m_accs, m_kcs = self._coalesce(key, mn, mx, accs, kcs)
            keep = ~touched
            self.s_key = np.concatenate([self.s_key[keep], m_key])
            self.s_min = np.concatenate([self.s_min[keep], m_min])
            self.s_max = np.concatenate([self.s_max[keep], m_max])
            self.s_accs = [np.concatenate([sa[keep], ma])
                           for sa, ma in zip(self.s_accs, m_accs)]
            self.s_keycols = [np.concatenate([sc[keep], mc])
                              for sc, mc in zip(self.s_keycols, m_kcs)]
        else:
            self.s_key = np.concatenate([self.s_key, r_key])
            self.s_min = np.concatenate([self.s_min, r_min])
            self.s_max = np.concatenate([self.s_max, r_max])
            self.s_accs = [np.concatenate([sa, ra])
                           for sa, ra in zip(self.s_accs, r_accs)]
            self.s_keycols = [np.concatenate([sc, rc])
                              for sc, rc in zip(self.s_keycols, r_keycols)]

    # ------------------------------------------------------------------

    def handle_watermark(self, watermark, ctx, collector):
        if watermark.is_idle:
            return watermark
        self._emit_closed(watermark.value, collector)
        self.emitted_watermark = watermark.value
        # future emissions are stamped window_start = session min_ts: open
        # sessions may hold arbitrarily old starts, and brand-new sessions
        # can begin at ts > w - gap; forward the lower bound (see tumbling)
        held = watermark.value - self.gap
        if len(self.s_min):
            held = min(held, int(self.s_min.min()))
        return Watermark.event_time(held)

    def on_close(self, ctx, collector):
        self._emit_closed(None, collector)

    def _emit_closed(self, watermark: Optional[int], collector) -> None:
        if len(self.s_key) == 0:
            return
        if watermark is None:
            closed = np.ones(len(self.s_key), dtype=bool)
        else:
            closed = self.s_max + self.gap <= watermark
        if not closed.any():
            return
        self._emit_rows(closed, collector)
        keep = ~closed
        self.s_key, self.s_min, self.s_max = (
            self.s_key[keep], self.s_min[keep], self.s_max[keep])
        self.s_accs = [a[keep] for a in self.s_accs]
        self.s_keycols = [c[keep] for c in self.s_keycols]

    def _emit_rows(self, closed: np.ndarray, collector) -> None:
        from ..ops.aggregate import finalize_aggs

        mn, mx, key = self.s_min[closed], self.s_max[closed], self.s_key[closed]
        # deterministic emission order: by (window_start, key); one fused
        # gather index instead of mask-then-permute per column
        idx = np.flatnonzero(closed)[np.lexsort((key, mn))]
        starts = self.s_min[idx]
        n = len(starts)
        cols: dict[str, np.ndarray] = {}
        for f, c in zip(self.key_fields, self.s_keycols):
            cols[f] = c[idx]
        cols[WINDOW_START] = starts
        cols[WINDOW_END] = self.s_max[idx] + self.gap
        finals = finalize_aggs([a[1] for a in self.aggregates],
                               [a[idx] for a in self.s_accs])
        for (name, _k, _e), arr in zip(self.aggregates, finals):
            cols[name] = arr
        cols[TIMESTAMP_FIELD] = starts
        out = Batch(cols)
        if self.final_projection is not None:
            proj = {
                name: eval_expr(e, out.columns, n) for name, e in self.final_projection
            }
            if TIMESTAMP_FIELD not in proj:
                proj[TIMESTAMP_FIELD] = out.timestamps
            out = Batch(proj)
        collector.collect(out)

    # ------------------------------------------------------------------

    def handle_checkpoint(self, barrier, ctx, collector):
        persist_mark(ctx, "e", {"emitted_watermark": self.emitted_watermark})
        tbl = ctx.table_manager.expiring_time_key("s", self.gap)
        n = len(self.s_key)
        if n == 0:
            tbl.replace_all([])
            return
        cols: dict[str, np.ndarray] = {
            TIMESTAMP_FIELD: self.s_max.copy(),
            KEY_FIELD: self.s_key.view(np.uint64).copy(),
            "__min_ts": self.s_min.copy(),
            "__max_ts": self.s_max.copy(),
        }
        for i, kind in enumerate(self.acc_kinds):
            if kind == "collect":
                cols[f"__acc_{i}"] = object_column(list(v) for v in self.s_accs[i])
            else:
                cols[f"__acc_{i}"] = self.s_accs[i].copy()
        for f, c in zip(self.key_fields, self.s_keycols):
            cols[f] = c.copy()
        tbl.replace_all([Batch(cols)])


@register_operator(OpName.SESSION_AGGREGATE)
def _make_session(cfg: dict):
    return SessionAggregate(cfg)
