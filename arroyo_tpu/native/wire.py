"""Columnar wire codec: Batch <-> bytes for the data plane.

Plays the role of Arrow IPC in the reference's network manager
(network_manager.rs:279-287 encoded_batch): self-describing little-endian
columnar frames. Layout:

  u32 header_len | header json | per-column payloads (in header order)

header: {"n": rows, "cols": [{"name", "dtype", "nbytes"}, ...]}
String columns serialize as i64 offsets[n+1] + utf-8 arena (None -> offset
pair with sentinel -1 length encoded via a null bitmap appended after the
arena). Signals serialize via a tiny tagged json payload.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from ..batch import Batch, Field
from ..types import CheckpointBarrier, Signal, SignalKind, Watermark


def encode_batch(batch: Batch) -> bytes:
    header_cols = []
    payloads: list[bytes] = []
    for name, col in batch.columns.items():
        if col.dtype == object:
            offs = np.zeros(len(col) + 1, dtype=np.int64)
            # per-value tag: 0 = utf-8 string, 1 = null, 2 = raw bytes
            tags = np.zeros(len(col), dtype=np.uint8)
            parts = []
            total = 0
            for i, v in enumerate(col):
                if v is None:
                    tags[i] = 1
                    b = b""
                elif isinstance(v, bytes):
                    tags[i] = 2
                    b = v
                else:
                    b = str(v).encode("utf-8")
                parts.append(b)
                total += len(b)
                offs[i + 1] = total
            payload = offs.tobytes() + b"".join(parts) + tags.tobytes()
            header_cols.append({"name": name, "dtype": "string", "nbytes": len(payload)})
            payloads.append(payload)
        else:
            c = np.ascontiguousarray(col)
            payload = c.tobytes()
            header_cols.append({
                "name": name, "dtype": c.dtype.str, "nbytes": len(payload),
            })
            payloads.append(payload)
    header = json.dumps({"n": batch.num_rows, "cols": header_cols}).encode()
    return struct.pack("<I", len(header)) + header + b"".join(payloads)


def decode_batch(data: bytes) -> Batch:
    (hlen,) = struct.unpack_from("<I", data, 0)
    header = json.loads(data[4 : 4 + hlen])
    n = header["n"]
    off = 4 + hlen
    cols: dict[str, np.ndarray] = {}
    for c in header["cols"]:
        payload = data[off : off + c["nbytes"]]
        off += c["nbytes"]
        if c["dtype"] == "string":
            offs = np.frombuffer(payload[: 8 * (n + 1)], dtype=np.int64)
            arena_end = 8 * (n + 1) + int(offs[-1])
            arena = payload[8 * (n + 1) : arena_end]
            tags = np.frombuffer(payload[arena_end : arena_end + n], dtype=np.uint8)
            col = np.empty(n, dtype=object)
            for i in range(n):
                if tags[i] == 1:
                    col[i] = None
                elif tags[i] == 2:
                    col[i] = arena[offs[i] : offs[i + 1]]
                else:
                    col[i] = arena[offs[i] : offs[i + 1]].decode("utf-8")
            cols[c["name"]] = col
        else:
            cols[c["name"]] = np.frombuffer(payload, dtype=np.dtype(c["dtype"])).copy()
    return Batch(cols)


def encode_signal(sig: Signal) -> bytes:
    d: dict = {"kind": sig.kind.value}
    if sig.watermark is not None:
        d["watermark"] = sig.watermark.value
        d["has_wm"] = True
    if sig.barrier is not None:
        b = sig.barrier
        d["barrier"] = [b.epoch, b.min_epoch, b.timestamp, b.then_stop]
    return json.dumps(d).encode()


def decode_signal(data: bytes) -> Signal:
    d = json.loads(data)
    wm = Watermark(d["watermark"]) if d.get("has_wm") else None
    barrier = None
    if "barrier" in d:
        e, m, t, s = d["barrier"]
        barrier = CheckpointBarrier(e, m, t, s)
    return Signal(SignalKind(d["kind"]), watermark=wm, barrier=barrier)
