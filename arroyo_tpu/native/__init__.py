"""ctypes bindings for the C++ host runtime (cpp/arroyo_host.cc).

The library is built on first use with `make -C cpp` (g++ is in the image)
and cached next to the sources. Every entry point has a NumPy fallback so
the framework still runs if the toolchain is unavailable; the config flag
``native.enabled`` force-disables the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "cpp")
_LIB_PATH = os.path.join(_CPP_DIR, "libarroyo_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", _CPP_DIR],
            capture_output=True, text=True, timeout=120,
        )
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        from ..config import config

        if not config().get("native.enabled", True):
            _lib_failed = True
            return None
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(os.path.join(_CPP_DIR, "arroyo_host.cc"))
            and os.path.getmtime(os.path.join(_CPP_DIR, "arroyo_host.cc"))
            > os.path.getmtime(_LIB_PATH)
        ):
            if not _build():
                _lib_failed = True
                return None
        try:
            l = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _lib_failed = True
            return None
        try:
            _declare(l)
        except AttributeError:
            # missing/renamed symbol (stale or incompatible .so): honor the
            # module contract — degrade to the NumPy fallbacks, never crash
            _lib_failed = True
            return None
        _lib = l
        return _lib


def _declare(l: ctypes.CDLL) -> None:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    l.ah_hash_u64.argtypes = [u64p, u64p, ctypes.c_int64]
    l.ah_hash_combine.argtypes = [u64p, u64p, ctypes.c_int64]
    l.ah_hash_f64.argtypes = [f64p, u64p, ctypes.c_int64]
    l.ah_partition.argtypes = [u64p, ctypes.c_int64, ctypes.c_int32, i64p, i64p]
    l.ah_partition.restype = ctypes.c_int
    l.ah_dir_resolve.argtypes = [
        i64p, i64p, ctypes.c_int64,          # keys, bins, n
        u64p, i64p, i64p,                    # hcode, hbin, hslot
        ctypes.c_int64, ctypes.c_int64,      # hcap, boundary
        i64p, i64p,                          # slot_keys, slot_bins
        i64p, i64p,                          # out_slots, miss_ord
        u64p, i64p, i64p,                    # miss_codes, miss_keys, miss_bins
    ]
    l.ah_dir_resolve.restype = ctypes.c_int64
    l.ah_parse_json_lines.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(i64p), ctypes.POINTER(f64p), ctypes.POINTER(u8p),
        ctypes.POINTER(i64p), ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int64),
    ]
    l.ah_parse_json_lines.restype = ctypes.c_int64
    l.ah_free.argtypes = [ctypes.c_void_p]
    l.dp_listen.argtypes = [ctypes.c_char_p, ctypes.c_int]
    l.dp_listen.restype = ctypes.c_int
    l.dp_bound_port.argtypes = [ctypes.c_int]
    l.dp_bound_port.restype = ctypes.c_int
    l.dp_accept.argtypes = [ctypes.c_int]
    l.dp_accept.restype = ctypes.c_int
    l.dp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
    l.dp_connect.restype = ctypes.c_int
    l.dp_send_frame.argtypes = [
        ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32,
    ]
    l.dp_send_frame.restype = ctypes.c_int
    l.dp_recv_header.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_uint32)]
    l.dp_recv_header.restype = ctypes.c_int
    l.dp_recv_payload.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
    l.dp_recv_payload.restype = ctypes.c_int
    l.dp_close.argtypes = [ctypes.c_int]


def available() -> bool:
    return lib() is not None


# --------------------------------------------------------------- hashing


def _u64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def hash_u64(arr: np.ndarray) -> Optional[np.ndarray]:
    l = lib()
    if l is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.uint64)
    out = np.empty(len(arr), dtype=np.uint64)
    l.ah_hash_u64(_u64p(arr), _u64p(out), len(arr))
    return out


def hash_f64(arr: np.ndarray) -> Optional[np.ndarray]:
    l = lib()
    if l is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.float64)
    out = np.empty(len(arr), dtype=np.uint64)
    l.ah_hash_f64(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), _u64p(out), len(arr))
    return out


def hash_combine(h: np.ndarray, h2: np.ndarray) -> Optional[np.ndarray]:
    l = lib()
    if l is None:
        return None
    h = np.ascontiguousarray(h, dtype=np.uint64).copy()
    h2 = np.ascontiguousarray(h2, dtype=np.uint64)
    l.ah_hash_combine(_u64p(h), _u64p(h2), len(h))
    return h


def partition(hashes: np.ndarray, n_dest: int):
    """(perm, offsets): stable grouping of row indices by destination
    (native counting sort; None if the library is unavailable)."""
    l = lib()
    if l is None:
        return None
    hashes = np.ascontiguousarray(hashes, dtype=np.uint64)
    perm = np.empty(len(hashes), dtype=np.int64)
    offsets = np.empty(n_dest + 1, dtype=np.int64)
    rc = l.ah_partition(
        _u64p(hashes), len(hashes), n_dest,
        perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        return None
    return perm, offsets


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def dir_resolve(keys: np.ndarray, bins: np.ndarray, hcode: np.ndarray,
                hbin: np.ndarray, hslot: np.ndarray, boundary: int,
                slot_keys: np.ndarray, slot_bins: np.ndarray):
    """Single-pass (key,bin)->slot resolution against the slot directory's
    open-addressing arrays (see cpp ah_dir_resolve). Returns (slots,
    miss_ord, miss_codes, miss_keys, miss_bins) or None when the native
    library is unavailable. Raises on 64-bit code collision, matching
    BinSlotDirectory.lookup_or_assign."""
    l = lib()
    if l is None:
        return None
    n = len(keys)
    out_slots = np.empty(n, dtype=np.int64)
    miss_ord = np.empty(n, dtype=np.int64)
    miss_codes = np.empty(n, dtype=np.uint64)
    miss_keys = np.empty(n, dtype=np.int64)
    miss_bins = np.empty(n, dtype=np.int64)
    rc = l.ah_dir_resolve(
        _i64p(keys), _i64p(bins), n,
        _u64p(hcode), _i64p(hbin), _i64p(hslot),
        len(hcode), boundary,
        _i64p(slot_keys), _i64p(slot_bins),
        _i64p(out_slots), _i64p(miss_ord),
        _u64p(miss_codes), _i64p(miss_keys), _i64p(miss_bins),
    )
    if rc == -2:
        raise RuntimeError("64-bit (bin,key) code collision in slot directory")
    if rc < 0:
        return None
    m = int(rc)
    return out_slots, miss_ord, miss_codes[:m], miss_keys[:m], miss_bins[:m]


# -------------------------------------------------------------- JSON lines

_KIND = {"int64": 0, "timestamp": 0, "int32": 0, "uint64": 0,
         "float64": 1, "float32": 1, "bool": 2, "string": 3}


def parse_json_lines(data: bytes, fields: list[tuple[str, str]],
                     max_rows: int) -> Optional[dict[str, np.ndarray]]:
    """Parse newline-delimited flat JSON objects into columns.
    fields: (name, dtype) with dtypes from batch.Schema. Returns None when
    the native library is unavailable or input is malformed (caller falls
    back to the Python parser, which produces the precise error)."""
    l = lib()
    if l is None:
        return None
    n_cols = len(fields)
    if n_cols > 64:
        return None
    kinds = np.array([_KIND.get(d, 4) for _n, d in fields], dtype=np.int32)
    names_blob = b"".join(n.encode() + b"\x00" for n, _d in fields)
    int_arrays, f64_arrays, bool_arrays, off_arrays = {}, {}, {}, {}
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    int_ptrs = (i64p * n_cols)()
    f64_ptrs = (f64p * n_cols)()
    bool_ptrs = (u8p * n_cols)()
    off_ptrs = (i64p * n_cols)()
    for c, (_name, _d) in enumerate(fields):
        k = kinds[c]
        if k == 0:
            a = np.zeros(max_rows, dtype=np.int64)
            int_arrays[c] = a
            int_ptrs[c] = a.ctypes.data_as(i64p)
        elif k == 1:
            a = np.zeros(max_rows, dtype=np.float64)
            f64_arrays[c] = a
            f64_ptrs[c] = a.ctypes.data_as(f64p)
        elif k == 2:
            a = np.zeros(max_rows, dtype=np.uint8)
            bool_arrays[c] = a
            bool_ptrs[c] = a.ctypes.data_as(u8p)
        elif k == 3:
            a = np.zeros(max_rows + 1, dtype=np.int64)
            off_arrays[c] = a
            off_ptrs[c] = a.ctypes.data_as(i64p)
    arena = ctypes.c_char_p()
    arena_len = ctypes.c_int64()
    n = l.ah_parse_json_lines(
        data, len(data), n_cols, names_blob,
        kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_rows,
        int_ptrs, f64_ptrs, bool_ptrs, off_ptrs,
        ctypes.byref(arena), ctypes.byref(arena_len),
    )
    if n < 0:
        return None
    try:
        arena_bytes = ctypes.string_at(arena, arena_len.value) if arena_len.value else b""
    finally:
        if arena:
            l.ah_free(arena)
    out: dict[str, np.ndarray] = {}
    from ..batch import Field

    for c, (name, dtype) in enumerate(fields):
        k = kinds[c]
        if k == 0:
            out[name] = int_arrays[c][:n].astype(Field(name, dtype).numpy_dtype(), copy=False)
        elif k == 1:
            out[name] = f64_arrays[c][:n].astype(Field(name, dtype).numpy_dtype(), copy=False)
        elif k == 2:
            out[name] = bool_arrays[c][:n].astype(bool)
        elif k == 3:
            offs = off_arrays[c]
            col = np.empty(n, dtype=object)
            for i in range(n):
                col[i] = arena_bytes[offs[i]:offs[i + 1]].decode("utf-8")
            out[name] = col
    return out


# -------------------------------------------------------------- data plane


class DataPlaneError(RuntimeError):
    pass


MSG_DATA = 0
MSG_SIGNAL = 1


class DataPlaneListener:
    """Server half (reference network_manager.rs InNetworkLink)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        l = lib()
        if l is None:
            raise DataPlaneError("native library unavailable")
        self._l = l
        self.fd = l.dp_listen(host.encode(), port)
        if self.fd < 0:
            raise DataPlaneError(f"dp_listen failed: {self.fd}")
        self.port = l.dp_bound_port(self.fd)

    def accept(self) -> "DataPlaneConn":
        fd = self._l.dp_accept(self.fd)
        if fd < 0:
            raise DataPlaneError("dp_accept failed")
        return DataPlaneConn(fd)

    def close(self) -> None:
        self._l.dp_close(self.fd)


class DataPlaneConn:
    """One framed TCP link multiplexing all quads between two workers
    (reference OutNetworkLink, network_manager.rs:211)."""

    def __init__(self, fd: int):
        self._l = lib()
        self.fd = fd
        # one connection is shared by every sending task thread on this
        # worker pair; header+payload are two writes and must not interleave
        from ..obs.lockorder import make_lock  # lazy: keep native import-light

        self._send_lock = make_lock("DataPlaneConn._send_lock")

    @staticmethod
    def connect(host: str, port: int, retries: int = 10, backoff_ms: int = 50) -> "DataPlaneConn":
        l = lib()
        if l is None:
            raise DataPlaneError("native library unavailable")
        fd = l.dp_connect(host.encode(), port, retries, backoff_ms)
        if fd < 0:
            raise DataPlaneError(f"dp_connect failed: {fd}")
        return DataPlaneConn(fd)

    def send(self, quad: tuple[int, int, int, int], msg_type: int, payload: bytes) -> None:
        with self._send_lock:
            rc = self._l.dp_send_frame(
                self.fd, quad[0], quad[1], quad[2], quad[3], msg_type,
                payload, len(payload),
            )
        if rc != 0:
            raise DataPlaneError("dp_send_frame failed (peer closed?)")

    def recv(self):
        """-> (quad, msg_type, payload bytes) or None on clean close."""
        header = (ctypes.c_uint32 * 6)()
        rc = self._l.dp_recv_header(self.fd, header)
        if rc == -1:
            return None
        if rc != 0:
            raise DataPlaneError(f"dp_recv_header failed: {rc}")
        n = header[5]
        buf = ctypes.create_string_buffer(n) if n else None
        if n:
            if self._l.dp_recv_payload(self.fd, buf, n) != 0:
                raise DataPlaneError("dp_recv_payload failed")
        quad = (header[0], header[1], header[2], header[3])
        return quad, header[4], (buf.raw[:n] if n else b"")

    def close(self) -> None:
        self._l.dp_close(self.fd)
