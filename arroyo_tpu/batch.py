"""Columnar micro-batch model.

The reference moves Arrow RecordBatches between operators
(crates/arroyo-rpc/src/df.rs:24 ArroyoSchema: schema + timestamp_index +
key/routing indices). The TPU-native design keeps the same contract but as a
plain dict of NumPy columns so batches can be (a) manipulated host-side with
vectorized ops and (b) staged to HBM as padded fixed-shape arrays without an
Arrow dependency on the hot path. pyarrow is used only at the storage/format
boundary (Parquet checkpoints, file connectors).

Conventions (mirroring ArroyoSchema):
  - ``_timestamp``: int64 micros event-time column, present on every batch.
  - ``_key``: uint64 routing-hash column, present after a Key operator.
  - string columns are object-dtype ndarrays host-side; they never reach the
    device (keyed device state stores 64-bit hashes and the operator keeps a
    hash -> value dictionary for output reconstruction).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Iterable, Optional

import numpy as np

TIMESTAMP_FIELD = "_timestamp"
KEY_FIELD = "_key"

# dtype sentinels
STRING = "string"
_NUMPY_DTYPES = {
    "int32": np.int32,
    "int64": np.int64,
    "uint64": np.uint64,
    "float32": np.float32,
    "float64": np.float64,
    "bool": np.bool_,
    # event-time micros; int64 on device, ISO-8601 strings at the
    # format boundary (json_fmt parses/formats by schema dtype)
    "timestamp": np.int64,
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str  # "int32"|"int64"|"uint64"|"float32"|"float64"|"bool"|"string"
    nullable: bool = False

    def numpy_dtype(self):
        if self.dtype == STRING:
            return np.dtype(object)
        return np.dtype(_NUMPY_DTYPES[self.dtype])


@dataclass(frozen=True)
class Schema:
    """Stream schema (reference: arroyo-rpc/src/df.rs:24 ArroyoSchema)."""

    fields: tuple[Field, ...]
    key_fields: tuple[str, ...] = ()  # logical group-by columns
    has_keys: bool = False  # whether batches carry a _key routing column

    def __post_init__(self):
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate fields in schema: {names}")

    @staticmethod
    def of(fields: Iterable[Field | tuple[str, str]], key_fields=(), has_keys=False) -> "Schema":
        fs = tuple(f if isinstance(f, Field) else Field(f[0], f[1]) for f in fields)
        return Schema(fs, tuple(key_fields), has_keys)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def data_names(self) -> list[str]:
        """Field names excluding internal _timestamp/_key columns."""
        return [f.name for f in self.fields if f.name not in (TIMESTAMP_FIELD, KEY_FIELD)]

    def with_keys(self, key_fields: Iterable[str]) -> "Schema":
        fields = self.fields
        if KEY_FIELD not in [f.name for f in fields]:
            fields = fields + (Field(KEY_FIELD, "uint64"),)
        return Schema(fields, tuple(key_fields), True)

    def without_keys(self) -> "Schema":
        fields = tuple(f for f in self.fields if f.name != KEY_FIELD)
        return Schema(fields, (), False)

    def to_json(self) -> dict:
        return {
            "fields": [{"name": f.name, "dtype": f.dtype, "nullable": f.nullable} for f in self.fields],
            "key_fields": list(self.key_fields),
            "has_keys": self.has_keys,
        }

    @staticmethod
    def from_json(d: dict) -> "Schema":
        return Schema(
            tuple(Field(f["name"], f["dtype"], f.get("nullable", False)) for f in d["fields"]),
            tuple(d.get("key_fields", ())),
            d.get("has_keys", False),
        )


class Batch:
    """A columnar micro-batch: equal-length numpy columns."""

    __slots__ = ("columns", "num_rows")

    def __init__(self, columns: dict[str, np.ndarray]):
        if not columns:
            raise ValueError("batch must have at least one column")
        n = None
        for name, col in columns.items():
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(f"column {name} length {len(col)} != {n}")
        self.columns = columns
        self.num_rows = int(n)

    def __len__(self) -> int:
        return self.num_rows

    def __getitem__(self, name: str) -> np.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    @property
    def timestamps(self) -> np.ndarray:
        return self.columns[TIMESTAMP_FIELD]

    @property
    def keys(self) -> np.ndarray:
        return self.columns[KEY_FIELD]

    def with_column(self, name: str, col: np.ndarray) -> "Batch":
        cols = dict(self.columns)
        cols[name] = col
        return Batch(cols)

    def without_columns(self, names: Iterable[str]) -> "Batch":
        drop = set(names)
        return Batch({k: v for k, v in self.columns.items() if k not in drop})

    def select(self, names: Iterable[str]) -> "Batch":
        return Batch({n: self.columns[n] for n in names})

    def take(self, indices: np.ndarray) -> "Batch":
        return Batch({k: v[indices] for k, v in self.columns.items()})

    def filter(self, mask: np.ndarray) -> "Batch":
        return Batch({k: v[mask] for k, v in self.columns.items()})

    def slice(self, start: int, stop: int) -> "Batch":
        return Batch({k: v[start:stop] for k, v in self.columns.items()})

    @staticmethod
    def concat(batches: list["Batch"]) -> "Batch":
        if not batches:
            raise ValueError("cannot concat zero batches")
        if len(batches) == 1:
            return batches[0]
        names = batches[0].columns.keys()
        return Batch({n: np.concatenate([b.columns[n] for b in batches]) for n in names})

    @staticmethod
    def empty(schema: Schema) -> "Batch":
        return Batch({f.name: np.empty(0, dtype=f.numpy_dtype()) for f in schema.fields})

    def to_pylist(self) -> list[dict]:
        names = list(self.columns.keys())
        cols = [self.columns[n] for n in names]
        return [
            {n: _to_py(c[i]) for n, c in zip(names, cols)}
            for i in range(self.num_rows)
        ]

    def nbytes(self) -> int:
        """Approximate payload size (object columns estimated)."""
        total = 0
        for c in self.columns.values():
            if c.dtype == object:
                total += 16 * len(c)
            else:
                total += c.nbytes
        return total

    def __repr__(self) -> str:
        return f"Batch(rows={self.num_rows}, cols={list(self.columns.keys())})"


def object_column(values) -> "np.ndarray":
    """1-D object array from arbitrary python values. np.array(vals,
    dtype=object) coerces equal-length lists into a 2-D array; element-wise
    assignment keeps list-valued cells (UDAF collect state) intact."""
    vals = list(values)
    col = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        col[i] = v
    return col


def _to_py(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


def batch_from_pylist(rows: list[dict], schema: Schema) -> Batch:
    cols = {}
    for f in schema.fields:
        vals = [r.get(f.name) for r in rows]
        if f.dtype == STRING:
            cols[f.name] = np.array(vals, dtype=object)
        else:
            cols[f.name] = np.array(vals, dtype=f.numpy_dtype())
    return Batch(cols)
