"""SQL expression -> runtime expression compilation + dtype inference.

Replaces the reference's DataFusion expression planning (logical exprs ->
physical exprs serialized into operator protos, arroyo-planner/src/physical.rs)
with direct compilation into arroyo_tpu.expr nodes evaluable on host (NumPy)
and device (jax.numpy).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..batch import TIMESTAMP_FIELD, Schema
from ..expr import BinOp, Case, Cast, Col, Expr, Func, Lit, Neg, Not
from .ast import (
    Between,
    BinaryOp,
    CaseExpr,
    CastExpr,
    FuncCall,
    Ident,
    InList,
    Interval,
    IsNull,
    Like,
    Literal,
    OverExpr,
    SqlExpr,
    Star,
    UnaryOp,
)
from .lexer import SqlError

AGG_FUNCS = {"count", "sum", "min", "max", "avg", "array_agg"}


def _is_udaf(name: str) -> bool:
    from ..udf import lookup_udaf

    return lookup_udaf(name) is not None
WINDOW_TVFS = {"tumble", "hop", "session"}
RANKING_FUNCS = {"row_number", "rank", "dense_rank"}

# SQL type name -> Schema dtype string
_SQL_TYPES = {
    "INT": "int32",
    "INTEGER": "int32",
    "SMALLINT": "int32",
    "TINYINT": "int32",
    "INT UNSIGNED": "uint64",
    "INTEGER UNSIGNED": "uint64",
    "BIGINT": "int64",
    "BIGINT UNSIGNED": "uint64",
    "FLOAT": "float32",
    "REAL": "float32",
    "DOUBLE": "float64",
    "DOUBLE PRECISION": "float64",
    "NUMERIC": "float64",
    "DECIMAL": "float64",
    "BOOLEAN": "bool",
    "BOOL": "bool",
    "TEXT": "string",
    "VARCHAR": "string",
    "CHAR": "string",
    "CHARACTER VARYING": "string",
    "STRING": "string",
    "JSON": "string",  # raw JSON text column (reference json type)
    "TIMESTAMP": "timestamp",
    "TIMESTAMPTZ": "timestamp",
    "DATE": "timestamp",
}


def sql_type_to_dtype(type_name: str) -> str:
    t = type_name.upper().strip()
    if t not in _SQL_TYPES:
        raise SqlError(f"unsupported SQL type {type_name!r}")
    return _SQL_TYPES[t]


# --------------------------------------------------------------------------
# name resolution scope


class Scope:
    """Column / window-struct name resolution for one relation.

    An entry is (qualifier, name) -> ("col", physical_column) or
    ("window", (start Expr, end Expr)). Unqualified resolution requires the
    name to be unambiguous across qualifiers.
    """

    def __init__(self):
        # name -> list of (qualifier, kind, payload); insertion-ordered
        self._by_name: dict[str, list[tuple[Optional[str], str, object]]] = {}
        self._order: list[tuple[Optional[str], str, str, object]] = []

    def add_col(self, qualifier: Optional[str], name: str, colname: str) -> None:
        self._by_name.setdefault(name, []).append((qualifier, "col", colname))
        self._order.append((qualifier, name, "col", colname))

    def add_window(self, qualifier: Optional[str], name: str, payload: tuple[Expr, Expr]) -> None:
        self._by_name.setdefault(name, []).append((qualifier, "window", payload))
        self._order.append((qualifier, name, "window", payload))

    def try_resolve(self, qualifier: Optional[str], name: str):
        cands = self._by_name.get(name, [])
        if qualifier is not None:
            matches = [(k, p) for q, k, p in cands if q == qualifier]
        else:
            matches = [(k, p) for _q, k, p in cands]
            # identical payloads from multiple qualifiers are not ambiguous
            uniq = {(k, repr(p)) for k, p in matches}
            if len(uniq) > 1:
                raise SqlError(f"ambiguous column reference {name!r}")
        if not matches:
            return None
        return matches[0]

    def resolve(self, qualifier: Optional[str], name: str):
        r = self.try_resolve(qualifier, name)
        if r is None:
            disp = f"{qualifier}.{name}" if qualifier else name
            raise SqlError(f"unknown column {disp!r} (have {sorted(self._by_name)})")
        return r

    def window_entry(self, qualifier: Optional[str] = None):
        """The (single) window struct visible in this scope, if any."""
        for _q, _n, k, p in self._order:
            if k == "window":
                return p
        return None

    def columns_in_order(self, qualifier: Optional[str] = None) -> list[tuple[str, str]]:
        """(name, physical column) pairs for SELECT * expansion; windows
        expand to <name>_start/<name>_end via their payload exprs."""
        out: list[tuple[str, str]] = []
        seen = set()
        for q, n, k, p in self._order:
            if qualifier is not None and q != qualifier:
                continue
            if k != "col" or n.startswith("_"):
                continue
            if (n, p) in seen:
                continue
            seen.add((n, p))
            out.append((n, p))
        return out

    def qualifiers(self) -> set:
        return {q for q, _n, _k, _p in self._order if q is not None}


# --------------------------------------------------------------------------
# compilation


def compile_expr(e: SqlExpr, scope: Scope) -> Expr:
    """SqlExpr AST -> runtime Expr. Aggregates/OVER must already be rewritten
    out by the planner; their presence here is an error."""
    if isinstance(e, Literal):
        return Lit(e.value)
    if isinstance(e, Interval):
        return Lit(e.micros)
    if isinstance(e, Ident):
        # qualifier may be a window-struct alias: [t.]window.start / .end
        if e.qualifier is not None:
            if "." in e.qualifier:
                tq, wname = e.qualifier.rsplit(".", 1)
            else:
                tq, wname = None, e.qualifier
            w = scope.try_resolve(tq, wname)
            if w is not None and w[0] == "window":
                start, end = w[1]
                if e.name == "start":
                    return start
                if e.name == "end":
                    return end
                raise SqlError(f"window struct has no field {e.name!r}")
            if "." in e.qualifier:
                raise SqlError(f"cannot resolve nested reference {e.display()!r}")
        kind, payload = scope.resolve(e.qualifier, e.name)
        if kind == "window":
            raise SqlError(
                f"window column {e.display()!r} cannot be used as a scalar; "
                "use .start/.end"
            )
        return Col(payload)
    if isinstance(e, BinaryOp):
        if e.op == "||":
            return Func("concat", (compile_expr(e.left, scope), compile_expr(e.right, scope)))
        if e.op in ("->", "->>"):
            # -> returns the accessed value as JSON text; ->> as bare text
            # (reference json functions, arroyo-planner json.rs)
            fn = "json_get" if e.op == "->" else "json_get_str"
            return Func(fn, (compile_expr(e.left, scope), compile_expr(e.right, scope)))
        return BinOp(e.op, compile_expr(e.left, scope), compile_expr(e.right, scope))
    if isinstance(e, UnaryOp):
        if e.op == "not":
            return Not(compile_expr(e.operand, scope))
        return Neg(compile_expr(e.operand, scope))
    if isinstance(e, CastExpr):
        dtype = sql_type_to_dtype(e.type_name)
        inner = compile_expr(e.operand, scope)
        if dtype == "timestamp":
            return Cast(inner, "int64")
        return Cast(inner, dtype)
    if isinstance(e, CaseExpr):
        branches = []
        for cond, val in e.branches:
            if e.operand is not None:
                cond = BinaryOp("==", e.operand, cond)
            branches.append((compile_expr(cond, scope), compile_expr(val, scope)))
        other = compile_expr(e.otherwise, scope) if e.otherwise is not None else None
        return Case(tuple(branches), other)
    if isinstance(e, IsNull):
        f = Func("is_not_null" if e.negated else "is_null", (compile_expr(e.operand, scope),))
        return f
    if isinstance(e, InList):
        op = compile_expr(e.operand, scope)
        out: Expr = BinOp("==", op, compile_expr(e.items[0], scope))
        for item in e.items[1:]:
            out = BinOp("or", out, BinOp("==", op, compile_expr(item, scope)))
        return Not(out) if e.negated else out
    if isinstance(e, Between):
        op = compile_expr(e.operand, scope)
        rng = BinOp(
            "and",
            BinOp(">=", op, compile_expr(e.low, scope)),
            BinOp("<=", op, compile_expr(e.high, scope)),
        )
        return Not(rng) if e.negated else rng
    if isinstance(e, Like):
        f = Func("like", (compile_expr(e.operand, scope), compile_expr(e.pattern, scope)))
        return Not(f) if e.negated else f
    if isinstance(e, FuncCall):
        name = e.name
        if name in AGG_FUNCS:
            raise SqlError(f"aggregate {name}() not allowed in this context")
        if name in WINDOW_TVFS:
            raise SqlError(f"window function {name}() only allowed in GROUP BY")
        return _compile_scalar_func(e, scope)
    if isinstance(e, OverExpr):
        raise SqlError("OVER window expression not allowed in this context")
    if isinstance(e, Star):
        raise SqlError("* not allowed in this context")
    raise SqlError(f"cannot compile expression {e!r}")


_FUNC_ALIASES = {
    "pow": "power",
    "log": "ln",
    "char_length": "length",
    "character_length": "length",
    "substr": "substring",
    "ceiling": "ceil",
}

_KNOWN_SCALARS = {
    "abs", "round", "floor", "ceil", "sqrt", "power", "ln", "log10", "exp",
    "coalesce", "concat", "lower", "upper", "length", "substring", "md5",
    "hash", "extract_epoch", "date_trunc_micros", "to_timestamp_micros",
    "is_null", "is_not_null", "like",
}


def _compile_scalar_func(e: FuncCall, scope: Scope) -> Expr:
    name = _FUNC_ALIASES.get(e.name, e.name)
    args = tuple(compile_expr(a, scope) for a in e.args)
    if name == "date_trunc":
        # date_trunc('minute', ts) -> truncate micros timestamp
        if not isinstance(e.args[0], Literal):
            raise SqlError("date_trunc granularity must be a string literal")
        gran = str(e.args[0].value).lower()
        unit = {
            "microsecond": 1, "millisecond": 1_000, "second": 1_000_000,
            "minute": 60_000_000, "hour": 3_600_000_000, "day": 86_400_000_000,
            "week": 7 * 86_400_000_000,
        }.get(gran)
        if unit is None:
            raise SqlError(f"unsupported date_trunc granularity {gran!r}")
        return Func("date_trunc_micros", (Lit(unit), args[1]))
    if name == "to_timestamp":
        return Func("to_timestamp_micros", args)
    if name in ("nullif",):
        a, b = args
        return Case(((BinOp("==", a, b), Lit(None)),), a)
    if name not in _KNOWN_SCALARS:
        from ..udf import lookup_udf

        udf = lookup_udf(name)
        if udf is not None:
            return udf.as_expr(args)
        raise SqlError(f"unknown function {e.name!r}")
    return Func(name, args)


# --------------------------------------------------------------------------
# dtype inference over runtime Exprs


def _promote(a: str, b: str) -> str:
    if a == b:
        return a
    if "string" in (a, b):
        return "string"
    if "float64" in (a, b):
        return "float64"
    if "float32" in (a, b):
        return "float32" if {a, b} <= {"float32", "int32", "bool"} else "float64"
    if {a, b} == {"uint64", "int64"} or {a, b} == {"uint64", "int32"}:
        return "uint64"  # integer-literal-friendly; SQL unsigned wins
    if "int64" in (a, b) or "timestamp" in (a, b):
        return "int64"
    return "int64"


def infer_dtype(expr: Expr, field_dtypes: dict[str, str]) -> str:
    """Schema dtype string an expression evaluates to."""
    if isinstance(expr, Col):
        if expr.name not in field_dtypes:
            raise SqlError(f"unknown column {expr.name!r} during type inference")
        return field_dtypes[expr.name]
    if isinstance(expr, Lit):
        v = expr.value
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, int):
            return "int64"
        if isinstance(v, float):
            return "float64"
        if v is None:
            return "string"
        return "string"
    if isinstance(expr, BinOp):
        if expr.op in ("==", "!=", "<", "<=", ">", ">=", "and", "or"):
            return "bool"
        l = infer_dtype(expr.left, field_dtypes)
        r = infer_dtype(expr.right, field_dtypes)
        # integer literal against unsigned keeps unsigned
        if isinstance(expr.right, Lit) and isinstance(expr.right.value, int) and l in ("uint64", "int32"):
            r = l
        if isinstance(expr.left, Lit) and isinstance(expr.left.value, int) and r in ("uint64", "int32"):
            l = r
        if expr.op == "/" and l not in ("float32", "float64") and r not in ("float32", "float64"):
            return _promote(l, r)  # SQL integer division
        return _promote(l, r)
    if isinstance(expr, Not):
        return "bool"
    if isinstance(expr, Neg):
        d = infer_dtype(expr.inner, field_dtypes)
        return "int64" if d == "uint64" else d
    if isinstance(expr, Cast):
        return expr.dtype
    if isinstance(expr, Case):
        dtypes = [infer_dtype(v, field_dtypes) for _c, v in expr.branches]
        if expr.otherwise is not None:
            dtypes.append(infer_dtype(expr.otherwise, field_dtypes))
        # integer literals defer to the widest non-literal branch
        non_lit = [
            d for (_c, v), d in zip(expr.branches, dtypes[: len(expr.branches)])
            if not isinstance(v, Lit)
        ]
        if expr.otherwise is not None and not isinstance(expr.otherwise, Lit):
            non_lit.append(dtypes[-1])
        pool = non_lit or dtypes
        out = pool[0]
        for d in pool[1:]:
            out = _promote(out, d)
        return out
    if isinstance(expr, Func):
        name = expr.name
        if name in ("length", "hash", "extract_epoch"):
            return "int64" if name != "hash" else "uint64"
        if name in ("is_null", "is_not_null", "like"):
            return "bool"
        if name in ("lower", "upper", "substring", "md5", "concat",
                    "json_get", "json_get_str"):
            return "string"
        if name in ("floor", "ceil", "round", "sqrt", "power", "ln", "log10", "exp"):
            return "float64"
        if name in ("date_trunc_micros", "to_timestamp_micros"):
            return "timestamp"
        if name == "coalesce":
            return infer_dtype(expr.args[0], field_dtypes)
        if hasattr(expr, "return_dtype"):
            return expr.return_dtype
        return "float64"
    if hasattr(expr, "return_dtype"):  # UDF expr nodes
        return expr.return_dtype
    raise SqlError(f"cannot infer dtype of {expr!r}")


def agg_result_dtype(kind: str, input_dtype: Optional[str]) -> str:
    if kind == "count":
        return "int64"
    if kind == "avg":
        return "float64"
    return input_dtype or "int64"


# --------------------------------------------------------------------------
# AST utilities used by the planner


def walk(e: SqlExpr):
    yield e
    if isinstance(e, BinaryOp):
        yield from walk(e.left)
        yield from walk(e.right)
    elif isinstance(e, UnaryOp):
        yield from walk(e.operand)
    elif isinstance(e, CastExpr):
        yield from walk(e.operand)
    elif isinstance(e, CaseExpr):
        if e.operand is not None:
            yield from walk(e.operand)
        for c, v in e.branches:
            yield from walk(c)
            yield from walk(v)
        if e.otherwise is not None:
            yield from walk(e.otherwise)
    elif isinstance(e, IsNull):
        yield from walk(e.operand)
    elif isinstance(e, InList):
        yield from walk(e.operand)
        for i in e.items:
            yield from walk(i)
    elif isinstance(e, Between):
        yield from walk(e.operand)
        yield from walk(e.low)
        yield from walk(e.high)
    elif isinstance(e, Like):
        yield from walk(e.operand)
        yield from walk(e.pattern)
    elif isinstance(e, FuncCall):
        for a in e.args:
            yield from walk(a)
    elif isinstance(e, OverExpr):
        yield from walk(e.func)
        for p in e.window.partition_by:
            yield from walk(p)
        for o, _asc in e.window.order_by:
            yield from walk(o)


def replace_nodes(e: SqlExpr, mapping: list[tuple[SqlExpr, SqlExpr]]) -> SqlExpr:
    """Structurally replace subtrees (outermost match wins)."""
    for old, new in mapping:
        if e == old:
            return new
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, replace_nodes(e.left, mapping), replace_nodes(e.right, mapping))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, replace_nodes(e.operand, mapping))
    if isinstance(e, CastExpr):
        return CastExpr(replace_nodes(e.operand, mapping), e.type_name)
    if isinstance(e, CaseExpr):
        return CaseExpr(
            replace_nodes(e.operand, mapping) if e.operand is not None else None,
            tuple((replace_nodes(c, mapping), replace_nodes(v, mapping)) for c, v in e.branches),
            replace_nodes(e.otherwise, mapping) if e.otherwise is not None else None,
        )
    if isinstance(e, IsNull):
        return IsNull(replace_nodes(e.operand, mapping), e.negated)
    if isinstance(e, InList):
        return InList(
            replace_nodes(e.operand, mapping),
            tuple(replace_nodes(i, mapping) for i in e.items),
            e.negated,
        )
    if isinstance(e, Between):
        return Between(
            replace_nodes(e.operand, mapping),
            replace_nodes(e.low, mapping),
            replace_nodes(e.high, mapping),
            e.negated,
        )
    if isinstance(e, Like):
        return Like(replace_nodes(e.operand, mapping), replace_nodes(e.pattern, mapping), e.negated)
    if isinstance(e, FuncCall):
        return FuncCall(e.name, tuple(replace_nodes(a, mapping) for a in e.args), e.distinct, e.star)
    if isinstance(e, OverExpr):
        return OverExpr(
            replace_nodes(e.func, mapping),  # type: ignore[arg-type]
            e.window,
        )
    return e


def find_aggregates(e: SqlExpr) -> list[FuncCall]:
    """Aggregate calls NOT inside an OVER expression."""
    out: list[FuncCall] = []

    def rec(x: SqlExpr):
        if isinstance(x, OverExpr):
            return  # aggregates inside OVER belong to the window fn
        if isinstance(x, FuncCall) and (x.name in AGG_FUNCS or _is_udaf(x.name)):
            out.append(x)
            return  # nested aggs are illegal anyway
        for child in _children(x):
            rec(child)

    rec(e)
    return out


def find_overs(e: SqlExpr) -> list[OverExpr]:
    return [x for x in walk(e) if isinstance(x, OverExpr)]


def _children(e: SqlExpr) -> list[SqlExpr]:
    if isinstance(e, BinaryOp):
        return [e.left, e.right]
    if isinstance(e, UnaryOp):
        return [e.operand]
    if isinstance(e, CastExpr):
        return [e.operand]
    if isinstance(e, CaseExpr):
        out = list(sum(([c, v] for c, v in e.branches), []))
        if e.operand is not None:
            out.append(e.operand)
        if e.otherwise is not None:
            out.append(e.otherwise)
        return out
    if isinstance(e, IsNull):
        return [e.operand]
    if isinstance(e, InList):
        return [e.operand, *e.items]
    if isinstance(e, Between):
        return [e.operand, e.low, e.high]
    if isinstance(e, Like):
        return [e.operand, e.pattern]
    if isinstance(e, FuncCall):
        return list(e.args)
    if isinstance(e, OverExpr):
        return [e.func, *e.window.partition_by, *[o for o, _ in e.window.order_by]]
    return []
