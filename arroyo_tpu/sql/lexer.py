"""SQL tokenizer.

Hand-rolled (no sqlparser dependency); mirrors the token classes the
reference gets from its forked sqlparser-rs (SURVEY §2.3 stage 1).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


class SqlError(ValueError):
    """Parse/plan-time SQL error."""


@dataclass(frozen=True)
class Token:
    kind: str  # "ident" | "quoted_ident" | "string" | "number" | "op" | "eof"
    value: str
    pos: int  # character offset (for error messages)

    def upper(self) -> str:
        return self.value.upper()


_MULTI_OPS = ["<>", "!=", ">=", "<=", "||", "::", "->>", "->"]
_SINGLE_OPS = "+-*/%(),.;=<>[]"


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        # comments
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        # string literal
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            else:
                raise SqlError(f"unterminated string literal at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        # quoted identifier
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlError(f"unterminated quoted identifier at {i}")
            toks.append(Token("quoted_ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        # number
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    seen_exp = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            toks.append(Token("number", sql[i:j], i))
            i = j
            continue
        # identifier / keyword
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            toks.append(Token("ident", sql[i:j], i))
            i = j
            continue
        # operators (longest match first: ->> before ->)
        three = sql[i : i + 3]
        if three in _MULTI_OPS:
            toks.append(Token("op", three, i))
            i += 3
            continue
        two = sql[i : i + 2]
        if two in _MULTI_OPS:
            toks.append(Token("op", two, i))
            i += 2
            continue
        if c in _SINGLE_OPS:
            toks.append(Token("op", c, i))
            i += 1
            continue
        raise SqlError(f"unexpected character {c!r} at offset {i}")
    toks.append(Token("eof", "", n))
    return toks
