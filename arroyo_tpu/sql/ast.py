"""SQL AST node definitions.

Parallel of the reference planner's statement/expression layer (forked
sqlparser AST + DataFusion logical exprs, SURVEY §2.3); trimmed to the
dialect the dataflow planner consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


# --------------------------------------------------------------------------
# scalar expressions


class SqlExpr:
    pass


@dataclass(frozen=True)
class Ident(SqlExpr):
    name: str
    qualifier: Optional[str] = None  # table/alias qualifier: t.col

    def display(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


@dataclass(frozen=True)
class Literal(SqlExpr):
    value: object  # int | float | str | bool | None


@dataclass(frozen=True)
class Interval(SqlExpr):
    micros: int


@dataclass(frozen=True)
class BinaryOp(SqlExpr):
    op: str  # + - * / % = <> < <= > >= and or ||
    left: SqlExpr
    right: SqlExpr


@dataclass(frozen=True)
class UnaryOp(SqlExpr):
    op: str  # "-" | "not"
    operand: SqlExpr


@dataclass(frozen=True)
class CastExpr(SqlExpr):
    operand: SqlExpr
    type_name: str  # SQL type name, uppercase


@dataclass(frozen=True)
class FuncCall(SqlExpr):
    name: str  # lowercase
    args: tuple[SqlExpr, ...]
    distinct: bool = False
    star: bool = False  # count(*)


@dataclass(frozen=True)
class WindowSpec:
    partition_by: tuple[SqlExpr, ...]
    order_by: tuple[tuple[SqlExpr, bool], ...]  # (expr, ascending)


@dataclass(frozen=True)
class OverExpr(SqlExpr):
    func: FuncCall
    window: WindowSpec


@dataclass(frozen=True)
class CaseExpr(SqlExpr):
    operand: Optional[SqlExpr]  # CASE x WHEN v ... (simple form)
    branches: tuple[tuple[SqlExpr, SqlExpr], ...]
    otherwise: Optional[SqlExpr]


@dataclass(frozen=True)
class IsNull(SqlExpr):
    operand: SqlExpr
    negated: bool


@dataclass(frozen=True)
class InList(SqlExpr):
    operand: SqlExpr
    items: tuple[SqlExpr, ...]
    negated: bool


@dataclass(frozen=True)
class Between(SqlExpr):
    operand: SqlExpr
    low: SqlExpr
    high: SqlExpr
    negated: bool


@dataclass(frozen=True)
class Like(SqlExpr):
    operand: SqlExpr
    pattern: SqlExpr
    negated: bool


@dataclass(frozen=True)
class Star(SqlExpr):
    qualifier: Optional[str] = None  # t.*


# --------------------------------------------------------------------------
# statements


@dataclass(frozen=True)
class SelectItem:
    expr: SqlExpr
    alias: Optional[str]


@dataclass(frozen=True)
class TableRef:
    """FROM item: named table/view or subquery."""

    name: Optional[str] = None
    subquery: Optional["Select"] = None
    alias: Optional[str] = None

    def display(self) -> str:
        return self.alias or self.name or "<subquery>"


@dataclass(frozen=True)
class Join:
    join_type: str  # "inner" | "left" | "right" | "full"
    table: TableRef
    on: SqlExpr


@dataclass
class Select:
    items: list[SelectItem]
    from_table: Optional[TableRef]
    joins: list[Join] = field(default_factory=list)
    where: Optional[SqlExpr] = None
    group_by: list[SqlExpr] = field(default_factory=list)
    having: Optional[SqlExpr] = None
    order_by: list[tuple[SqlExpr, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False
    # left-associative UNION chain: [("all"|"distinct", rhs), ...]
    union: list[tuple[str, "Select"]] = field(default_factory=list)


@dataclass(frozen=True)
class ColumnDef:
    name: str
    type_name: str  # SQL type, uppercase
    nullable: bool = True
    generated: Optional[SqlExpr] = None  # GENERATED ALWAYS AS (expr) STORED
    metadata_key: Optional[str] = None  # METADATA FROM 'key'


@dataclass(frozen=True)
class CreateTable:
    name: str
    columns: tuple[ColumnDef, ...]  # empty for schemaless sinks
    options: dict  # WITH (...) key/values, string-valued
    virtual_fields: tuple[str, ...] = ()


@dataclass(frozen=True)
class CreateView:
    name: str
    query: Select


@dataclass(frozen=True)
class Insert:
    table: str
    query: Select


@dataclass(frozen=True)
class Query:
    """Bare SELECT at top level (preview pipeline)."""

    query: Select


@dataclass(frozen=True)
class SetVariable:
    name: str
    value: object


Statement = Union[CreateTable, CreateView, Insert, Query, SetVariable]
