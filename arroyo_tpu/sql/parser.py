"""Recursive-descent SQL parser.

Produces the AST of sql/ast.py. Plays the role of the reference's forked
sqlparser-rs + statement handling in arroyo-planner/src/lib.rs:744-777
(ArroyoDialect, SET handling) for the dialect subset this framework plans.
"""

from __future__ import annotations

from typing import Optional

from .ast import (
    Between,
    BinaryOp,
    CaseExpr,
    CastExpr,
    ColumnDef,
    CreateTable,
    CreateView,
    FuncCall,
    Ident,
    InList,
    Insert,
    Interval,
    IsNull,
    Like,
    Literal,
    OverExpr,
    Query,
    Select,
    SelectItem,
    SetVariable,
    Star,
    Statement,
    TableRef,
    UnaryOp,
    WindowSpec,
    Join,
)
from .lexer import SqlError, Token, tokenize

_UNITS_MICROS = {
    "MICROSECOND": 1,
    "MICROSECONDS": 1,
    "MILLISECOND": 1_000,
    "MILLISECONDS": 1_000,
    "SECOND": 1_000_000,
    "SECONDS": 1_000_000,
    "MINUTE": 60_000_000,
    "MINUTES": 60_000_000,
    "HOUR": 3_600_000_000,
    "HOURS": 3_600_000_000,
    "DAY": 86_400_000_000,
    "DAYS": 86_400_000_000,
}

_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "JOIN", "INNER",
    "LEFT", "RIGHT", "FULL", "CROSS", "ON", "AS", "AND", "OR", "NOT", "UNION",
    "SELECT", "BY", "ASC", "DESC", "WITH", "THEN", "ELSE", "END", "WHEN",
    "INTO", "VALUES", "SET",
}


def parse_interval_str(s: str) -> int:
    """'10 seconds' / '1 minute' / '500 millisecond' -> micros."""
    parts = s.strip().split()
    if len(parts) == 1:
        # bare number: treated as seconds would be ambiguous; reject
        raise SqlError(f"interval string {s!r} must include a unit")
    total = 0
    i = 0
    while i < len(parts):
        try:
            qty = float(parts[i])
        except ValueError:
            raise SqlError(f"bad interval quantity in {s!r}")
        unit = parts[i + 1].upper() if i + 1 < len(parts) else None
        if unit not in _UNITS_MICROS:
            raise SqlError(f"bad interval unit in {s!r}")
        total += int(qty * _UNITS_MICROS[unit])
        i += 2
    return total


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # ------------------------------------------------------------- helpers

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper() in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            t = self.peek()
            raise SqlError(f"expected {kw}, found {t.value!r} at offset {t.pos}")

    def at_op(self, op: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value == op

    def eat_op(self, op: str) -> bool:
        if self.at_op(op):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            t = self.peek()
            raise SqlError(f"expected {op!r}, found {t.value!r} at offset {t.pos}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "quoted_ident":
            self.next()
            return t.value
        if t.kind == "ident":
            self.next()
            return t.value
        raise SqlError(f"expected identifier, found {t.value!r} at offset {t.pos}")

    def skip_until_op(self, op: str) -> None:
        """Consume tokens (paren-aware) until ``op`` at depth 0; raises on
        EOF — next() does not advance past EOF, so a bare while-loop would
        spin forever on truncated input."""
        depth = 0
        while True:
            t = self.peek()
            if t.kind == "eof":
                raise SqlError(f"unexpected end of input, expected {op!r}")
            if t.kind == "op":
                if t.value == op and depth == 0:
                    self.next()
                    return
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    depth -= 1
            self.next()

    # ---------------------------------------------------------- statements

    def parse_statements(self) -> list[Statement]:
        out: list[Statement] = []
        while self.peek().kind != "eof":
            if self.eat_op(";"):
                continue
            out.append(self.parse_statement())
            if self.peek().kind != "eof":
                self.expect_op(";")
        return out

    def parse_statement(self) -> Statement:
        if self.at_kw("CREATE"):
            return self._parse_create()
        if self.at_kw("INSERT"):
            return self._parse_insert()
        if self.at_kw("SELECT") or self.at_op("("):
            return Query(self.parse_select())
        if self.at_kw("SET"):
            return self._parse_set()
        t = self.peek()
        raise SqlError(f"unsupported statement starting with {t.value!r} at {t.pos}")

    def _parse_set(self) -> SetVariable:
        self.expect_kw("SET")
        name = self.ident()
        self.expect_op("=")
        t = self.next()
        if t.kind == "string":
            val: object = t.value
        elif t.kind == "number":
            val = float(t.value) if "." in t.value else int(t.value)
        else:
            val = t.value
        return SetVariable(name.lower(), val)

    def _parse_create(self) -> Statement:
        self.expect_kw("CREATE")
        self.eat_kw("TEMPORARY")
        if self.eat_kw("VIEW"):
            name = self.ident()
            self.expect_kw("AS")
            return CreateView(name, self.parse_select())
        self.expect_kw("TABLE")
        if self.eat_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
        name = self.ident()
        columns: list[ColumnDef] = []
        virtual: list[str] = []
        if self.at_op("("):
            self.next()
            while not self.eat_op(")"):
                columns.append(self._parse_column_def())
                if not self.eat_op(","):
                    self.expect_op(")")
                    break
        options: dict = {}
        if self.eat_kw("WITH"):
            self.expect_op("(")
            while not self.eat_op(")"):
                key = self._parse_option_key()
                self.expect_op("=")
                t = self.next()
                if t.kind == "string":
                    options[key] = t.value
                elif t.kind == "number":
                    options[key] = float(t.value) if "." in t.value else int(t.value)
                elif t.kind == "ident" and t.upper() in ("TRUE", "FALSE"):
                    options[key] = t.upper() == "TRUE"
                else:
                    options[key] = t.value
                if not self.eat_op(","):
                    self.expect_op(")")
                    break
        if self.eat_kw("AS"):
            # CREATE TABLE x AS SELECT — memory table from query
            q = self.parse_select()
            return CreateView(name, q) if not options else CreateTable(name, tuple(columns), {**options, "__as_query__": q})
        return CreateTable(name, tuple(columns), options, tuple(virtual))

    def _parse_option_key(self) -> str:
        parts = [self.ident() if self.peek().kind in ("ident", "quoted_ident") else self.next().value]
        while self.eat_op("."):
            parts.append(self.ident())
        return ".".join(parts)

    def _parse_column_def(self) -> ColumnDef:
        if self.at_kw("WATERMARK"):
            # WATERMARK FOR col [AS (expr)] — flink-style; represented as a
            # generated column named "_watermark_for_<col>"; without AS the
            # column itself is the watermark expression
            self.next()
            self.expect_kw("FOR")
            col = self.ident()
            if self.eat_kw("AS"):
                expr = self.parse_expr()
            else:
                expr = Ident(col)
            return ColumnDef(f"__watermark_for_{col}", "WATERMARK", generated=expr)
        name = self.ident()
        type_parts = [self.ident().upper()]
        # multi-word types: DOUBLE PRECISION, TIMESTAMP WITH(OUT) TIME ZONE, BIGINT UNSIGNED
        while self.peek().kind == "ident" and self.peek().upper() in (
            "PRECISION", "UNSIGNED", "VARYING",
        ):
            type_parts.append(self.next().value.upper())
        if type_parts[0] == "TIMESTAMP" and self.at_kw("WITH", "WITHOUT"):
            self.next()
            self.expect_kw("TIME")
            self.expect_kw("ZONE")
        if self.eat_op("("):  # VARCHAR(255), DECIMAL(10, 2)
            self.skip_until_op(")")
        type_name = " ".join(type_parts)
        nullable = True
        generated = None
        metadata_key = None
        while True:
            if self.eat_kw("NOT"):
                self.expect_kw("NULL")
                nullable = False
            elif self.eat_kw("NULL"):
                nullable = True
            elif self.eat_kw("PRIMARY"):
                self.expect_kw("KEY")
            elif self.eat_kw("GENERATED"):
                self.expect_kw("ALWAYS")
                self.expect_kw("AS")
                self.expect_op("(")
                generated = self.parse_expr()
                self.expect_op(")")
                self.eat_kw("STORED")
                self.eat_kw("VIRTUAL")
            elif self.eat_kw("METADATA"):
                self.expect_kw("FROM")
                t = self.next()
                metadata_key = t.value
            else:
                break
        return ColumnDef(name, type_name, nullable, generated, metadata_key)

    def _parse_insert(self) -> Insert:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.ident()
        if self.at_op("("):  # column list — consumed and ignored (order must match)
            self.next()
            self.skip_until_op(")")
        return Insert(table, self.parse_select())

    # -------------------------------------------------------------- select

    def parse_select(self) -> Select:
        if self.eat_op("("):
            q = self.parse_select()
            self.expect_op(")")
        else:
            self.expect_kw("SELECT")
            distinct = self.eat_kw("DISTINCT")
            self.eat_kw("ALL")
            items = [self._parse_select_item()]
            while self.eat_op(","):
                items.append(self._parse_select_item())
            from_table = None
            joins: list[Join] = []
            if self.eat_kw("FROM"):
                from_table = self._parse_table_ref()
                while True:
                    jt = self._maybe_join_type()
                    if jt is None:
                        break
                    tbl = self._parse_table_ref()
                    self.expect_kw("ON")
                    on = self.parse_expr()
                    joins.append(Join(jt, tbl, on))
            where = self.parse_expr() if self.eat_kw("WHERE") else None
            group_by: list = []
            if self.eat_kw("GROUP"):
                self.expect_kw("BY")
                group_by.append(self.parse_expr())
                while self.eat_op(","):
                    group_by.append(self.parse_expr())
            having = self.parse_expr() if self.eat_kw("HAVING") else None
            order_by: list[tuple] = []
            if self.eat_kw("ORDER"):
                self.expect_kw("BY")
                while True:
                    e = self.parse_expr()
                    asc = True
                    if self.eat_kw("DESC"):
                        asc = False
                    else:
                        self.eat_kw("ASC")
                    if self.eat_kw("NULLS"):
                        self.next()  # FIRST/LAST — accepted, default ordering applies
                    order_by.append((e, asc))
                    if not self.eat_op(","):
                        break
            limit = None
            if self.eat_kw("LIMIT"):
                t = self.next()
                limit = int(t.value)
            q = Select(items, from_table, joins, where, group_by, having, order_by, limit, distinct)
        while self.eat_kw("UNION"):
            how = "all" if self.eat_kw("ALL") else "distinct"
            rhs = self.parse_select()
            # append (never overwrite): a parenthesized lhs may already
            # carry its own union branches
            q.union.append((how, rhs))
        return q

    def _maybe_join_type(self) -> Optional[str]:
        if self.eat_kw("JOIN"):
            return "inner"
        if self.at_kw("INNER") and self.peek(1).upper() == "JOIN":
            self.next(); self.next()
            return "inner"
        for kw, jt in (("LEFT", "left"), ("RIGHT", "right"), ("FULL", "full")):
            if self.at_kw(kw):
                nxt = self.peek(1).upper()
                if nxt in ("JOIN", "OUTER"):
                    self.next()
                    self.eat_kw("OUTER")
                    self.expect_kw("JOIN")
                    return jt
        return None

    def _parse_table_ref(self) -> TableRef:
        if self.at_op("("):
            self.next()
            sub = self.parse_select()
            self.expect_op(")")
            alias = None
            if self.eat_kw("AS"):
                alias = self.ident()
            elif self.peek().kind in ("ident", "quoted_ident") and self.peek().upper() not in _RESERVED_STOP:
                alias = self.ident()
            return TableRef(subquery=sub, alias=alias)
        name = self.ident()
        alias = None
        if self.eat_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in ("ident", "quoted_ident") and self.peek().upper() not in _RESERVED_STOP:
            alias = self.ident()
        return TableRef(name=name, alias=alias)

    def _parse_select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star(), None)
        e = self.parse_expr()
        alias = None
        if self.eat_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in ("ident", "quoted_ident") and self.peek().upper() not in _RESERVED_STOP:
            alias = self.ident()
        return SelectItem(e, alias)

    # ---------------------------------------------------------- expressions

    def parse_expr(self):
        return self._parse_or()

    def _parse_or(self):
        e = self._parse_and()
        while self.at_kw("OR"):
            self.next()
            e = BinaryOp("or", e, self._parse_and())
        return e

    def _parse_and(self):
        e = self._parse_not()
        while self.at_kw("AND"):
            self.next()
            e = BinaryOp("and", e, self._parse_not())
        return e

    def _parse_not(self):
        if self.at_kw("NOT"):
            self.next()
            return UnaryOp("not", self._parse_not())
        return self._parse_comparison()

    def _parse_comparison(self):
        e = self._parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.next()
                op = {"=": "==", "<>": "!=", "!=": "!="}.get(t.value, t.value)
                e = BinaryOp(op, e, self._parse_additive())
                continue
            if self.at_kw("IS"):
                self.next()
                negated = self.eat_kw("NOT")
                self.expect_kw("NULL")
                e = IsNull(e, negated)
                continue
            negated = False
            save = self.i
            if self.at_kw("NOT"):
                self.next()
                negated = True
            if self.at_kw("BETWEEN"):
                self.next()
                low = self._parse_additive()
                self.expect_kw("AND")
                high = self._parse_additive()
                e = Between(e, low, high, negated)
                continue
            if self.at_kw("IN"):
                self.next()
                self.expect_op("(")
                items = [self.parse_expr()]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                e = InList(e, tuple(items), negated)
                continue
            if self.at_kw("LIKE"):
                self.next()
                e = Like(e, self._parse_additive(), negated)
                continue
            if negated:
                self.i = save  # NOT belonged to an outer context
            break
        return e

    def _parse_additive(self):
        e = self._parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-", "||"):
                self.next()
                e = BinaryOp(t.value, e, self._parse_multiplicative())
            else:
                return e

    def _parse_multiplicative(self):
        e = self._parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                e = BinaryOp(t.value, e, self._parse_unary())
            else:
                return e

    def _parse_unary(self):
        if self.eat_op("-"):
            return UnaryOp("-", self._parse_unary())
        if self.eat_op("+"):
            return self._parse_unary()
        return self._parse_postfix()

    def _parse_postfix(self):
        e = self._parse_primary()
        while True:
            if self.at_op("->") or self.at_op("->>"):
                # JSON access: -> yields JSON text, ->> unquoted text
                op = self.next().value
                e = BinaryOp(op, e, self._parse_primary())
                continue
            if self.eat_op("::"):
                tname = self.ident().upper()
                while self.peek().kind == "ident" and self.peek().upper() in ("PRECISION", "UNSIGNED"):
                    tname += " " + self.next().value.upper()
                e = CastExpr(e, tname)
                continue
            if self.at_op(".") and isinstance(e, Ident):
                self.next()
                if self.at_op("*"):
                    self.next()
                    return Star(qualifier=e.display())
                fieldname = self.ident()
                # chains like t.window.start become qualifier "t.window"
                e = Ident(fieldname, qualifier=e.display())
                continue
            return e

    def _parse_primary(self):
        t = self.peek()
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if t.kind == "number":
            self.next()
            if "." in t.value or "e" in t.value or "E" in t.value:
                return Literal(float(t.value))
            return Literal(int(t.value))
        if self.eat_op("("):
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "quoted_ident":
            self.next()
            return Ident(t.value)
        if t.kind != "ident":
            raise SqlError(f"unexpected token {t.value!r} at offset {t.pos}")
        upper = t.upper()
        if upper in ("TRUE", "FALSE"):
            self.next()
            return Literal(upper == "TRUE")
        if upper == "NULL":
            self.next()
            return Literal(None)
        if upper == "INTERVAL":
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlError(f"INTERVAL requires a string literal at offset {s.pos}")
            if self.peek().kind == "ident" and self.peek().upper() in _UNITS_MICROS:
                unit = self.next().upper()
                return Interval(int(float(s.value) * _UNITS_MICROS[unit]))
            return Interval(parse_interval_str(s.value))
        if upper == "CASE":
            return self._parse_case()
        if upper == "CAST":
            self.next()
            self.expect_op("(")
            inner = self.parse_expr()
            self.expect_kw("AS")
            tname = self.ident().upper()
            while self.peek().kind == "ident" and self.peek().upper() in ("PRECISION", "UNSIGNED"):
                tname += " " + self.next().value.upper()
            if self.eat_op("("):
                self.skip_until_op(")")
            self.expect_op(")")
            return CastExpr(inner, tname)
        if upper == "EXTRACT":
            self.next()
            self.expect_op("(")
            part = self.ident().lower()
            self.expect_kw("FROM")
            inner = self.parse_expr()
            self.expect_op(")")
            return FuncCall(f"extract_{part}", (inner,))
        # function call or plain identifier
        if self.peek(1).kind == "op" and self.peek(1).value == "(":
            name = self.ident().lower()
            self.expect_op("(")
            distinct = False
            star = False
            args: list = []
            if self.at_op("*"):
                self.next()
                star = True
            elif not self.at_op(")"):
                if self.eat_kw("DISTINCT"):
                    distinct = True
                args.append(self.parse_expr())
                while self.eat_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            fc = FuncCall(name, tuple(args), distinct, star)
            if self.at_kw("OVER"):
                self.next()
                self.expect_op("(")
                partition: list = []
                order: list[tuple] = []
                if self.eat_kw("PARTITION"):
                    self.expect_kw("BY")
                    partition.append(self.parse_expr())
                    while self.eat_op(","):
                        partition.append(self.parse_expr())
                if self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    while True:
                        e = self.parse_expr()
                        asc = True
                        if self.eat_kw("DESC"):
                            asc = False
                        else:
                            self.eat_kw("ASC")
                        order.append((e, asc))
                        if not self.eat_op(","):
                            break
                # ROWS BETWEEN ... — accepted and ignored (full-partition frame)
                self.skip_until_op(")")
                self.i -= 1  # skip consumed the ')'; rewind for expect_op
                self.expect_op(")")
                return OverExpr(fc, WindowSpec(tuple(partition), tuple(order)))
            return fc
        return Ident(self.ident())

    def _parse_case(self) -> CaseExpr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches: list[tuple] = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            branches.append((cond, val))
        otherwise = None
        if self.eat_kw("ELSE"):
            otherwise = self.parse_expr()
        self.expect_kw("END")
        return CaseExpr(operand, tuple(branches), otherwise)


def parse_statements(sql: str) -> list[Statement]:
    return Parser(sql).parse_statements()
