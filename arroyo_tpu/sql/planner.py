"""SQL AST -> logical dataflow Graph.

TPU-native parallel of arroyo-planner's plan pipeline (SURVEY §2.3:
parse_and_get_arrow_program lib.rs:779-921 — DDL tables, rewrite passes,
extension nodes, PlanToGraphVisitor): statements become Graph nodes whose
configs hold compiled runtime expressions (arroyo_tpu.expr) instead of
serialized DataFusion physical plans. The per-branch windowing discipline
(WindowDetectingVisitor, plan/mod.rs:39-190) is enforced by tracking a single
WindowInfo per planned relation.
"""

from __future__ import annotations

import itertools
import logging
from dataclasses import dataclass, field as dc_field
from typing import Optional

import numpy as np

_log = logging.getLogger("arroyo_tpu.planner")

from ..batch import KEY_FIELD, TIMESTAMP_FIELD, Field, Schema
from ..expr import BinOp, Case, Cast, Col, Expr, Func, Lit, Neg, Not
from ..graph import EdgeType, Graph, Node, OpName
from ..windows.tumbling import WINDOW_END, WINDOW_START
from .ast import (
    CreateTable,
    CreateView,
    FuncCall,
    Ident,
    Insert,
    Interval,
    Literal,
    OverExpr,
    Query,
    Select,
    SelectItem,
    SetVariable,
    SqlExpr,
    Star,
    TableRef,
)
from .compile import (
    AGG_FUNCS,
    RANKING_FUNCS,
    WINDOW_TVFS,
    Scope,
    agg_result_dtype,
    compile_expr,
    find_aggregates,
    find_overs,
    infer_dtype,
    replace_nodes,
    sql_type_to_dtype,
)
from .lexer import SqlError
from .parser import parse_interval_str, parse_statements

IS_RETRACT_FIELD = "_is_retract"


class PlanError(SqlError):
    pass


@dataclass(frozen=True)
class WindowInfo:
    kind: str  # "tumbling" | "sliding" | "session"
    width: int = 0
    slide: int = 0
    gap: int = 0

    @property
    def stride(self) -> Optional[int]:
        """Spacing between successive window starts (None for session)."""
        if self.kind == "tumbling":
            return self.width
        if self.kind == "sliding":
            return self.slide
        return None


@dataclass
class Rel:
    """A planned relation: output node + name resolution + stream traits."""

    node_id: str
    dtypes: dict[str, str]  # physical column -> dtype string
    scope: Scope
    updating: bool = False
    window: Optional[WindowInfo] = None
    keyed: bool = False  # batches carry _key

    def schema(self) -> Schema:
        fields = [Field(n, d) for n, d in self.dtypes.items()]
        names = set(self.dtypes)
        if TIMESTAMP_FIELD not in names:
            fields.append(Field(TIMESTAMP_FIELD, "int64"))
        if self.keyed and KEY_FIELD not in names:
            fields.append(Field(KEY_FIELD, "uint64"))
        return Schema(tuple(fields), has_keys=self.keyed)


@dataclass
class TableDecl:
    name: str
    columns: tuple
    options: dict

    @property
    def connector(self) -> str:
        c = self.options.get("connector")
        if not c:
            raise PlanError(f"table {self.name!r} has no connector option")
        return str(c)

    @property
    def ttype(self) -> Optional[str]:
        t = self.options.get("type")
        return str(t) if t else None

    @property
    def event_time_field(self) -> Optional[str]:
        v = self.options.get("event_time_field")
        return str(v) if v else None

    @property
    def watermark_field(self) -> Optional[str]:
        v = self.options.get("watermark_field")
        return str(v) if v else None

    def physical_columns(self):
        return [c for c in self.columns if c.generated is None and c.type_name != "WATERMARK"]

    def generated_columns(self):
        return [c for c in self.columns if c.generated is not None and c.type_name != "WATERMARK"]

    def watermark_defs(self):
        return [c for c in self.columns if c.type_name == "WATERMARK"]


@dataclass
class SinkInfo:
    node_id: str
    table: str
    connector: str
    rows: Optional[list] = None  # preview sinks


@dataclass
class PlannedPipeline:
    graph: Graph
    sinks: list[SinkInfo]
    settings: dict


def rename_cols(e: Expr, mapping: dict[str, str]) -> Expr:
    """Rewrite Col names in a runtime expression (join output remapping)."""
    if isinstance(e, Col):
        return Col(mapping.get(e.name, e.name))
    if isinstance(e, Lit):
        return e
    if isinstance(e, BinOp):
        return BinOp(e.op, rename_cols(e.left, mapping), rename_cols(e.right, mapping))
    if isinstance(e, Not):
        return Not(rename_cols(e.inner, mapping))
    if isinstance(e, Neg):
        return Neg(rename_cols(e.inner, mapping))
    if isinstance(e, Cast):
        return Cast(rename_cols(e.inner, mapping), e.dtype)
    if isinstance(e, Case):
        return Case(
            tuple((rename_cols(c, mapping), rename_cols(v, mapping)) for c, v in e.branches),
            rename_cols(e.otherwise, mapping) if e.otherwise is not None else None,
        )
    if isinstance(e, Func):
        return Func(e.name, tuple(rename_cols(a, mapping) for a in e.args))
    from ..udf import UdfExpr

    if isinstance(e, UdfExpr):
        return UdfExpr(e.udf_name, e.fn, e.vectorized, e.return_dtype,
                       tuple(rename_cols(a, mapping) for a in e.args))
    raise PlanError(f"cannot rename columns in {e!r}")


def _conjuncts(e: SqlExpr) -> list[SqlExpr]:
    from .ast import BinaryOp

    if isinstance(e, BinaryOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


class Planner:
    """Multi-statement SQL script -> PlannedPipeline."""

    def __init__(self, parallelism: int = 1):
        self.parallelism = parallelism
        self.tables: dict[str, TableDecl] = {}
        self.views: dict[str, Select] = {}
        # connector-less tables: INSERT INTO them plants a named stream in
        # the dataflow that later SELECTs tap (reference memory tables,
        # planner tables.rs Table::MemoryTable)
        self.memory_rels: dict[str, "Rel"] = {}
        self.graph = Graph()
        self.sinks: list[SinkInfo] = []
        self.settings: dict = {}
        self._counter = itertools.count()

    # ---------------------------------------------------------------- ids

    def _id(self, kind: str, hint: str = "") -> str:
        n = next(self._counter)
        return f"{kind}_{n}_{hint}" if hint else f"{kind}_{n}"

    # ------------------------------------------------------------ top level

    def plan(self, sql: str) -> PlannedPipeline:
        stmts = parse_statements(sql)
        for stmt in stmts:
            if isinstance(stmt, CreateTable):
                if "__as_query__" in stmt.options:
                    raise PlanError("CREATE TABLE ... AS with options is unsupported")
                self.tables[stmt.name] = TableDecl(stmt.name, stmt.columns, stmt.options)
            elif isinstance(stmt, CreateView):
                self.views[stmt.name] = stmt.query
            elif isinstance(stmt, SetVariable):
                val = stmt.value
                if stmt.name == "updating_ttl" and isinstance(val, str):
                    val = parse_interval_str(val)
                self.settings[stmt.name] = val
            elif isinstance(stmt, Insert):
                self._plan_insert(stmt)
            elif isinstance(stmt, Query):
                self._plan_preview(stmt.query)
            else:
                raise PlanError(f"unsupported statement {stmt!r}")
        if not self.sinks:
            raise PlanError("pipeline has no INSERT INTO or SELECT statement")
        return PlannedPipeline(self.graph, self.sinks, self.settings)

    # -------------------------------------------------------------- helpers

    def _add_node(self, node_id: str, op: OpName, cfg: dict, parallelism: Optional[int] = None,
                  description: str = "") -> Node:
        p = self.parallelism if parallelism is None else parallelism
        return self.graph.add_node(Node(node_id, op, cfg, p, description))

    def _edge(self, src_rel_or_id, dst: str, etype: EdgeType, schema: Schema):
        src = src_rel_or_id.node_id if isinstance(src_rel_or_id, Rel) else src_rel_or_id
        self.graph.add_edge(src, dst, etype, schema)

    # ------------------------------------------------------------- sources

    def _plan_table_ref(self, tr: TableRef) -> Rel:
        if tr.subquery is not None:
            rel = self.plan_select(tr.subquery)
            return self._aliased(rel, tr.alias)
        name = tr.name
        assert name is not None
        if name in self.views:
            rel = self.plan_select(self.views[name])
            return self._aliased(rel, tr.alias or name)
        if name in self.memory_rels:
            return self._aliased(self.memory_rels[name], tr.alias or name)
        if name not in self.tables:
            raise PlanError(f"unknown table {name!r}")
        decl = self.tables[name]
        if decl.options.get("connector") is None:
            raise PlanError(
                f"memory table {name!r} is read before any INSERT INTO writes it")
        if decl.ttype == "sink":
            raise PlanError(f"table {name!r} is a sink; cannot SELECT from it")
        return self._plan_source(decl, tr.alias or name)

    def _aliased(self, rel: Rel, alias: Optional[str]) -> Rel:
        """Re-qualify a subquery/view output scope under its alias."""
        s = Scope()
        for q, n, k, p in rel.scope._order:
            if q is not None and alias is not None and q != alias:
                continue
            if k == "col":
                s.add_col(alias, n, p)
            else:
                s.add_window(alias, n, p)
        return Rel(rel.node_id, rel.dtypes, s, rel.updating, rel.window, rel.keyed)

    def _plan_source(self, decl: TableDecl, alias: str) -> Rel:
        phys = decl.physical_columns()
        if not phys and decl.connector not in ("impulse", "nexmark"):
            raise PlanError(f"source table {decl.name!r} needs at least one column")
        dtypes: dict[str, str] = {}
        fields = []
        for c in phys:
            dt = sql_type_to_dtype(c.type_name)
            dtypes[c.name] = dt
            fields.append(Field(c.name, dt, c.nullable))
        fields.append(Field(TIMESTAMP_FIELD, "int64"))
        src_schema = Schema(tuple(fields))

        cfg = dict(decl.options)
        cfg.pop("type", None)
        cfg.pop("event_time_field", None)
        cfg["connector"] = decl.connector
        cfg["schema"] = src_schema
        etf = decl.event_time_field
        if etf and any(c.name == etf for c in phys):
            # physical event-time column: the deserializer stamps _timestamp;
            # generated ones are stamped by the generated-columns VALUE node
            cfg["event_time_field"] = etf
        cfg.setdefault("bad_data", str(decl.options.get("bad_data", "fail")))
        src_id = self._id("source", decl.name)
        self._add_node(src_id, OpName.SOURCE, cfg, description=f"{decl.connector}:{decl.name}")

        scope = Scope()
        for c in phys:
            scope.add_col(alias, c.name, c.name)
        rel = Rel(src_id, dict(dtypes), scope)

        # generated columns (incl. generated event-time) via a VALUE node
        gens = decl.generated_columns()
        if gens:
            proj = [(n, Col(n)) for n in dtypes]
            gen_scope = rel.scope
            gen_exprs: dict[str, Expr] = {}
            for c in gens:
                e = compile_expr(c.generated, gen_scope)
                dt = sql_type_to_dtype(c.type_name)
                ce = Cast(e, "int64") if dt == "timestamp" else e
                proj.append((c.name, ce))
                gen_exprs[c.name] = ce
                dtypes[c.name] = dt
            if etf and etf in gen_exprs:
                # projections all evaluate against the INPUT batch, so the
                # event-time column must be re-derived from its generating
                # expression, not referenced by name
                proj.append((TIMESTAMP_FIELD, gen_exprs[etf]))
            vid = self._id("value", f"{decl.name}_gen")
            self._add_node(vid, OpName.VALUE, {"projections": proj})
            self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
            scope = Scope()
            for n in dtypes:
                scope.add_col(alias, n, n)
            rel = Rel(vid, dict(dtypes), scope)

        # watermark node (reference: SourceRewriter inserts WatermarkNode)
        wm_expr: Expr = Col(TIMESTAMP_FIELD)
        wdefs = decl.watermark_defs()
        if wdefs:
            wm_expr = compile_expr(wdefs[0].generated, rel.scope)
        elif decl.watermark_field:
            wf = decl.watermark_field
            if wf in dtypes:
                wm_expr = Col(wf)
            else:
                raise PlanError(f"watermark_field {wf!r} is not a column of {decl.name!r}")
        wm_cfg: dict = {"expr": wm_expr}
        if "idle-time-ms" in decl.options:
            wm_cfg["idle_time_micros"] = int(decl.options["idle-time-ms"]) * 1000
        wid = self._id("watermark", decl.name)
        self._add_node(wid, OpName.WATERMARK, wm_cfg)
        self._edge(rel, wid, EdgeType.FORWARD, rel.schema())
        # a debezium source is an UPDATING relation: rows carry _is_retract
        # and downstream plans must use retract-aware operators (reference
        # tables.rs is_updating; de.rs debezium handling)
        updating = str(decl.options.get("format", "")) == "debezium_json"
        return Rel(wid, dtypes, rel.scope, updating)

    # --------------------------------------------------------------- select

    def plan_select(self, q: Select) -> Rel:
        if q.union:
            return self._plan_union(q)
        if q.order_by:
            raise PlanError("ORDER BY is only supported inside OVER(...) windows")
        if q.limit is not None:
            raise PlanError("LIMIT is unsupported on streaming queries")
        if q.distinct:
            raise PlanError(
                "SELECT DISTINCT is unsupported; GROUP BY the columns instead"
            )
        if q.from_table is None:
            raise PlanError("SELECT without FROM is unsupported")
        rel = self._plan_table_ref(q.from_table)
        for j in q.joins:
            other = self._plan_table_ref(j.table)
            rel = self._plan_join(rel, other, j)

        has_agg = bool(q.group_by) or any(
            find_aggregates(it.expr) for it in q.items if not isinstance(it.expr, Star)
        )
        overs = [o for it in q.items if not isinstance(it.expr, Star) for o in find_overs(it.expr)]
        if has_agg and overs:
            raise PlanError("mixing GROUP BY aggregates and OVER window functions is unsupported")
        if has_agg:
            return self._plan_aggregate(rel, q)
        if overs:
            return self._plan_window_fn(rel, q)
        return self._plan_projection(rel, q)

    # ---------------------------------------------------- plain projection

    def _expand_items(self, items: list[SelectItem], scope: Scope) -> list[tuple[str, SqlExpr]]:
        out: list[tuple[str, SqlExpr]] = []
        for i, it in enumerate(items):
            if isinstance(it.expr, Star):
                for name, col in scope.columns_in_order(it.expr.qualifier):
                    out.append((name, Ident(col)))
                continue
            out.append((self._item_name(it, i), it.expr))
        return out

    @staticmethod
    def _item_name(it: SelectItem, i: int) -> str:
        if it.alias:
            return it.alias
        if isinstance(it.expr, Ident):
            return it.expr.name
        if isinstance(it.expr, FuncCall):
            return it.expr.name
        if isinstance(it.expr, OverExpr):
            return it.expr.func.name
        return f"_col_{i}"

    def _plan_projection(self, rel: Rel, q: Select) -> Rel:
        rel, q = self._plan_async_udfs(rel, q)
        rel, q = self._plan_unnest(rel, q)
        pairs = self._expand_items(q.items, rel.scope)
        proj: list[tuple[str, Expr]] = []
        dtypes: dict[str, str] = {}
        out_scope = Scope()
        window_kept = False
        used = set()
        for name, e in pairs:
            # window struct passthrough: project its physical columns
            if isinstance(e, Ident):
                r = rel.scope.try_resolve(e.qualifier, e.name)
                if r is not None and r[0] == "window":
                    start_e, end_e = r[1]
                    proj.append((WINDOW_START, start_e))
                    proj.append((WINDOW_END, end_e))
                    dtypes[WINDOW_START] = "timestamp"
                    dtypes[WINDOW_END] = "timestamp"
                    out_scope.add_window(None, name, (Col(WINDOW_START), Col(WINDOW_END)))
                    out_scope.add_col(None, WINDOW_START, WINDOW_START)
                    out_scope.add_col(None, WINDOW_END, WINDOW_END)
                    window_kept = True
                    continue
            if name in used:
                name = f"{name}_{len(used)}"
            used.add(name)
            ce = compile_expr(e, rel.scope)
            proj.append((name, ce))
            dtypes[name] = infer_dtype(ce, rel.dtypes)
            out_scope.add_col(None, name, name)
        filt = compile_expr(q.where, rel.scope) if q.where is not None else None
        vid = self._id("value")
        self._add_node(vid, OpName.VALUE, {"projections": proj, "filter": filt})
        self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
        # rel.window (the branch's windowing trait) carries through a
        # projection even when the window struct columns are dropped
        return Rel(vid, dtypes, out_scope, rel.updating, rel.window, rel.keyed)

    def _plan_unnest(self, rel: Rel, q: Select):
        """unnest(array_col) select items explode through a dedicated
        UNNEST node (reference UnnestRewriter, rewriters.rs:323); at most
        one unnest per projection, matching the reference."""
        unnests = [
            (i, it) for i, it in enumerate(q.items)
            if not isinstance(it.expr, Star)
            and isinstance(it.expr, FuncCall) and it.expr.name == "unnest"
        ]
        if not unnests:
            return rel, q
        if len(unnests) > 1:
            raise PlanError("only one unnest() per SELECT is supported")
        i, it = unnests[0]
        call = it.expr
        if call.star or len(call.args) != 1:
            raise PlanError("unnest() takes exactly one argument")
        out_name = self._item_name(it, i)
        arr = compile_expr(call.args[0], rel.scope)
        arr_dt = infer_dtype(arr, rel.dtypes)
        elem_dt = arr_dt.split(":", 1)[1] if arr_dt.startswith("array:") else "int64"
        # stage the array column, then explode it; carry columns under their
        # PHYSICAL names (display names can collide across join sides)
        carried: list[str] = []
        for _q2, _n, k, p in rel.scope._order:
            if k == "col" and p not in carried:
                carried.append(p)
        vid = self._id("value", "pre_unnest")
        self._add_node(vid, OpName.VALUE, {
            "projections": [("__unnest_in", arr)] + [(p, Col(p)) for p in carried],
        })
        self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
        uid = self._id("unnest")
        self._add_node(uid, OpName.UNNEST, {
            "column": "__unnest_in", "out_name": out_name, "out_dtype": elem_dt})
        dt2 = dict(rel.dtypes)
        dt2["__unnest_in"] = arr_dt
        self._edge(vid, uid, EdgeType.FORWARD, Schema.of(
            [(n, "string" if d.startswith("array:") else d) for n, d in dt2.items()]
            + [(TIMESTAMP_FIELD, "int64")]))
        scope = Scope()
        dtypes: dict[str, str] = {}
        for q2, n, k, p in rel.scope._order:
            # preserve qualifiers and window structs: other select items /
            # WHERE may reference t.col or the window after the rewrite
            if k == "col":
                scope.add_col(q2, n, p)
                dtypes[p] = rel.dtypes[p]
            else:
                scope.add_window(q2, n, p)
        scope.add_col(None, out_name, out_name)
        dtypes[out_name] = elem_dt
        new_rel = Rel(uid, dtypes, scope, rel.updating, rel.window, rel.keyed)
        items = list(q.items)
        items[i] = SelectItem(Ident(out_name), it.alias)
        q2 = Select(items, q.from_table, q.joins, q.where, q.group_by,
                    q.having, q.order_by, q.limit, q.distinct)
        return new_rel, q2

    def _plan_async_udfs(self, rel: Rel, q: Select):
        """Select items calling async Python UDFs get their own dataflow
        node (reference AsyncUdfRewriter, rewriters.rs): bounded-concurrency
        out-of-band compute, results re-joined positionally."""
        from ..udf import lookup_udf

        async_calls: list[tuple[str, object, object]] = []  # (out, call, udf)
        for i, it in enumerate(q.items):
            if isinstance(it.expr, FuncCall):
                udf = lookup_udf(it.expr.name)
                if udf is not None and udf.is_async:
                    async_calls.append((self._item_name(it, i), it.expr, udf))
        if not async_calls:
            return rel, q
        # pre-filter applies before the async hop (rows dropped early)
        if q.where is not None:
            filt = compile_expr(q.where, rel.scope)
            vid = self._id("value", "pre_async")
            self._add_node(vid, OpName.VALUE, {"projections": None, "filter": filt})
            self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
            rel = Rel(vid, rel.dtypes, rel.scope, rel.updating, rel.window, rel.keyed)
            q = Select(q.items, q.from_table, q.joins, None, q.group_by,
                       q.having, q.order_by, q.limit, q.distinct)
        rewrites: list[tuple[SqlExpr, SqlExpr]] = []
        for out_name, call, udf in async_calls:
            args = tuple(compile_expr(a, rel.scope) for a in call.args)
            aid = self._id("async_udf", udf.name)
            self._add_node(aid, OpName.ASYNC_UDF, {
                "name": udf.name, "fn": udf.fn, "arg_exprs": list(args),
                "out_name": out_name, "return_dtype": udf.return_dtype,
                "ordered": udf.ordered, "max_concurrency": udf.max_concurrency,
            })
            self._edge(rel, aid, EdgeType.FORWARD, rel.schema())
            dt = dict(rel.dtypes)
            dt[out_name] = udf.return_dtype
            scope = Scope()
            for qq, n, k, p in rel.scope._order:
                if k == "col":
                    scope.add_col(qq, n, p)
                else:
                    scope.add_window(qq, n, p)
            scope.add_col(None, out_name, out_name)
            rel = Rel(aid, dt, scope, rel.updating, rel.window, rel.keyed)
            rewrites.append((call, Ident(out_name)))
        items = [SelectItem(replace_nodes(it.expr, rewrites), it.alias)
                 for it in q.items]
        q = Select(items, q.from_table, q.joins, q.where, q.group_by,
                   q.having, q.order_by, q.limit, q.distinct)
        return rel, q

    # ------------------------------------------------------------ aggregate

    def _substitute_aliases(self, e: SqlExpr, q: Select) -> SqlExpr:
        """GROUP BY may reference select aliases or 1-based positions."""
        if isinstance(e, Literal) and isinstance(e.value, int):
            idx = e.value - 1
            if 0 <= idx < len(q.items):
                return q.items[idx].expr
            raise PlanError(f"GROUP BY position {e.value} out of range")
        if isinstance(e, Ident) and e.qualifier is None:
            for it in q.items:
                if it.alias == e.name:
                    return it.expr
        return e

    def _window_from_call(self, fc: FuncCall) -> WindowInfo:
        def iv(a) -> int:
            if isinstance(a, Interval):
                return a.micros
            raise PlanError(f"{fc.name}() arguments must be INTERVAL literals")

        if fc.name == "tumble":
            if len(fc.args) != 1:
                raise PlanError("tumble(width) takes one interval")
            return WindowInfo("tumbling", width=iv(fc.args[0]))
        if fc.name == "hop":
            if len(fc.args) != 2:
                raise PlanError("hop(slide, width) takes two intervals")
            return WindowInfo("sliding", slide=iv(fc.args[0]), width=iv(fc.args[1]))
        if fc.name == "session":
            if len(fc.args) != 1:
                raise PlanError("session(gap) takes one interval")
            return WindowInfo("session", gap=iv(fc.args[0]))
        raise PlanError(f"unknown window function {fc.name}")

    def _plan_aggregate(self, rel: Rel, q: Select) -> Rel:
        # pre-aggregation filter
        if q.where is not None:
            filt = compile_expr(q.where, rel.scope)
            vid = self._id("value", "filter")
            self._add_node(vid, OpName.VALUE, {"projections": None, "filter": filt})
            self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
            rel = Rel(vid, rel.dtypes, rel.scope, rel.updating, rel.window, rel.keyed)

        # classify GROUP BY items
        window: Optional[WindowInfo] = None
        carried_window = False
        window_name = "window"
        window_refs: list[SqlExpr] = []  # AST forms that denote the window
        key_exprs: list[tuple[str, SqlExpr]] = []
        group_rewrites: list[tuple[SqlExpr, SqlExpr]] = []
        for gi_raw in q.group_by:
            gi = self._substitute_aliases(gi_raw, q)
            if isinstance(gi, FuncCall) and gi.name in WINDOW_TVFS:
                if window is not None:
                    raise PlanError("only one window per GROUP BY")
                window = self._window_from_call(gi)
                window_refs.extend([gi_raw, gi])
                for it in q.items:
                    if it.expr == gi and it.alias:
                        window_name = it.alias
                continue
            if isinstance(gi, Ident):
                r = rel.scope.try_resolve(gi.qualifier, gi.name)
                if r is not None and r[0] == "window":
                    # grouping by an existing (subquery) window column
                    if rel.window is None or rel.window.stride is None:
                        raise PlanError(
                            "GROUP BY on a session window column is unsupported"
                        )
                    if window is not None:
                        raise PlanError("only one window per GROUP BY")
                    window = rel.window
                    carried_window = True
                    window_name = gi.name
                    window_refs.extend([gi_raw, gi])
                    continue
            name = None
            if isinstance(gi, Ident):
                name = gi.name
            else:
                for it in q.items:
                    if it.alias and self._substitute_aliases(it.expr, q) == gi:
                        name = it.alias
                        break
            if name is None:
                name = f"__key_{len(key_exprs)}"
            key_exprs.append((name, gi))
            group_rewrites.append((gi_raw, Ident(name)))
            if gi is not gi_raw:
                group_rewrites.append((gi, Ident(name)))

        if rel.window is not None and window is not None and not carried_window:
            raise PlanError("input is already windowed; nested windowing is invalid")

        # collect aggregates from select + having
        agg_calls: list[FuncCall] = []
        for it in q.items:
            if not isinstance(it.expr, Star):
                agg_calls.extend(find_aggregates(it.expr))
        if q.having is not None:
            agg_calls.extend(find_aggregates(q.having))
        uniq_aggs: list[FuncCall] = []
        for a in agg_calls:
            if a not in uniq_aggs:
                uniq_aggs.append(a)
        if not uniq_aggs and not key_exprs and window is None:
            raise PlanError("GROUP BY query with nothing to aggregate")

        aggregates: list[tuple[str, str, Optional[Expr]]] = []
        agg_rewrites: list[tuple[SqlExpr, SqlExpr]] = []
        agg_out_dtypes: dict[str, str] = {}
        for i, a in enumerate(uniq_aggs):
            out = f"__agg_{i}"
            if a.distinct:
                # COUNT(DISTINCT x): collect machinery in session/tumbling
                # windows; per-value multiplicity maps in the updating
                # aggregate (incl. retracting inputs — beyond the reference,
                # which rejects that case). Other DISTINCT aggregates remain
                # out of scope, like the reference's datafusion fork.
                if a.name != "count" or a.star or len(a.args) != 1:
                    raise PlanError(
                        "only COUNT(DISTINCT expr) is supported among "
                        "DISTINCT aggregates")
                e = compile_expr(a.args[0], rel.scope)
                aggregates.append((out, "count_distinct", e))
                agg_out_dtypes[out] = "int64"
                agg_rewrites.append((a, Ident(out)))
                continue
            if rel.updating and a.name in ("min", "max"):
                # reject at plan time: retractions need invertible
                # accumulators (sum/count/avg); min/max would crash at the
                # first retract row mid-stream
                raise PlanError(
                    f"{a.name}() over an updating input is unsupported "
                    "(non-invertible accumulator)"
                )
            if a.name == "count":
                aggregates.append((out, "count", None))
                agg_out_dtypes[out] = "int64"
            elif a.name == "array_agg":
                # collect-kind accumulator (reference datafusion array_agg +
                # UnnestRewriter pairing, rewriters.rs:323)
                if a.star or len(a.args) != 1:
                    raise PlanError("array_agg() takes exactly one argument")
                e = compile_expr(a.args[0], rel.scope)
                aggregates.append((out, "collect", e))
                agg_out_dtypes[out] = f"array:{infer_dtype(e, rel.dtypes)}"
            elif a.name not in ("sum", "min", "max", "avg"):
                from ..udf import lookup_udaf

                udaf = lookup_udaf(a.name)
                if udaf is None:
                    raise PlanError(f"unknown aggregate {a.name!r}")
                if a.star or len(a.args) != 1:
                    raise PlanError(
                        f"UDAF {a.name}() takes exactly one argument"
                    )
                e = compile_expr(a.args[0], rel.scope)
                aggregates.append((out, f"udaf:{udaf.name}", e))
                agg_out_dtypes[out] = udaf.return_dtype
            else:
                if a.star or not a.args:
                    raise PlanError(f"{a.name}(*) is not valid")
                e = compile_expr(a.args[0], rel.scope)
                aggregates.append((out, a.name, e))
                agg_out_dtypes[out] = agg_result_dtype(
                    a.name, infer_dtype(e, rel.dtypes)
                )
            agg_rewrites.append((a, Ident(out)))

        # KEY node
        keyed = bool(key_exprs)
        key_fields = [n for n, _e in key_exprs]
        key_dtypes: dict[str, str] = {}
        cur = rel
        if keyed:
            keys_cfg = []
            for n, ge in key_exprs:
                ce = compile_expr(ge, rel.scope)
                keys_cfg.append((n, ce))
                key_dtypes[n] = infer_dtype(ce, rel.dtypes)
            kid = self._id("key")
            self._add_node(kid, OpName.KEY, {"keys": keys_cfg})
            self._edge(cur, kid, EdgeType.FORWARD, cur.schema())
            mid_dtypes = dict(rel.dtypes)
            mid_dtypes.update(key_dtypes)
            cur = Rel(kid, mid_dtypes, rel.scope, rel.updating, rel.window, True)

        # aggregate node
        input_dtypes = dict(cur.dtypes)

        def dtype_of(e: Expr) -> np.dtype:
            return Field("_", infer_dtype(e, input_dtypes)).numpy_dtype()

        agg_cfg: dict = {
            "key_fields": key_fields,
            "aggregates": aggregates,
            "input_dtype_of": dtype_of,
            # declarative twin of the callable above: survives graph
            # serialization so shipped-IR workers can rebuild the resolver
            "input_dtypes": dict(input_dtypes),
        }
        updating_out = False
        if window is None:
            op = OpName.UPDATING_AGGREGATE
            if "updating_ttl" in self.settings:
                agg_cfg["ttl_micros"] = int(self.settings["updating_ttl"])
            updating_out = True
        elif carried_window:
            op = OpName.TUMBLING_AGGREGATE
            agg_cfg["width_micros"] = window.stride
        elif window.kind == "tumbling":
            op = OpName.TUMBLING_AGGREGATE
            agg_cfg["width_micros"] = window.width
        elif window.kind == "sliding":
            op = OpName.SLIDING_AGGREGATE
            agg_cfg["width_micros"] = window.width
            agg_cfg["slide_micros"] = window.slide
        else:
            op = OpName.SESSION_AGGREGATE
            agg_cfg["gap_micros"] = window.gap
        if rel.updating and window is not None:
            raise PlanError("windowed aggregates over updating inputs are unsupported")
        has_collect = any(k.startswith("udaf:") or k in ("collect", "count_distinct")
                          for _n, k, _e in aggregates)
        if (has_collect and op == OpName.UPDATING_AGGREGATE
                and all(k == "count_distinct" for _n, k, _e in aggregates
                        if k.startswith("udaf:") or k in ("collect", "count_distinct"))):
            # COUNT(DISTINCT) is invertible via per-value multiplicity maps,
            # so the updating aggregate supports it alongside any other
            # kinds this op takes (min/max over a RETRACTING input are
            # rejected by the earlier updating-input check, not here)
            has_collect = False
        if has_collect and op not in (OpName.SESSION_AGGREGATE,
                                      OpName.TUMBLING_AGGREGATE):
            # collected values are host-resident python lists; the sliding
            # path's partial-combine arithmetic and the updating path's
            # retractions have no list analog
            offenders = sorted({
                "COUNT(DISTINCT)" if k == "count_distinct"
                else "array_agg" if k == "collect" else k[5:] + "()"
                for _n, k, _e in aggregates
                if k.startswith("udaf:") or k in ("collect", "count_distinct")})
            raise PlanError(
                f"{', '.join(offenders)} supported in session and tumbling "
                "windows only")
        if has_collect and op == OpName.TUMBLING_AGGREGATE:
            # object lanes cannot ride HBM; force the host aggregator
            agg_cfg["backend"] = "numpy"
        aid = self._id("agg", op.value)
        self._add_node(aid, op, agg_cfg, parallelism=None if keyed else 1)
        self._edge(cur, aid, EdgeType.SHUFFLE if keyed else EdgeType.FORWARD, cur.schema())

        # post-aggregate scope: key fields, window cols, __agg_i
        post_dtypes: dict[str, str] = dict(key_dtypes)
        post_dtypes.update(agg_out_dtypes)
        post_scope = Scope()
        for n in key_fields:
            post_scope.add_col(None, n, n)
        for n in agg_out_dtypes:
            post_scope.add_col(None, n, n)
        window_payload = None
        if window is not None and window.kind != "session" or carried_window:
            post_dtypes[WINDOW_START] = "timestamp"
            post_dtypes[WINDOW_END] = "timestamp"
            if carried_window:
                end_e: Expr = BinOp("+", Col(WINDOW_START), Lit(window.width))
            else:
                end_e = Col(WINDOW_END)
            window_payload = (Col(WINDOW_START), end_e)
            post_scope.add_window(None, window_name, window_payload)
        elif window is not None and window.kind == "session":
            post_dtypes[WINDOW_START] = "timestamp"
            post_dtypes[WINDOW_END] = "timestamp"
            window_payload = (Col(WINDOW_START), Col(WINDOW_END))
            post_scope.add_window(None, window_name, window_payload)
        agg_rel = Rel(aid, post_dtypes, post_scope, updating_out, window, keyed)

        # final projection + HAVING
        rewrites = agg_rewrites + group_rewrites
        proj: list[tuple[str, Expr]] = []
        out_dtypes: dict[str, str] = {}
        out_scope = Scope()
        used: set = set()
        for i, it in enumerate(q.items):
            if isinstance(it.expr, Star):
                raise PlanError("SELECT * is invalid in an aggregate query")
            name = self._item_name(it, i)
            is_window_item = window_payload is not None and (
                it.expr in window_refs
                or (isinstance(it.expr, Ident) and it.expr.qualifier is None
                    and it.expr.name == window_name)
            )
            if is_window_item:
                # the window struct itself selected: project its columns
                out_scope.add_window(None, it.alias or window_name,
                                     (Col(WINDOW_START), Col(WINDOW_END)))
                out_scope.add_col(None, WINDOW_START, WINDOW_START)
                out_scope.add_col(None, WINDOW_END, WINDOW_END)
                proj.append((WINDOW_START, window_payload[0]))
                proj.append((WINDOW_END, window_payload[1]))
                out_dtypes[WINDOW_START] = "timestamp"
                out_dtypes[WINDOW_END] = "timestamp"
                continue
            e = replace_nodes(it.expr, rewrites)
            if name in used:
                name = f"{name}_{i}"
            used.add(name)
            ce = compile_expr(e, post_scope)
            proj.append((name, ce))
            out_dtypes[name] = infer_dtype(ce, post_dtypes)
            out_scope.add_col(None, name, name)
        having_e = None
        if q.having is not None:
            having_e = compile_expr(replace_nodes(q.having, rewrites), post_scope)
        pvid = self._id("value", "post_agg")
        self._add_node(pvid, OpName.VALUE, {"projections": proj, "filter": having_e})
        self._edge(agg_rel, pvid, EdgeType.FORWARD, agg_rel.schema())
        return Rel(pvid, out_dtypes, out_scope, updating_out, window, False)

    # ----------------------------------------------------------------- join

    def _plan_join(self, left: Rel, right: Rel, j) -> Rel:
        lq = left.scope.qualifiers()
        rq = right.scope.qualifiers()

        def side_of(e: SqlExpr) -> Optional[str]:
            """'l' / 'r' / None(ambiguous or neither) by compilability."""
            okl = okr = True
            try:
                compile_expr(e, left.scope)
            except SqlError:
                okl = False
            try:
                compile_expr(e, right.scope)
            except SqlError:
                okr = False
            if okl and not okr:
                return "l"
            if okr and not okl:
                return "r"
            if okl and okr:
                return "lr"
            return None

        from .ast import BinaryOp

        def win_side(e: SqlExpr) -> Optional[str]:
            """'l'/'r' when e names a window struct of that side."""
            if not isinstance(e, Ident):
                return None
            for tag, rel_ in (("l", left), ("r", right)):
                r = rel_.scope.try_resolve(e.qualifier, e.name)
                if r is not None and r[0] == "window":
                    return tag
            return None

        equi: list[tuple[SqlExpr, SqlExpr]] = []
        residual: list[SqlExpr] = []
        for c in _conjuncts(j.on):
            if isinstance(c, BinaryOp) and c.op == "==":
                wl, wr = win_side(c.left), win_side(c.right)
                if wl == "l" and wr == "r":
                    equi.append((c.left, c.right))
                    continue
                if wl == "r" and wr == "l":
                    equi.append((c.right, c.left))
                    continue
                sl, sr = side_of(c.left), side_of(c.right)
                if sl == "l" and sr == "r":
                    equi.append((c.left, c.right))
                    continue
                if sl == "r" and sr == "l":
                    equi.append((c.right, c.left))
                    continue
            residual.append(c)
        if not equi:
            raise PlanError("join requires at least one equality condition")

        windowed = (
            left.window is not None
            and right.window is not None
            and not left.updating
            and not right.updating
        )
        if residual and j.join_type != "inner":
            raise PlanError("non-equi join conditions require INNER JOIN")
        if windowed and left.window != right.window:
            raise PlanError(
                "windowed join requires both sides to share the same window "
                f"(left={left.window}, right={right.window}); InstantJoin "
                "matches rows per window-start bin"
            )

        # key exprs per side; window structs expand to (start, end)
        def key_exprs(side_rel: Rel, raw: SqlExpr) -> list[Expr]:
            if isinstance(raw, Ident):
                r = side_rel.scope.try_resolve(raw.qualifier, raw.name)
                if r is None and raw.qualifier is not None:
                    w = side_rel.scope.try_resolve(None, raw.qualifier)
                    if w is not None and w[0] == "window":
                        r = w  # window.start/.end handled by compile_expr
                if r is not None and r[0] == "window":
                    return [r[1][0], r[1][1]]
            return [compile_expr(raw, side_rel.scope)]

        lkeys: list[Expr] = []
        rkeys: list[Expr] = []
        for le, re_ in equi:
            lk = key_exprs(left, le)
            rk = key_exprs(right, re_)
            if len(lk) != len(rk):
                raise PlanError("cannot equate a window with a scalar in JOIN ON")
            lkeys.extend(lk)
            rkeys.extend(rk)

        def add_key_node(rel: Rel, keys: list[Expr], tag: str) -> Rel:
            keys_cfg = [(f"__jk_{i}", e) for i, e in enumerate(keys)]
            kid = self._id("key", f"join_{tag}")
            self._add_node(kid, OpName.KEY, {"keys": keys_cfg})
            self._edge(rel, kid, EdgeType.FORWARD, rel.schema())
            dt = dict(rel.dtypes)
            for (n, e) in keys_cfg:
                dt[n] = infer_dtype(e, rel.dtypes)
            return Rel(kid, dt, rel.scope, rel.updating, rel.window, True)

        lrel = add_key_node(left, lkeys, "l")
        rrel = add_key_node(right, rkeys, "r")

        # output column names: dedupe collisions with side qualifier prefixes
        def out_names(rel: Rel, other: Rel, prefix: str):
            pairs = []  # (out, src)
            mapping: dict[str, str] = {}
            other_names = {n for _q, n, k, _p in other.scope._order if k == "col"}
            for q, n, k, p in rel.scope._order:
                if k != "col" or p.startswith("__jk_"):
                    continue
                if p in mapping:
                    continue
                out = n if n not in other_names else f"{q or prefix}_{n}"
                mapping[p] = out
                pairs.append((out, p))
            return pairs, mapping

        lpairs, lmap = out_names(lrel, rrel, "left")
        rpairs, rmap = out_names(rrel, lrel, "right")

        jt = j.join_type
        cfg = {
            "join_type": jt,
            "left_names": lpairs,
            "right_names": rpairs,
        }
        if windowed:
            op = OpName.INSTANT_JOIN
            jid = self._id("join", "instant")
        else:
            op = OpName.JOIN_WITH_EXPIRATION
            jid = self._id("join", "updating")
            if "updating_ttl" in self.settings:
                cfg["ttl_micros"] = int(self.settings["updating_ttl"])
        self._add_node(jid, op, cfg)
        self._edge(lrel, jid, EdgeType.LEFT_JOIN, lrel.schema())
        self._edge(rrel, jid, EdgeType.RIGHT_JOIN, rrel.schema())

        out_scope = Scope()
        out_dtypes: dict[str, str] = {}
        nullable_l = jt in ("right", "full")
        nullable_r = jt in ("left", "full")
        for (rel_, mapping, nullable) in ((lrel, lmap, nullable_l), (rrel, rmap, nullable_r)):
            for q, n, k, p in rel_.scope._order:
                if k == "col":
                    if p in mapping:
                        out_scope.add_col(q, n, mapping[p])
                        out_dtypes[mapping[p]] = rel_.dtypes[p]
                else:
                    start, end = p
                    try:
                        out_scope.add_window(q, n, (rename_cols(start, mapping), rename_cols(end, mapping)))
                    except PlanError:
                        pass
        updating_out = not windowed
        window_out = left.window if windowed else None
        jrel = Rel(jid, out_dtypes, out_scope, updating_out, window_out, True)

        if residual:
            combined = residual[0]
            for c in residual[1:]:
                combined = BinaryOp("and", combined, c)
            f = compile_expr(combined, out_scope)
            vid = self._id("value", "join_filter")
            self._add_node(vid, OpName.VALUE, {"projections": None, "filter": f})
            self._edge(jrel, vid, EdgeType.FORWARD, jrel.schema())
            jrel = Rel(vid, out_dtypes, out_scope, updating_out, window_out, True)
        return jrel

    # -------------------------------------------------------- window fns

    def _plan_window_fn(self, rel: Rel, q: Select) -> Rel:
        if q.where is not None:
            filt = compile_expr(q.where, rel.scope)
            vid = self._id("value", "filter")
            self._add_node(vid, OpName.VALUE, {"projections": None, "filter": filt})
            self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
            rel = Rel(vid, rel.dtypes, rel.scope, rel.updating, rel.window, rel.keyed)

        pairs = self._expand_items(q.items, rel.scope)
        overs: list[tuple[str, OverExpr]] = []
        for name, e in pairs:
            for o in find_overs(e):
                overs.append((name, o))
        specs = {o.window for _n, o in overs}
        if len(specs) > 1:
            raise PlanError("all OVER clauses in one SELECT must share a window spec")
        spec = overs[0][1].window

        # partition fields must be physical columns; window structs -> start col
        part_fields: list[str] = []
        pre_proj_extra: list[tuple[str, Expr]] = []
        for i, pe in enumerate(spec.partition_by):
            if isinstance(pe, Ident):
                r = rel.scope.try_resolve(pe.qualifier, pe.name)
                if r is not None and r[0] == "window":
                    start, end = r[1]
                    if isinstance(start, Col):
                        part_fields.append(start.name)
                    else:
                        pre_proj_extra.append((f"__part_{i}", start))
                        part_fields.append(f"__part_{i}")
                    continue
                if r is not None:
                    part_fields.append(r[1])
                    continue
            ce = compile_expr(pe, rel.scope)
            if isinstance(ce, Col):
                part_fields.append(ce.name)
            else:
                pre_proj_extra.append((f"__part_{i}", ce))
                part_fields.append(f"__part_{i}")
        if pre_proj_extra:
            proj = [(n, Col(n)) for n in rel.dtypes] + pre_proj_extra
            vid = self._id("value", "part_keys")
            self._add_node(vid, OpName.VALUE, {"projections": proj})
            self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
            dt = dict(rel.dtypes)
            for n, e in pre_proj_extra:
                dt[n] = infer_dtype(e, rel.dtypes)
            rel = Rel(vid, dt, rel.scope, rel.updating, rel.window, rel.keyed)

        order_by = [(compile_expr(e, rel.scope), asc) for e, asc in spec.order_by]

        functions: list[tuple[str, str, Optional[Expr]]] = []
        over_rewrites: list[tuple[SqlExpr, SqlExpr]] = []
        for i, (_iname, o) in enumerate(overs):
            fname = o.func.name
            out = f"__wf_{i}"
            if fname in RANKING_FUNCS:
                functions.append((out, fname, None))
            elif fname in AGG_FUNCS:
                arg = None
                if not o.func.star and o.func.args:
                    arg = compile_expr(o.func.args[0], rel.scope)
                functions.append((out, fname, arg))
            else:
                raise PlanError(f"unsupported window function {fname!r}")
            over_rewrites.append((o, Ident(out)))

        # shuffle by partition so parallel instances see whole partitions
        key_cfg = [(f, Col(f)) for f in part_fields]
        cur: Rel = rel
        keyed = bool(part_fields)
        if keyed:
            kid = self._id("key", "wf")
            self._add_node(kid, OpName.KEY, {"keys": key_cfg})
            self._edge(cur, kid, EdgeType.FORWARD, cur.schema())
            cur = Rel(kid, rel.dtypes, rel.scope, rel.updating, rel.window, True)

        wf_cfg = {
            "partition_fields": part_fields,
            "order_by": order_by,
            "functions": functions,
        }
        wid = self._id("window_fn")
        self._add_node(wid, OpName.WINDOW_FUNCTION, wf_cfg, parallelism=None if keyed else 1)
        self._edge(cur, wid, EdgeType.SHUFFLE if keyed else EdgeType.FORWARD, cur.schema())
        post_dtypes = dict(rel.dtypes)
        for out, kind, arg in functions:
            if kind in RANKING_FUNCS or kind == "count":
                post_dtypes[out] = "int64"
            elif kind == "avg":
                post_dtypes[out] = "float64"
            else:
                post_dtypes[out] = infer_dtype(arg, rel.dtypes) if arg is not None else "int64"
        post_scope = Scope()
        for q_, n, k, p in rel.scope._order:
            if k == "col":
                post_scope.add_col(q_, n, p)
            else:
                post_scope.add_window(q_, n, p)
        for out, _k, _a in functions:
            post_scope.add_col(None, out, out)
        wf_rel = Rel(wid, post_dtypes, post_scope, rel.updating, rel.window, keyed)

        # final projection applying the item expressions
        proj = []
        out_dtypes: dict[str, str] = {}
        out_scope = Scope()
        for name, e in pairs:
            if isinstance(e, Ident):
                r = rel.scope.try_resolve(e.qualifier, e.name)
                if r is not None and r[0] == "window":
                    start_e, end_e = r[1]
                    proj.append((WINDOW_START, start_e))
                    proj.append((WINDOW_END, end_e))
                    out_dtypes[WINDOW_START] = "timestamp"
                    out_dtypes[WINDOW_END] = "timestamp"
                    out_scope.add_window(None, name, (Col(WINDOW_START), Col(WINDOW_END)))
                    continue
            ce = compile_expr(replace_nodes(e, over_rewrites), post_scope)
            proj.append((name, ce))
            out_dtypes[name] = infer_dtype(ce, post_dtypes)
            out_scope.add_col(None, name, name)
        pvid = self._id("value", "post_wf")
        self._add_node(pvid, OpName.VALUE, {"projections": proj})
        self._edge(wf_rel, pvid, EdgeType.FORWARD, wf_rel.schema())
        return Rel(pvid, out_dtypes, out_scope, rel.updating, rel.window, False)

    # ---------------------------------------------------------------- union

    def _plan_union(self, q: Select) -> Rel:
        if any(how != "all" for how, _r in q.union):
            raise PlanError("UNION DISTINCT is unsupported; use UNION ALL")
        lhs = Select(
            q.items, q.from_table, q.joins, q.where, q.group_by, q.having,
            q.order_by, q.limit, q.distinct,
        )
        lrel = self.plan_select(lhs)
        lnames = list(lrel.dtypes)
        branches: list[Rel] = [lrel]
        updating = lrel.updating
        for _how, rhs_q in q.union:
            rrel = self.plan_select(rhs_q)
            rnames = list(rrel.dtypes)
            if len(lnames) != len(rnames):
                raise PlanError("UNION sides have different column counts")
            # align each branch positionally to the left's names
            rproj = [(ln, Col(rn)) for ln, rn in zip(lnames, rnames)]
            rvid = self._id("value", "union_align")
            self._add_node(rvid, OpName.VALUE, {"projections": rproj})
            self._edge(rrel, rvid, EdgeType.FORWARD, rrel.schema())
            branches.append(Rel(rvid, dict(lrel.dtypes), lrel.scope, rrel.updating))
            updating = updating or rrel.updating
        uid = self._id("value", "union")
        self._add_node(uid, OpName.VALUE, {"projections": None})
        out_schema = lrel.schema()
        for b in branches:
            self._edge(b, uid, EdgeType.FORWARD, out_schema)
        scope = Scope()
        for n in lnames:
            scope.add_col(None, n, n)
        return Rel(uid, dict(lrel.dtypes), scope, updating, None, False)

    # ---------------------------------------------------------------- sinks

    def _plan_insert(self, stmt: Insert) -> None:
        rel = self.plan_select(stmt.query)
        if stmt.table not in self.tables:
            raise PlanError(f"unknown sink table {stmt.table!r}")
        decl = self.tables[stmt.table]
        if decl.ttype == "source":
            raise PlanError(f"table {stmt.table!r} is a source; cannot INSERT into it")
        if decl.options.get("connector") is None:
            # memory table: no sink node — the coerced stream itself becomes
            # the named relation later FROM clauses read
            if stmt.table in self.memory_rels:
                raise PlanError(
                    f"memory table {stmt.table!r} already written; multiple "
                    "INSERTs into one memory table are unsupported")
            self.memory_rels[stmt.table] = self._coerce_to_decl(rel, decl)
            return
        out_names = list(rel.dtypes)
        sink_cols = decl.physical_columns()
        if sink_cols:
            if len(sink_cols) != len(out_names):
                raise PlanError(
                    f"INSERT INTO {stmt.table}: query produces {len(out_names)} "
                    f"columns but sink has {len(sink_cols)}"
                )
            proj = []
            fields = []
            for c, src in zip(sink_cols, out_names):
                dt = sql_type_to_dtype(c.type_name)
                src_dt = rel.dtypes[src]
                e: Expr = Col(src)
                if dt != src_dt and not (
                    {dt, src_dt} <= {"timestamp", "int64"}
                ):
                    e = Cast(e, "int64" if dt == "timestamp" else dt)
                proj.append((c.name, e))
                fields.append(Field(c.name, dt, c.nullable))
            sink_schema = Schema(tuple(fields) + (Field(TIMESTAMP_FIELD, "int64"),))
            cvid = self._id("value", "sink_coerce")
            self._add_node(cvid, OpName.VALUE, {"projections": proj})
            self._edge(rel, cvid, EdgeType.FORWARD, rel.schema())
            src_id = cvid
        else:
            fields = [Field(n, d) for n, d in rel.dtypes.items()]
            sink_schema = Schema(tuple(fields) + (Field(TIMESTAMP_FIELD, "int64"),))
            src_id = rel.node_id
        cfg = dict(decl.options)
        cfg.pop("type", None)
        cfg["connector"] = decl.connector
        cfg["schema"] = sink_schema
        sid = self._id("sink", decl.name)
        self._add_node(sid, OpName.SINK, cfg, parallelism=1,
                       description=f"{decl.connector}:{decl.name}")
        self._edge(src_id, sid, EdgeType.FORWARD, sink_schema)
        self.sinks.append(SinkInfo(sid, stmt.table, decl.connector))

    def _coerce_to_decl(self, rel: Rel, decl: TableDecl) -> Rel:
        """Project a query's output positionally onto a declared column list
        (names + dtypes), as the sink path does, yielding a Rel scoped under
        the declared names — the body of a memory table."""
        cols = decl.physical_columns()
        out_names = list(rel.dtypes)
        if not cols:
            return rel
        if len(cols) != len(out_names):
            raise PlanError(
                f"INSERT INTO {decl.name}: query produces {len(out_names)} "
                f"columns but table declares {len(cols)}")
        proj = []
        dtypes: dict[str, str] = {}
        for c, src in zip(cols, out_names):
            dt = sql_type_to_dtype(c.type_name)
            src_dt = rel.dtypes[src]
            e: Expr = Col(src)
            if dt != src_dt and not ({dt, src_dt} <= {"timestamp", "int64"}):
                e = Cast(e, "int64" if dt == "timestamp" else dt)
            proj.append((c.name, e))
            dtypes[c.name] = dt
        vid = self._id("value", f"{decl.name}_memory")
        self._add_node(vid, OpName.VALUE, {"projections": proj})
        self._edge(rel, vid, EdgeType.FORWARD, rel.schema())
        scope = Scope()
        for c in cols:
            scope.add_col(None, c.name, c.name)
        return Rel(vid, dtypes, scope, rel.updating, rel.window, rel.keyed)

    def _plan_preview(self, q: Select) -> None:
        rel = self.plan_select(q)
        rows: list = []
        sid = self._id("sink", "preview")
        self._add_node(
            sid, OpName.SINK,
            {"connector": "preview", "rows": rows, "schema": rel.schema()},
            parallelism=1,
        )
        self._edge(rel, sid, EdgeType.FORWARD, rel.schema())
        self.sinks.append(SinkInfo(sid, "<preview>", "preview", rows))


def connection_table_decl(ct: dict) -> TableDecl:
    """A registered connection table (API CRUD rows: name, connector,
    table_type, config, schema_fields) as a planner TableDecl — pipelines
    reference it by name with no inline DDL (reference connection_tables
    registered into the ArroyoSchemaProvider, tables.rs)."""
    from .ast import ColumnDef

    cols = tuple(
        ColumnDef(f["name"], str(f.get("type", "TEXT")).upper(),
                  bool(f.get("nullable", True)))
        for f in ct.get("schema_fields", [])
    )
    options = dict(ct.get("config") or {})
    options["connector"] = ct["connector"]
    options["type"] = ct.get("table_type", "source")
    return TableDecl(ct["name"], cols, options)


def plan_query(sql: str, parallelism: int = 1,
               connection_tables: Optional[list[dict]] = None,
               analyze: bool = True) -> PlannedPipeline:
    """Plan a SQL script; with ``analyze`` (the default) the static plan
    analyzer (arroyo_tpu.analysis) then validates the graph — ERROR
    diagnostics raise AnalysisError (a SqlError) before any execution,
    WARNING diagnostics are logged. Pass analyze=False to collect the full
    diagnostic list yourself (the `check` CLI does)."""
    p = Planner(parallelism)
    for ct in connection_tables or []:
        p.tables[ct["name"]] = connection_table_decl(ct)
    pp = p.plan(sql)
    if analyze:
        from ..analysis import AnalysisError, Severity, analyze_graph

        diags = analyze_graph(pp.graph)
        errors = [d for d in diags if d.severity == Severity.ERROR]
        if errors:
            raise AnalysisError(errors)
        for d in diags:
            _log.warning("plan analysis: %s", d.render())
    return pp


def set_parallelism(graph: Graph, n: int) -> None:
    """Force internal parallelism for tests (reference smoke_tests
    set_internal_parallelism, engine.rs:232-298): scale every node except
    sinks (output determinism) and keyless global stages (pinned at 1)."""
    for node in graph.nodes.values():
        if node.op == OpName.SINK:
            continue
        if node.parallelism == 1 and node.op in (
            OpName.TUMBLING_AGGREGATE, OpName.SLIDING_AGGREGATE,
            OpName.SESSION_AGGREGATE, OpName.UPDATING_AGGREGATE,
            OpName.WINDOW_FUNCTION,
        ) and not node.config.get("key_fields") and not node.config.get("partition_fields"):
            continue  # global stage must stay single-instance
        node.parallelism = n


def executed_graph_view(sql: str, parallelism: int = 1,
                        connection_tables: Optional[list[dict]] = None
                        ) -> tuple[list[dict], list[dict]]:
    """The plan as the engine EXECUTES it — parallelism applied, Forward
    runs fused when ``pipeline.chaining.enabled`` — as plain node/edge
    dicts (the ``/pipelines/<id>/graph`` payload shape). Runtime metrics
    and the cost profile key by the executed graph's node ids (``"a+b"``
    for a chained run), so every plan-annotating consumer (the graph API
    endpoint, ``explain``) must derive its view here or its ids drift from
    the ones the runtime reports."""
    pp = plan_query(sql, connection_tables=connection_tables)
    if parallelism > 1:
        set_parallelism(pp.graph, parallelism)
    g = pp.graph
    from ..config import config as _cfg

    if _cfg().get("pipeline.chaining.enabled"):
        from ..optimizer import chain_graph

        g = chain_graph(g)
    compile_on = _cfg().get("segment.compile.enabled", True)
    nodes = [{"id": n.node_id, "op": n.op.value,
              "description": n.description or n.op.value,
              "parallelism": n.parallelism,
              # plan-time marking (optimizer.chain_graph): this chained run
              # will be offered to the whole-segment compiler. Runtime truth
              # (compiled vs fell back) rides the profile's
              # ``segment_compiled`` flag and the SEGMENT_* events
              **({"compilable": True}
                 if compile_on and n.config.get("compile") else {}),
              # the plan-time reject reason (optimizer.chain_graph /
              # AR009): consumers render "why is my segment not compiled"
              # without waiting for a runtime fallback event
              **({"not_compilable": n.config["compile_reject"]}
                 if compile_on and n.config.get("compile_reject") else {})}
             for n in g.nodes.values()]
    edges = [{"src": e.src, "dst": e.dst, "type": e.edge_type.value}
             for e in g.edges]
    return nodes, edges
