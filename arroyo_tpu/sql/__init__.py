"""SQL frontend: SQL text -> logical dataflow Graph.

TPU-native replacement for the reference's planner crate
(crates/arroyo-planner — parse_and_get_program, lib.rs:534): instead of a
forked DataFusion producing serialized physical plans, a self-contained
lexer/parser/planner compiles SQL directly to the Graph IR whose operator
bodies are the jax window runtime (arroyo_tpu.ops) and the expression
AST (arroyo_tpu.expr).

Scope mirrors what the reference's smoke-test suite exercises: connector DDL
with event-time/watermark options, projections/filters, tumble/hop/session
window aggregates, updating (non-windowed) aggregates, stream-stream windowed
and updating joins, SQL window functions (OVER), views, and INSERT INTO.
"""

from .parser import parse_statements
from .planner import PlanError, Planner, plan_query

__all__ = ["parse_statements", "plan_query", "Planner", "PlanError"]
