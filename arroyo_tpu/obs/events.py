"""Structured job event log: the third observability pillar.

PR 6 gave jobs metrics and epoch traces; this module gives them a
correlated *event* feed (reference: arroyo-server-common init_logging +
the per-job error/event list the API surfaces). Every operationally
meaningful moment — an operator exception, a whole-set restore, a wedged
epoch, a re-delivered commit, a rescale, a health transition — is recorded
as a ``JobEvent`` (timestamp, level, stable machine-readable ``code``,
scope {node, subtask, worker, epoch}, message, data) into a bounded
per-job ring. Worker subprocesses relay their events to the controller as
``{"event": "log"}`` JSON lines (the PR 6 span-relay pattern, via
``Engine.drain_relay``); the controller persists a capped ``job_events``
DB table served at ``GET /api/v1/jobs/<id>/events`` and read by
``python -m arroyo_tpu logs``. Epoch-scoped events additionally render as
instant markers inside the Chrome trace export, so one Perfetto view
correlates spans and events.

A ``logging.Handler`` bridge (installed by ``server_common.init_logging``
when ``logging.capture-events`` is set) turns existing stdlib log calls
that carry job context (``extra={"job_id": ...}``) into events too, so
adopting the pillar needs no rewrite of call sites.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Iterable, Optional

LEVELS = ("DEBUG", "INFO", "WARN", "ERROR")
_LEVEL_RANK = {name: i for i, name in enumerate(LEVELS)}

# Stable machine-readable event codes. Every code emitted anywhere in the
# package MUST appear here (and in the README "Events & health" table —
# tools/lint.sh --events-catalog enforces both), so dashboards and alerts
# can key on codes without grepping messages.
EVENT_CODES: dict[str, tuple[str, str]] = {
    # code: (default level, meaning)
    "OPERATOR_PANIC": (
        "ERROR", "an operator raised in the task run loop; the scope names "
                 "the node/subtask and data carries a traceback digest"),
    "WORKER_LOST": (
        "ERROR", "a worker of the set crashed, missed heartbeats, or wedged "
                 "checkpoints past escalation; the whole set comes down"),
    "RESTORE": (
        "WARN", "the worker set is being restored from the last globally "
                "complete checkpoint (epoch in scope)"),
    "EPOCH_WEDGED": (
        "WARN", "the stuck-checkpoint watchdog declared an epoch failed; "
                "its torn shards are subsumed and the checkpoint retried"),
    "COMMIT_REDELIVERED": (
        "WARN", "a dropped phase-2 commit for an earlier epoch was "
                "re-delivered cumulatively with a later one"),
    "RESCALE": (
        "INFO", "a live rescale started (data: from/to parallelism); the "
                "set drains behind a final checkpoint and restarts"),
    "AUTOSCALE_DECISION": (
        "INFO", "the elastic autoscaler decided a target parallelism after "
                "its hysteresis window (data: direction, from/to, raw "
                "target before the min/max rails, breaching signals)"),
    "AUTOSCALE_STARTED": (
        "INFO", "an autoscaler-initiated rescale began actuating: the set "
                "drains behind a final checkpoint (data: from/to)"),
    "AUTOSCALE_DONE": (
        "INFO", "the autoscaled worker set is running at its new "
                "parallelism (data: parallelism, restore epoch)"),
    "AUTOSCALE_BACKOFF": (
        "WARN", "a scale transition was disrupted; the next decision is "
                "gated by an exponential backoff window (data: backoff_s, "
                "consecutive failures)"),
    "HEALTH_DEGRADED": (
        "WARN", "a health rule fired past its hysteresis window; the job "
                "is degraded (data: per-rule detail)"),
    "HEALTH_CRITICAL": (
        "ERROR", "a critical-severity health rule is firing (data: "
                 "per-rule detail)"),
    "HEALTH_OK": (
        "INFO", "all health rules cleared their hysteresis window; the job "
                "is healthy again"),
    "SEGMENT_COMPILED": (
        "INFO", "a chained operator segment compiled into one jitted batch "
                "function; data carries member count, compile time, and "
                "the input schema the cache entry is keyed on"),
    "SEGMENT_FALLBACK": (
        "WARN", "a marked segment could not trace (or its first-batch "
                "verification diverged) and degraded to the interpreted "
                "per-operator path for this run; data carries the reason"),
    "MESH_OVERFLOW": (
        "WARN", "key skew pushed rows past the sharded aggregate's fixed-"
                "capacity exchange lane into the per-shard HBM spill "
                "buffer — correct but slower, and exhausting that buffer "
                "IS an error, so raise device.spill-capacity first "
                "(throttled: re-emitted only when the resident count "
                "doubles; data: overflow_rows)"),
    "JOB_QUEUED": (
        "INFO", "the fleet could not place the job (pool full / tenant at "
                "quota / placement 409'd) — it waits in its tenant's FIFO "
                "admission queue instead of failing (data: tenant, slots, "
                "reason; a 409 re-queue carries its deterministic "
                "backoff_s and is emitted at WARN)"),
    "JOB_ADMITTED": (
        "INFO", "the fleet's deficit-round-robin pass granted the job's "
                "slots; it proceeds to Scheduling (data: tenant, slots, "
                "waited_s when it queued first)"),
    "JOB_REJECTED": (
        "ERROR", "admission rejected structurally: the job's own demand "
                 "exceeds its tenant's max-slots quota, so it could never "
                 "run — the one admission verdict that fails the job"),
    "JOB_PREEMPTED": (
        "WARN", "a quota change left the tenant over its slot budget; the "
                "fleet preempts the tenant's newest job — drain behind a "
                "final checkpoint, then back into the admission queue"),
    "JOB_TICK_OVERRUN": (
        "WARN", "the job's supervision step overran fleet.tick-budget-ms; "
                "it is deprioritized (neighbors tick first, this job is "
                "skipped for `penalty` ticks then always runs again) so a "
                "melting job cannot starve its neighbors' heartbeat/"
                "watchdog checks (data: ms, budget_ms, penalty)"),
    "JOB_EVOLVE_STARTED": (
        "INFO", "a live evolution (versioned redeploy) was accepted: the "
                "running set drains behind a final checkpoint before the "
                "evolved plan restores from it (data: drain_epoch)"),
    "JOB_EVOLVE_CLASSIFIED": (
        "INFO", "the plan-diff pass classified every operator of the "
                "evolved plan (data: per-node carried/rebuilt/dropped/"
                "stateless classifications, pipeline version); emitted at "
                "ERROR with the AR-series diagnostics when the evolution "
                "is rejected and the unchanged plan restarts instead"),
    "JOB_EVOLVE_CUTOVER": (
        "INFO", "blue/green cutover: the evolved set's first epoch went "
                "durable (it caught up past the carried offsets) and its "
                "withheld phase-2 commits are released atomically at this "
                "barrier (epoch in scope)"),
    "JOB_EVOLVE_DONE": (
        "INFO", "the evolution finished: the evolved plan owns the single "
                "committed lineage at its bumped pipeline version"),
    "CHECKPOINT_QUARANTINED": (
        "ERROR", "a checkpoint epoch failed integrity verification (torn/"
                 "corrupt marker, sidecar, table file, or missing spill "
                 "run) and was quarantined: its marker is preserved under "
                 "metadata.json.quarantined, GC refuses the epoch, and an "
                 "operator must resolve it (data: reason)"),
    "RESTORE_FELL_BACK": (
        "WARN", "restore skipped one or more quarantined epochs and fell "
                "back to the next-older valid checkpoint; sources rewind "
                "to that epoch's offsets so replay covers the gap (data: "
                "skipped epochs with reasons, fallback epoch)"),
    "BAD_DATA_DROPPED": (
        "WARN", "a connector dropped undeserializable records under "
                "bad_data=drop (throttled; data carries the drop count "
                "since the last emission and the last error)"),
    "SPILL_STARTED": (
        "INFO", "tiered state engaged: a subtask's resident state passed "
                "its budget and cold partitions began spilling to storage "
                "(data: table, partition, rows, bytes)"),
    "SPILL_FALLBACK": (
        "WARN", "a spill or spill-compaction write failed after retries; "
                "the state stays resident (re-pinned hot) and spilling "
                "backs off — degraded, never corrupted (data: reason)"),
    "LOG": (
        "INFO", "a stdlib logging record carrying job context, bridged by "
                "the logging.capture-events handler"),
}


def now_us() -> int:
    return int(time.time() * 1e6)


def level_rank(level: str) -> int:
    return _LEVEL_RANK.get(str(level).upper(), 1)


class JobEventLog:
    """Bounded per-job ring of structured events, plus total counts per
    (code, level) for the ``arroyo_events_total`` exposition (counts keep
    growing after ring eviction — a log flood bounds memory, not truth).

    Single global instance (``recorder``). Each record gets a per-job,
    monotonically increasing ``seq`` so relays (worker -> controller) and
    persistence (controller -> DB) can drain incrementally: "everything
    after the seq I last saw" — the same cursor the ``logs --follow`` CLI
    and the ``?after=`` API parameter use.
    """

    def __init__(self, max_events_per_job: int = 512):
        self.default_max = max_events_per_job
        self._lock = threading.Lock()
        self._jobs: dict[str, list[dict]] = {}
        self._seq: dict[str, int] = {}
        # (job, code, level) -> count of ALL events ever recorded
        self._counts: dict[tuple[str, str, str], int] = {}

    def _cap(self) -> int:
        from ..config import config

        return int(config().get("obs.events.max-per-job",
                                self.default_max) or self.default_max)

    def record(self, job_id: str, level: str, code: str, message: str = "",
               node: Optional[str] = None, subtask: Optional[int] = None,
               worker: Optional[int] = None, epoch: Optional[int] = None,
               data: Optional[dict] = None, t_us: Optional[int] = None) -> dict:
        level = str(level).upper()
        if level not in _LEVEL_RANK:
            level = "INFO"
        ev = {
            "ts_us": now_us() if t_us is None else int(t_us),
            "level": level,
            "code": str(code),
            "node": node,
            "subtask": None if subtask is None else int(subtask),
            "worker": None if worker is None else int(worker),
            "epoch": None if epoch is None else int(epoch),
            "message": str(message),
            "data": data or {},
        }
        cap = self._cap()
        with self._lock:
            seq = self._seq.get(job_id, 0) + 1
            self._seq[job_id] = seq
            ev["seq"] = seq
            ring = self._jobs.setdefault(job_id, [])
            ring.append(ev)
            if len(ring) > cap:
                del ring[: len(ring) - cap]
            key = (job_id, ev["code"], level)
            self._counts[key] = self._counts.get(key, 0) + 1
        return ev

    def ingest(self, job_id: str, ev: dict) -> Optional[dict]:
        """Replay a relayed event dict (the controller feeds worker ``log``
        events through here). The original timestamp/level/code/scope are
        preserved; a fresh local seq is assigned."""
        if not isinstance(ev, dict) or "code" not in ev:
            return None
        return self.record(
            job_id, ev.get("level", "INFO"), ev["code"],
            message=ev.get("message", ""), node=ev.get("node"),
            subtask=ev.get("subtask"), worker=ev.get("worker"),
            epoch=ev.get("epoch"), data=ev.get("data") or {},
            t_us=ev.get("ts_us"))

    def events(self, job_id: str, level: Optional[str] = None,
               since_us: Optional[int] = None,
               after_seq: Optional[int] = None) -> list[dict]:
        """Ring contents oldest first, filtered by minimum level, wall-time
        floor, and/or seq cursor."""
        with self._lock:
            out = list(self._jobs.get(job_id, ()))
        if after_seq is not None:
            out = [e for e in out if e["seq"] > after_seq]
        if since_us is not None:
            out = [e for e in out if e["ts_us"] >= since_us]
        if level is not None:
            floor = level_rank(level)
            out = [e for e in out if _LEVEL_RANK[e["level"]] >= floor]
        return out

    def last_seq(self, job_id: str) -> int:
        with self._lock:
            return self._seq.get(job_id, 0)

    def ensure_seq_floor(self, job_id: str, seq: int) -> None:
        """Raise the job's seq counter to at least ``seq``. A restarted
        controller re-adopting a job must seed this from the DB's max
        persisted seq, or fresh events would collide with already-persisted
        (job, seq) rows and be dropped by the idempotent flush."""
        with self._lock:
            if seq > self._seq.get(job_id, 0):
                self._seq[job_id] = int(seq)

    def counts_snapshot(self) -> dict[tuple[str, str, str], int]:
        with self._lock:
            return dict(self._counts)

    def clear_job(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)
            self._seq.pop(job_id, None)
            self._counts = {k: v for k, v in self._counts.items()
                            if k[0] != job_id}


recorder = JobEventLog()


def traceback_digest(tb_text: str) -> dict:
    """Compact identity for an exception: a short stable hash of the
    traceback plus its last line, so repeated panics of the same bug
    aggregate without shipping full stacks through the event feed."""
    import hashlib

    lines = [l for l in tb_text.strip().splitlines() if l.strip()]
    return {
        "digest": hashlib.sha1(tb_text.encode(errors="replace"))
        .hexdigest()[:12],
        "error": lines[-1][:200] if lines else "",
    }


# ------------------------------------------------------- stdlib log bridge

_STDLIB_LEVEL = {"DEBUG": "DEBUG", "INFO": "INFO", "WARNING": "WARN",
                 "ERROR": "ERROR", "CRITICAL": "ERROR"}


class JobEventBridgeHandler(logging.Handler):
    """Captures stdlib log records that carry job context into the event
    ring: ``logger.warning("...", extra={"job_id": jid, "event_code": ...,
    "node": ..., "subtask": ..., "worker": ..., "epoch": ...})``. Records
    without a ``job_id`` pass through untouched (the bridge is a tap, not
    a filter), so service-level logs never pollute per-job feeds."""

    def emit(self, record: logging.LogRecord) -> None:
        job_id = getattr(record, "job_id", None)
        if not job_id:
            return
        try:
            recorder.record(
                str(job_id),
                _STDLIB_LEVEL.get(record.levelname, "INFO"),
                getattr(record, "event_code", "LOG"),
                message=record.getMessage(),
                node=getattr(record, "node", None),
                subtask=getattr(record, "subtask", None),
                worker=getattr(record, "worker", None),
                epoch=getattr(record, "epoch", None),
            )
        except Exception:  # noqa: BLE001 - logging must never raise
            self.handleError(record)


def install_bridge(root: Optional[logging.Logger] = None) -> JobEventBridgeHandler:
    """Idempotently attach the bridge handler (server_common.init_logging
    calls this when ``logging.capture-events`` is set)."""
    root = root or logging.getLogger()
    for h in root.handlers:
        if isinstance(h, JobEventBridgeHandler):
            return h
    handler = JobEventBridgeHandler()
    root.addHandler(handler)
    return handler


# ------------------------------------------------------------- rendering


def render_event(ev: dict) -> str:
    """One `logs` CLI line: time, level, code, scope, message, extra data."""
    ts = time.strftime("%H:%M:%S", time.localtime(ev["ts_us"] / 1e6))
    scope = []
    if ev.get("node") is not None:
        sub = ev.get("subtask")
        scope.append(f"{ev['node']}/{sub}" if sub is not None else ev["node"])
    if ev.get("worker") is not None:
        scope.append(f"w{ev['worker']}")
    if ev.get("epoch") is not None:
        scope.append(f"e{ev['epoch']}")
    where = f" [{' '.join(scope)}]" if scope else ""
    extra = ""
    if ev.get("data"):
        import json as _json

        extra = "  " + _json.dumps(ev["data"], sort_keys=True,
                                   separators=(",", ":"))
    return (f"{ts}  {ev['level']:<5} {ev['code']:<18}{where}  "
            f"{ev.get('message', '')}{extra}")


def trail(events: Iterable[dict],
          key: Callable[[dict], str] = lambda e: e["code"]) -> list[str]:
    """Causally-ordered (seq) projection of an event list — what the chaos
    tests assert an ERROR -> RESTORE -> recovery sequence against."""
    return [key(e) for e in sorted(events, key=lambda e: e.get("seq", 0))]
