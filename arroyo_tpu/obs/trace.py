"""Epoch-lifecycle tracing: correlated span trees for checkpoint epochs.

The reference engine surfaces per-operator rates and backpressure, but when
an epoch takes 90 seconds — or never completes — counters cannot say WHERE
the time went. This module records the checkpoint lifecycle as a timeline of
correlated events per epoch:

    trigger                controller (or single-worker engine) injects the
                           barrier into the sources
    align_start            a subtask saw its FIRST barrier input and began
                           holding traffic behind the alignment
    snapshot_start         alignment complete (every live input delivered the
                           barrier); the subtask starts writing its snapshot
    ack                    the subtask's snapshot is durable and its
                           checkpoint-completed response was posted
    metadata_durable       the job-level metadata marker is durable (global
                           coverage across every worker — 2PC phase 1)
    commit_sent            phase-2 commit left the controller for a worker
    commit_delivered       a worker's engine delivered the commit to its
                           committing operators

Events land in a process-global, bounded, in-memory ring (per job, newest
``obs.trace.max-epochs`` epochs) so the recorder is safe to leave on in
production. Multi-process workers relay their events to the controller over
the existing JSON-lines protocol (``{"event": "span", ...}``); the
controller's recorder therefore always holds the whole job's timeline and
persists it to the DB for ``GET /api/v1/jobs/<id>/traces``.

Exports:

    chrome_trace(...)       Chrome trace-event JSON (trace-viewer /
                            Perfetto's "Open with legacy UI" loads it as-is)
    timeline_report(...)    human-readable per-epoch timeline naming the
                            exact subtask whose barrier never arrived or
                            whose snapshot never acked — attached to the
                            wedged-epoch watchdog report and to
                            CheckpointWait timeouts
    phase_durations(...)    align/snapshot/ack/commit wall seconds per epoch
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional

# the epoch lifecycle, in causal order (used for stable sorting of events
# that share a timestamp, and by the timeline report)
EVENT_ORDER = ("trigger", "align_start", "snapshot_start", "ack",
               "metadata_durable", "commit_sent", "commit_delivered")

_EVENT_RANK = {name: i for i, name in enumerate(EVENT_ORDER)}


def now_us() -> int:
    """Wall-clock micros — the same clock CheckpointBarrier timestamps use,
    so spans correlate with barrier metadata across processes."""
    return int(time.time() * 1e6)


class EpochTraceRecorder:
    """Bounded per-job ring of epoch timelines. Single global instance
    (``recorder``); every record is an at-most-once fact keyed by
    (event, node, subtask, worker), so duplicate reports (an embedded
    engine and its controller sharing the process) collapse to the first
    observation instead of double-counting."""

    def __init__(self, max_epochs: int = 32, max_events_per_epoch: int = 4096):
        self.max_epochs = max_epochs
        self.max_events = max_events_per_epoch
        self._lock = threading.Lock()
        # job -> {epoch -> {(event, node, subtask, worker) -> t_us}}
        self._jobs: dict[str, dict[int, dict[tuple, int]]] = {}

    def record(self, job_id: str, epoch: int, event: str,
               node: Optional[str] = None, subtask: Optional[int] = None,
               worker: Optional[int] = None, t_us: Optional[int] = None) -> None:
        t = now_us() if t_us is None else int(t_us)
        key = (event, node, subtask, worker)
        with self._lock:
            epochs = self._jobs.setdefault(job_id, {})
            ev = epochs.get(epoch)
            if ev is None:
                ev = epochs[epoch] = {}
                while len(epochs) > self.max_epochs:
                    epochs.pop(min(epochs))
            if key not in ev and len(ev) < self.max_events:
                ev[key] = t

    def epochs(self, job_id: str) -> list[int]:
        with self._lock:
            return sorted(self._jobs.get(job_id, ()))

    def events(self, job_id: str, epoch: int) -> list[dict]:
        """One epoch's timeline, oldest first (ties broken causally)."""
        with self._lock:
            ev = dict(self._jobs.get(job_id, {}).get(epoch, {}))
        out = [
            {"epoch": epoch, "event": k[0], "node": k[1], "subtask": k[2],
             "worker": k[3], "t_us": t}
            for k, t in ev.items()
        ]
        out.sort(key=lambda e: (e["t_us"], _EVENT_RANK.get(e["event"], 99)))
        return out

    def ingest(self, job_id: str, events: Iterable[dict]) -> None:
        """Replay relayed/persisted event dicts (the controller feeds worker
        ``span`` events through here; the API feeds DB rows)."""
        for e in events:
            self.record(job_id, int(e["epoch"]), e["event"], e.get("node"),
                        e.get("subtask"), e.get("worker"), e.get("t_us"))

    def clear_job(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)


recorder = EpochTraceRecorder()


# ------------------------------------------------------------ derived views


def _by_subtask(events: list[dict]) -> dict[tuple, dict[str, int]]:
    """(node, subtask) -> {event -> t_us} for per-subtask events."""
    out: dict[tuple, dict[str, int]] = {}
    for e in events:
        if e["node"] is None:
            continue
        out.setdefault((e["node"], e["subtask"]), {})[e["event"]] = e["t_us"]
    return out


def _job_event(events: list[dict], name: str, last: bool = False) -> Optional[int]:
    ts = [e["t_us"] for e in events if e["event"] == name]
    if not ts:
        return None
    return max(ts) if last else min(ts)


def phase_durations(events: list[dict]) -> dict[str, float]:
    """Job-level sequential phase decomposition of one epoch, in seconds:

        align     trigger            -> last subtask's snapshot_start
                  (waiting for barriers to traverse the graph and align)
        snapshot  last snapshot_start -> last ack (state writes)
        ack       last ack           -> metadata_durable (marker publish)
        commit    metadata_durable   -> last commit event (2PC phase 2)

    Phases whose boundary events are missing are omitted; the sum of the
    returned values is the trigger->commit wall time actually observed.
    """
    trigger = _job_event(events, "trigger")
    snap = _job_event(events, "snapshot_start", last=True)
    ack = _job_event(events, "ack", last=True)
    durable = _job_event(events, "metadata_durable")
    commit = max(filter(None, (
        _job_event(events, "commit_sent", last=True),
        _job_event(events, "commit_delivered", last=True))), default=None)
    out: dict[str, float] = {}
    for name, lo, hi in (("align", trigger, snap), ("snapshot", snap, ack),
                         ("ack", ack, durable), ("commit", durable, commit)):
        if lo is not None and hi is not None:
            out[name] = max(0.0, (hi - lo) / 1e6)
    return out


def dominant_phase(phases: dict[str, float]) -> Optional[str]:
    if not phases:
        return None
    return max(phases, key=lambda k: phases[k])


def chrome_trace(job_id: str, events_by_epoch: dict[int, list[dict]],
                 job_events: Optional[list[dict]] = None) -> dict:
    """Chrome trace-event JSON for one job's recorded epochs.

    Spans render one track per subtask (tid = "node/subtask") inside one
    process (pid = job): per subtask an "align" span (align_start ->
    snapshot_start) and a "snapshot" span (snapshot_start -> ack); at the
    job level an "epoch N" span (trigger -> metadata_durable) and a
    "commit" span (metadata_durable -> last commit event). A phase still
    open when the trace was taken (a wedged subtask) is emitted as a "B"
    begin-event with no matching end — trace viewers render it running to
    the end of the timeline, which is exactly the visual for "stuck".

    ``job_events`` (structured obs.events dicts): entries scoped to a
    rendered epoch are added as instant markers — an OPERATOR_PANIC or
    EPOCH_WEDGED lands on its subtask's (or the job's "events") track at
    the exact wall time, so one Perfetto view correlates the span tree
    with the event feed."""
    out: list[dict] = []

    def span(name: str, tid: str, t0: Optional[int], t1: Optional[int],
             epoch: int, **args) -> None:
        if t0 is None:
            return
        base = {"name": name, "cat": "checkpoint", "pid": job_id, "tid": tid,
                "ts": t0, "args": {"epoch": epoch, **args}}
        if t1 is None:
            out.append({**base, "ph": "B"})
        else:
            out.append({**base, "ph": "X", "dur": max(0, t1 - t0)})

    for epoch, events in sorted(events_by_epoch.items()):
        trigger = _job_event(events, "trigger")
        durable = _job_event(events, "metadata_durable")
        commit = max(filter(None, (
            _job_event(events, "commit_sent", last=True),
            _job_event(events, "commit_delivered", last=True))), default=None)
        span(f"epoch {epoch}", "epoch", trigger, durable, epoch)
        span("commit", "epoch", durable, commit, epoch)
        for (node, sub), ev in sorted(_by_subtask(events).items()):
            tid = f"{node}/{sub}"
            align0 = ev.get("align_start")
            snap0 = ev.get("snapshot_start")
            ack = ev.get("ack")
            span("align", tid, align0, snap0, epoch)
            span("snapshot", tid, snap0, ack, epoch)
            if align0 is None and snap0 is None and ack is not None:
                # source subtasks snapshot without alignment; give the ack a
                # point on the track so every participant is visible
                out.append({"name": "ack", "cat": "checkpoint", "ph": "i",
                            "pid": job_id, "tid": tid, "ts": ack, "s": "t",
                            "args": {"epoch": epoch}})
    rendered = set(events_by_epoch)
    for ev in job_events or ():
        if ev.get("epoch") is None or int(ev["epoch"]) not in rendered:
            continue
        tid = (f"{ev['node']}/{ev['subtask']}"
               if ev.get("node") is not None and ev.get("subtask") is not None
               else "events")
        out.append({
            "name": ev.get("code", "EVENT"), "cat": "events", "ph": "i",
            "pid": job_id, "tid": tid, "ts": int(ev["ts_us"]), "s": "p",
            "args": {"epoch": int(ev["epoch"]),
                     "level": ev.get("level"),
                     "message": ev.get("message", "")},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def timeline_report(job_id: str, epoch: int, events: list[dict],
                    expected: Optional[Iterable[tuple]] = None) -> str:
    """Human-readable epoch timeline plus a diagnosis naming the exact
    subtask that is holding the epoch: barriers that never arrived
    (``expected`` subtasks with no events at all) and snapshots that never
    acked. This is what the wedged-epoch watchdog and chaos-test failures
    attach, so a stuck checkpoint is self-diagnosing instead of a
    log-archaeology session."""
    if not events:
        return (f"epoch {epoch} of job {job_id}: no trace events recorded "
                "(trigger never reached the engine?)")
    t0 = events[0]["t_us"]
    lines = [f"epoch {epoch} trace ({job_id}):"]
    for e in events:
        who = ""
        if e["node"] is not None:
            who = f"  {e['node']}/{e['subtask']}"
        elif e["worker"] is not None:
            who = f"  worker {e['worker']}"
        lines.append(f"  +{(e['t_us'] - t0) / 1e3:9.1f}ms  {e['event']}{who}")
    by_sub = _by_subtask(events)
    # root causes first: a subtask that STARTED its snapshot (or alignment)
    # and never acked is holding the epoch; subtasks whose barrier never
    # arrived are usually its downstream victims
    stuck: list[str] = []
    for (node, sub), ev in sorted(by_sub.items()):
        if "ack" in ev:
            continue
        if "snapshot_start" in ev:
            stuck.append(f"{node}/{sub}: snapshot started, never acked")
        else:
            stuck.append(f"{node}/{sub}: aligning, barrier(s) still missing "
                         "on some input")
    victims = [f"{key[0]}/{key[1]}: barrier never arrived"
               for key in sorted(set(expected or ())) if key not in by_sub]
    if len(victims) > 6:
        victims = victims[:6] + [f"... and {len(victims) - 6} more"]
    stuck += victims
    if stuck:
        lines.append("  stuck: " + "; ".join(stuck))
    else:
        phases = phase_durations(events)
        if phases:
            dom = dominant_phase(phases)
            lines.append("  phases: " + "  ".join(
                f"{k}={v * 1e3:.1f}ms" + ("  <- dominant" if k == dom else "")
                for k, v in phases.items()))
    return "\n".join(lines)
