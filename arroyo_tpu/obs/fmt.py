"""Shared human-unit formatters for the terminal views (`top`, `explain`).

One place for rate/duration/byte rendering so the two views can't drift;
`per_sec`/`spaced` cover the stylistic difference between the dense `top`
table ("1.2k", "1.5KiB") and the annotated explain lines ("1.2k/s",
"1.5 KiB").
"""

from __future__ import annotations


def fmt_rate(v, per_sec: bool = False) -> str:
    if v is None:
        return "-"
    v = float(v)
    suffix = "/s" if per_sec else ""
    if v >= 1e6:
        return f"{v / 1e6:.2f}M{suffix}"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k{suffix}"
    return f"{v:.1f}{suffix}"


def fmt_secs(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    if v >= 3600:
        return f"{v / 3600:.1f}h"
    if v >= 60:
        return f"{v / 60:.1f}m"
    if v >= 1:
        return f"{v:.2f}s"
    return f"{v * 1e3:.1f}ms"


def fmt_bytes(v, spaced: bool = False) -> str:
    v = float(v)
    sep = " " if spaced else ""
    for unit in ("B", "KiB", "MiB", "GiB"):
        if v < 1024 or unit == "GiB":
            return (f"{v:.0f}{sep}B" if unit == "B"
                    else f"{v:.1f}{sep}{unit}")
        v /= 1024
    return f"{v:.1f}{sep}GiB"
