"""Runtime cost attribution: where CPU, state, and traffic actually live.

PR 6 made the control plane observable; this layer answers the three
questions it could not: which operator burns the time, which table holds
the state, which keys are hot. Three coordinated signal families, all
owned by the task run loop and exported through the existing
``TaskMetrics`` -> ``job_metrics`` -> controller-DB path:

  self-time     ``TaskProfiler.begin()/end(category)`` wraps every operator
                hook (process/tick/close/checkpoint) with wall
                (``time.perf_counter``) + thread-CPU (``time.thread_time``)
                accounting. busy% = total self wall / subtask uptime;
                cost-per-row = process self-time / rows received. Both are
                derived at EXPORT time — the hot path only accumulates two
                floats per hook call.
  state sizes   ``TaskProfiler.refresh()`` walks the subtask's TableManager
                (plus any live columnar stores the operator exposes via a
                ``state_sizes()`` hook — e.g. the updating join's
                _SideStore) into ``arroyo_state_rows``/``arroyo_state_bytes``
                gauges per table, throttled to ~1/s. Device-resident window
                state mirrors into host tables at barrier time, so those
                gauges read "as of the last checkpoint"; live host stores
                (join side stores) override with their current size.
  key skew      the per-subtask ``obs.sketch.KeySketch`` is fed from
                exactly ONE boundary per operator: the shuffle boundary
                (operators/collector.py keyed repartition) for operators
                that keyed-shuffle their output, else the keyed-insert
                boundary (the run loop, for input batches carrying
                ``_key``) — never both, so one sketch never mixes two hash
                spaces. Its summary checkpoints into a ``__sketch`` global
                table so a restored run rebuilds the exact summary the
                original would have had.

Everything here is attribution for the NEXT PRs: the spill backend reads
the state gauges, the skew-adaptive shuffle reads the hot-key summaries,
the autoscaler reads busy%. ``job_profile`` folds a merged metrics snapshot
into the compact per-job profile the controller persists (``job_profiles``
table) and the API serves at ``GET /api/v1/jobs/<id>/profile``;
``render_explain`` is the terminal EXPLAIN ANALYZE view behind
``python -m arroyo_tpu explain``.
"""

from __future__ import annotations

import itertools
import sys
import time
from typing import Optional

from ..config import config
from . import fmt
from .sketch import KeySketch, merge_topk

# global-keyed table the key-skew summary checkpoints into (one entry per
# subtask index; rides the normal TableManager snapshot/restore path)
SKETCH_TABLE = "__sketch"

# state-gauge refresh throttle: the walk is O(tables), cheap, but there is
# no reason to pay it per batch when consumers read at ~1 Hz
REFRESH_INTERVAL_S = 1.0


def late_rows_of(op) -> int:
    """Late/expired-row drops an operator has accumulated (window operators
    and joins track ``late_rows``; chains sum their members')."""
    return int(getattr(op, "late_rows", 0) or 0)


def _approx_dict_bytes(data: dict) -> int:
    """Approximate heap bytes of a global-keyed table: sample up to 64
    entries for an average entry size (deterministic: insertion order)."""
    n = len(data)
    if not n:
        return 0
    sample = list(itertools.islice(data.items(), 64))
    per = sum(sys.getsizeof(k) + sys.getsizeof(v) for k, v in sample)
    return int(per * n / len(sample))


class TaskProfiler:
    """Per-subtask cost-attribution hooks, owned by the task run loop.

    Single-writer like TaskMetrics (only the task thread calls these);
    ``begin``/``end`` are the only per-hook cost when profiling is on, and
    the overhead guard (tests/test_perf_guard.py) holds them under 5% wall
    on the smoke-scale pipelines.
    """

    __slots__ = ("metrics", "op", "table_manager", "_last_refresh",
                 "_source_cpu_mark")

    def __init__(self, metrics, op, table_manager):
        self.metrics = metrics
        self.op = op
        self.table_manager = table_manager
        self._last_refresh = 0.0
        self._source_cpu_mark: Optional[float] = None

    # ----------------------------------------------------------- self-time

    def begin(self) -> tuple:
        return (time.perf_counter(), time.thread_time())

    def end(self, category: str, t0: tuple) -> None:
        self.metrics.self_time[category] += time.perf_counter() - t0[0]
        self.metrics.self_cpu[category] += time.thread_time() - t0[1]

    def source_tick(self) -> None:
        """Incremental source attribution, called from the connector poll
        path (and once more when run() returns): accumulate the thread-CPU
        spent since the last tick so LIVE snapshots of a streaming source
        carry its busy% — waiting for run() to return would report 0 for
        the whole job. Source run loops block in poll waits, so wall
        self-time would read ~100% by construction; thread-CPU is the
        honest busy signal and is recorded as BOTH series."""
        now = time.thread_time()
        if self._source_cpu_mark is not None:
            d = now - self._source_cpu_mark
            self.metrics.self_time["process"] += d
            self.metrics.self_cpu["process"] += d
        self._source_cpu_mark = now

    def source_reset(self) -> None:
        """Re-stamp the source CPU mark after work attributed to another
        category (a checkpoint inside the source run loop), so the next
        source_tick does not double-count it into "process"."""
        self._source_cpu_mark = time.thread_time()

    # ------------------------------------------------------------ key skew

    def observe_keys(self, keys) -> None:
        sk = self.metrics.sketch
        if sk is not None:
            sk.observe(keys)

    def checkpoint_sketch(self) -> None:
        """Persist the sketch summary into the ``__sketch`` global table
        (called just before the TableManager snapshot)."""
        sk = self.metrics.sketch
        if sk is not None and sk.total:
            self.table_manager.global_keyed(SKETCH_TABLE).insert(
                self.metrics.subtask, sk.state())

    # --------------------------------------------------------- state sizes

    def refresh(self, force: bool = False) -> None:
        """Refresh late-row counter + per-table state gauges (throttled)."""
        now = time.monotonic()
        if not force and now - self._last_refresh < REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        m = self.metrics
        m.late_rows = late_rows_of(self.op)
        rows: dict[str, int] = {}
        nbytes: dict[str, int] = {}
        tm = self.table_manager
        for name, tbl in tm.globals.items():
            if name == SKETCH_TABLE:
                continue  # profiler bookkeeping, not operator state
            rows[name] = len(tbl.data)
            nbytes[name] = _approx_dict_bytes(tbl.data)
        for name, tbl in tm.expiring.items():
            rows[name] = tbl.total_rows()
            nbytes[name] = sum(b.nbytes() for b in tbl.batches)
        sizes = getattr(self.op, "state_sizes", None)
        if sizes is not None:
            # live columnar stores (e.g. the updating join's _SideStore)
            # override the table-manager view — between barriers the host
            # tables lag the operator's resident state
            for name, (r, by) in sizes().items():
                rows[name] = int(r)
                nbytes[name] = int(by)
        m.state_rows = rows
        m.state_bytes = nbytes
        spill = getattr(self.op, "spill_stats", None)
        if spill is not None:
            # tiered state (state/spill.py): spilled bytes, hot/cold
            # partition split, and probe-pruning histogram -> arroyo_spill_*
            m.spill = spill()
        mesh = getattr(self.op, "mesh_stats", None)
        if mesh is not None:
            # sharded mesh execution (parallel/sharded_agg.py): exchange
            # throughput + spill-buffer residency -> arroyo_mesh_*
            m.mesh = mesh()


def make_profiler(metrics, task_info, table_manager, op) -> Optional[TaskProfiler]:
    """Build the task's profiler + sketch per ``profile.*`` config; returns
    None when profiling is disabled (the run loop then has zero added work).
    Restores the sketch from the checkpointed ``__sketch`` table — ONLY this
    subtask's own entry: global tables replicate every subtask's entry on
    restore, and merging them all would multiply the operator-level merge by
    the parallelism. A rescale therefore restarts the sketch from empty
    (it is a rolling traffic estimate, not exact state)."""
    c = config()
    if not c.get("profile.enabled", True):
        metrics.sketch = None
        return None
    sk = KeySketch(
        capacity=c.get("profile.sketch.capacity", 64),
        sample_every=c.get("profile.sketch.sample-every", 1),
        seed=task_info.subtask_index,
    )
    persisted = table_manager.globals.get(SKETCH_TABLE)
    if persisted is not None:
        sk.merge_state(persisted.get(task_info.subtask_index))
    metrics.sketch = sk
    return TaskProfiler(metrics, op, table_manager)


# ------------------------------------------------------------ job profile


def job_profile(metrics: Optional[dict]) -> dict:
    """Fold a merged per-operator metrics snapshot (metrics.job_metrics /
    merge_job_metrics output) into the compact per-job profile the
    controller persists and ``/profile`` serves. Pure selection/derivation —
    every number already exists in the snapshot."""
    out: dict[str, dict] = {}
    for op, m in (metrics or {}).items():
        if not isinstance(m, dict):
            continue
        per = {
            s: {k: d.get(k) for k in ("busy_pct", "self_time", "late_rows")
                if d.get(k) is not None}
            for s, d in (m.get("per_subtask") or {}).items()
            if isinstance(d, dict)
        }
        out[op] = {
            "subtasks": m.get("subtasks", len(per) or 1),
            "rows_in_per_sec": m.get("messages_recv_per_sec", 0.0),
            "rows_out_per_sec": m.get("messages_per_sec", 0.0),
            "busy_pct": m.get("busy_pct"),
            "self_time": m.get("self_time") or {},
            "self_cpu": m.get("self_cpu") or {},
            "self_us_per_row": m.get("self_us_per_row"),
            "late_rows": int(m.get("late_rows") or 0),
            "state_rows": m.get("state_rows") or {},
            "state_bytes": m.get("state_bytes") or {},
            "hot_keys": m.get("hot_keys") or [],
            "per_subtask": per,
        }
        if m.get("segment_compiled"):
            out[op]["segment_compiled"] = True
        if m.get("segment_mesh"):
            out[op]["segment_mesh"] = True
        if m.get("segment_reason"):
            out[op]["segment_reason"] = m["segment_reason"]
        if m.get("mesh"):
            out[op]["mesh"] = m["mesh"]
    return out


def aggregate_profiles(per_subtask: dict[str, dict]) -> dict:
    """Fold per-subtask profile fields into one operator row: self-time and
    counters sum, busy% takes the worst subtask, hot-key summaries merge via
    the space-saving union. Used by metrics._op_aggregate so a multi-worker
    set's union-by-subtask snapshot aggregates exactly like a local one."""
    self_time: dict[str, float] = {}
    self_cpu: dict[str, float] = {}
    state_rows: dict[str, int] = {}
    state_bytes: dict[str, int] = {}
    late = 0
    busy = None
    topks, sketch_total = [], 0
    for s in per_subtask.values():
        for cat, v in (s.get("self_time") or {}).items():
            self_time[cat] = self_time.get(cat, 0.0) + float(v)
        for cat, v in (s.get("self_cpu") or {}).items():
            self_cpu[cat] = self_cpu.get(cat, 0.0) + float(v)
        for t, v in (s.get("state_rows") or {}).items():
            state_rows[t] = state_rows.get(t, 0) + int(v)
        for t, v in (s.get("state_bytes") or {}).items():
            state_bytes[t] = state_bytes.get(t, 0) + int(v)
        late += int(s.get("late_rows") or 0)
        b = s.get("busy_pct")
        if b is not None and (busy is None or b > busy):
            busy = b
        hot = s.get("hot_keys")
        if hot:
            topks.append(hot)
            sketch_total += int(s.get("sketch_total") or 0)
    out: dict = {}
    if self_time:
        out["self_time"] = {c: round(v, 6) for c, v in self_time.items()}
        out["self_cpu"] = {c: round(v, 6) for c, v in self_cpu.items()}
    if busy is not None:
        out["busy_pct"] = busy
    out["late_rows"] = late
    if state_rows:
        out["state_rows"] = state_rows
        out["state_bytes"] = state_bytes
    if topks:
        out["hot_keys"] = merge_topk(topks, sketch_total)
        out["sketch_total"] = sketch_total
    return out


# --------------------------------------------------------- EXPLAIN ANALYZE


def _fmt_rate(v) -> str:
    return fmt.fmt_rate(v, per_sec=True)


def _fmt_bytes(v) -> str:
    return fmt.fmt_bytes(v, spaced=True)


def _annotations(prof: dict) -> list[str]:
    """The per-operator annotation lines under a plan node."""
    lines = []
    head = (f"busy {prof['busy_pct']:.1f}%" if prof.get("busy_pct") is not None
            else "busy -")
    if prof.get("segment_compiled"):
        # whole-segment compilation: this row's self-time is ONE jitted
        # dispatch covering every chained member, not a per-member sum
        head = "[compiled] " + head
        if prof.get("segment_mesh"):
            # fused mesh execution: that one dispatch is a shard_map'd
            # program covering the keyed exchange + state update too
            head = "[mesh] " + head
    elif prof.get("segment_reason"):
        # the plan-time reject or runtime fallback reason: the segment is
        # interpreted, and this line says why (AR009 / SEGMENT_FALLBACK)
        head = f"[not compiled: {prof['segment_reason']}] " + head
    head += (f"   in {_fmt_rate(prof.get('rows_in_per_sec'))}"
             f"   out {_fmt_rate(prof.get('rows_out_per_sec'))}")
    st = prof.get("self_time") or {}
    busy_cats = "  ".join(f"{c} {v:.2f}s" for c, v in
                          sorted(st.items(), key=lambda kv: -kv[1]) if v)
    if busy_cats:
        head += f"   self: {busy_cats}"
    if prof.get("self_us_per_row") is not None:
        head += f"   {prof['self_us_per_row']:.2f}us/row"
    lines.append(head)
    rows = prof.get("state_rows") or {}
    if rows:
        parts = "  ".join(
            f"{t} {rows[t]:,} rows/{_fmt_bytes((prof.get('state_bytes') or {}).get(t, 0))}"
            for t in sorted(rows))
        lines.append(f"state: {parts}")
    if prof.get("late_rows"):
        lines.append(f"late rows dropped: {prof['late_rows']:,}")
    hot = prof.get("hot_keys") or []
    if hot:
        parts = "  ".join(
            f"{e['key'][:6]}..{e['key'][-4:]} {100 * e.get('share', 0):.1f}%"
            for e in hot[:5])
        lines.append(f"hot keys: {parts}")
    return lines


def render_explain(nodes: list[dict], edges: list[dict], profile: dict,
                   job: Optional[dict] = None) -> str:
    """EXPLAIN ANALYZE over the logical plan: the dataflow DAG rendered
    sink-first (each ``->`` line is one operator, inputs nested beneath it),
    annotated with the live profile — the reference's
    pipeline-graph-with-metrics UI view, in the terminal.

    ``nodes``: [{id, op, description?, parallelism}], ``edges``:
    [{src, dst}] (the /pipelines/<id>/graph shape); ``profile``: the
    ``job_profile`` dict keyed by operator/node id."""
    lines: list[str] = []
    if job is not None:
        lines.append(
            f"EXPLAIN ANALYZE job {job.get('id', '?')}  "
            f"state={job.get('state', '?')}  "
            f"workers={job.get('n_workers', 1)}  "
            f"epoch={job.get('checkpoint_epoch', 0)}  "
            f"restarts={job.get('restarts', 0)}")
    by_id = {n["id"]: n for n in nodes}
    inputs: dict[str, list[str]] = {n["id"]: [] for n in nodes}
    has_out: set[str] = set()
    for e in edges:
        inputs.setdefault(e["dst"], []).append(e["src"])
        has_out.add(e["src"])
    sinks = [nid for nid in by_id if nid not in has_out] or list(by_id)
    seen: set[str] = set()

    def emit(nid: str, depth: int) -> None:
        pad = "   " * depth
        n = by_id.get(nid, {"id": nid, "op": "?", "parallelism": "?"})
        desc = n.get("description") or n.get("op", "")
        label = f"{pad}-> {nid} [{desc} x{n.get('parallelism', '?')}]"
        if nid in seen:
            lines.append(label + "  (shown above)")
            return
        seen.add(nid)
        lines.append(label)
        prof = profile.get(nid)
        if prof:
            for a in _annotations(prof):
                lines.append(f"{pad}     {a}")
        elif n.get("not_compilable"):
            # no runtime profile yet: the plan-time verdict still explains
            # why this chained run will never compile
            lines.append(f"{pad}     [{n['not_compilable']}]")
        for src in inputs.get(nid, []):
            emit(src, depth + 1)

    for s in sinks:
        emit(s, 0)
    # operators in the profile but not the plan (e.g. a plan re-derived with
    # different chaining than the run used) still deserve their numbers
    for op in sorted(set(profile) - seen):
        lines.append(f"-> {op} [not in plan]")
        for a in _annotations(profile[op]):
            lines.append(f"     {a}")
    return "\n".join(lines)
