"""Controller-side health monitors: the autoscaler's sensor layer.

ROADMAP item 5 (elastic autoscaling) needs a layer that *watches*
per-operator backpressure, queue-transit p99, and watermark lag and
*decides* — this module is that watch/decide half. Each controller
supervision tick evaluates a small rule set over the merged per-operator
metrics snapshot the controller already holds (``merge_job_metrics``
output — the same dict behind ``top`` and ``/metrics``), with hysteresis:
a rule FIRES only after ``health.fire-ticks`` consecutive breaching
evaluations and CLEARS only after ``health.clear-ticks`` consecutive
healthy ones, so a metric oscillating around its threshold cannot flap
the job state (or spam transition events).

The job's health is the worst firing rule's severity: ``ok`` ->
``degraded`` -> ``critical``. Transitions emit WARN/ERROR job events
(HEALTH_DEGRADED / HEALTH_CRITICAL / HEALTH_OK); the state surfaces as
the ``arroyo_job_health`` gauge, a ``health`` field on the jobs API, a
header entry in ``top``, and per-rule detail at
``GET /api/v1/jobs/<id>/health``. The future autoscaler only has to add
the actuator: read ``firing`` rules, pick a new worker count.

Thresholds live under ``health.*`` in the config; a rule whose metric is
absent from the snapshot (e.g. no sink latency before the first batch)
evaluates as healthy rather than unknown-degraded.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

STATES = ("ok", "degraded", "critical")
_STATE_RANK = {s: i for i, s in enumerate(STATES)}


def _worst(metrics: dict, key: str) -> Optional[float]:
    """Max of a per-operator field over the merged snapshot (the worst
    operator is the one the job's health hinges on)."""
    vals = [m.get(key) for m in (metrics or {}).values()
            if isinstance(m, dict) and m.get(key) is not None]
    return max(vals) if vals else None


@dataclass(frozen=True)
class Rule:
    """One health rule: extract an observed value from the evaluation
    context, compare against its configured threshold."""

    rule_id: str
    severity: str  # "degraded" | "critical"
    config_key: str  # threshold under health.*
    default: float
    description: str
    observe: Callable[[dict], Optional[float]]

    def threshold(self) -> float:
        from ..config import config

        v = config().get(f"health.{self.config_key}")
        return float(v) if v is not None else self.default


def _observe_memory_pressure(ctx: dict) -> Optional[float]:
    """Worst subtask's resident state bytes as a fraction of the
    per-subtask spill budget (``state.spill.budget-bytes``). Fed from the
    same ``arroyo_state_bytes`` accounting the spill layer enforces its
    budget against: a sustained breach means spilling is disabled,
    failing (SPILL_FALLBACK), or falling behind the ingest rate."""
    from ..config import config

    budget = config().get("state.spill.budget-bytes")
    if not budget:
        return None
    worst = None
    for m in (ctx.get("metrics") or {}).values():
        if not isinstance(m, dict):
            continue
        for s in (m.get("per_subtask") or {}).values():
            sb = (s or {}).get("state_bytes") or {}
            if not sb:
                continue
            v = sum(sb.values()) / float(budget)
            if worst is None or v > worst:
                worst = v
    return worst


RULES: tuple[Rule, ...] = (
    Rule("watermark-lag", "degraded", "watermark-lag-max-s", 900.0,
         "worst-operator watermark lag (event time falling behind)",
         lambda ctx: _worst(ctx.get("metrics") or {}, "watermark_lag_seconds")),
    Rule("backpressure", "degraded", "backpressure-max", 0.9,
         "sustained worst-operator backpressure (a queue near its budget)",
         lambda ctx: _worst(ctx.get("metrics") or {}, "backpressure")),
    Rule("queue-transit", "degraded", "queue-transit-p99-max-ms", 1000.0,
         "worst-operator inbox transit p99 over budget",
         lambda ctx: _worst(ctx.get("metrics") or {}, "queue_transit_p99_ms")),
    Rule("sink-latency", "degraded", "sink-latency-p99-max-s", 600.0,
         "sink end-to-end event latency p99 over budget",
         lambda ctx: _worst(ctx.get("metrics") or {},
                            "sink_event_latency_p99_s")),
    Rule("checkpoint-failures", "critical", "checkpoint-failure-streak", 2.0,
         "consecutive failed/wedged checkpoint epochs",
         lambda ctx: float(ctx.get("ckpt_failures") or 0)),
    Rule("memory-pressure", "degraded", "memory-pressure-max", 0.9,
         "worst subtask's resident state vs the per-subtask spill budget",
         _observe_memory_pressure),
)


@dataclass
class _RuleState:
    breach_ticks: int = 0
    healthy_ticks: int = 0
    firing: bool = False
    value: Optional[float] = None


class HealthMonitor:
    """Per-job hysteresis evaluator. ``on_transition(old, new, detail)``
    is called exactly once per state change (the controller records the
    HEALTH_* event and persists the new state there)."""

    def __init__(self, job_id: str,
                 on_transition: Optional[Callable[[str, str, dict], None]] = None):
        self.job_id = job_id
        self.on_transition = on_transition
        self.state = "ok"
        self._rules: dict[str, _RuleState] = {r.rule_id: _RuleState()
                                              for r in RULES}

    def evaluate(self, metrics: Optional[dict],
                 ckpt_failures: int = 0) -> dict:
        """One supervision-tick evaluation; returns the detail dict that
        /health serves (state + per-rule observed/threshold/firing)."""
        from ..config import config

        cfg = config()
        fire_n = max(1, int(cfg.get("health.fire-ticks", 3) or 3))
        clear_m = max(1, int(cfg.get("health.clear-ticks", 5) or 5))
        ctx = {"metrics": metrics, "ckpt_failures": ckpt_failures}
        worst = "ok"
        rules_detail = []
        for rule in RULES:
            st = self._rules[rule.rule_id]
            value = rule.observe(ctx)
            threshold = rule.threshold()
            breaching = value is not None and value >= threshold
            st.value = value
            if breaching:
                st.breach_ticks += 1
                st.healthy_ticks = 0
                if st.breach_ticks >= fire_n:
                    st.firing = True
            else:
                st.healthy_ticks += 1
                st.breach_ticks = 0
                if st.firing and st.healthy_ticks >= clear_m:
                    st.firing = False
            if st.firing:
                worst = max(worst, rule.severity, key=_STATE_RANK.__getitem__)
            rules_detail.append({
                "rule": rule.rule_id,
                "severity": rule.severity,
                "description": rule.description,
                "value": value,
                "threshold": threshold,
                "breaching": breaching,
                "firing": st.firing,
            })
        detail = {"state": worst, "rules": rules_detail}
        if worst != self.state:
            old, self.state = self.state, worst
            if self.on_transition is not None:
                self.on_transition(old, worst, detail)
        return detail

    def firing_rules(self) -> list[str]:
        return [rid for rid, st in self._rules.items() if st.firing]


def health_event_code(state: str) -> str:
    return {"ok": "HEALTH_OK", "degraded": "HEALTH_DEGRADED",
            "critical": "HEALTH_CRITICAL"}[state]


def health_value(state: str) -> int:
    """Numeric encoding for the ``arroyo_job_health`` gauge."""
    return _STATE_RANK.get(state, 0)
