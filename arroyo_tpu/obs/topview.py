"""Controller-side live job view: the rendering behind `arroyo_tpu top`.

Pure formatting over data the controller already persists to the shared DB
(job row, per-operator metrics snapshot, checkpoint history with phase
durations) so the CLI, tests, and any future UI panel share one view model:
per-operator rows/s in/out, backpressure, queue-transit p99, watermark lag,
and the last epoch's duration with its dominant phase — a hot subtask or a
stalled watermark is visible at a glance.
"""

from __future__ import annotations

import json
from typing import Optional

from . import trace
from .fmt import fmt_bytes as _fmt_bytes
from .fmt import fmt_rate as _fmt_rate
from .fmt import fmt_secs as _fmt_secs


def last_epoch_line(checkpoints: list[dict]) -> Optional[str]:
    """"last epoch 7: 1.23s (snapshot 0.91s <- dominant, align 0.21s, ...)"
    from the newest checkpoint row carrying phase durations."""
    for row in sorted(checkpoints, key=lambda r: -int(r["epoch"])):
        if row.get("state") not in ("complete", "compacted"):
            continue
        phases = row.get("phases")
        if isinstance(phases, str):
            try:
                phases = json.loads(phases)
            except json.JSONDecodeError:
                phases = None
        if not phases:
            continue
        total = sum(phases.values())
        dom = trace.dominant_phase(phases)
        parts = ", ".join(
            f"{k} {_fmt_secs(v)}" + (" <- dominant" if k == dom else "")
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1])
        )
        return f"last epoch {row['epoch']}: {_fmt_secs(total)} ({parts})"
    return None


_COLUMNS = ("operator", "sub", "in/s", "out/s", "busy%", "backpr",
            "transit p99", "wm lag", "sink p99", "state", "late", "hot key")


def render(job: dict, metrics: Optional[dict],
           checkpoints: Optional[list[dict]] = None) -> str:
    """One refresh frame of the live job view (plain text, one table)."""
    head = (f"job {job['id']}  state={job['state']}  "
            f"health={job.get('health') or 'ok'}  "
            f"workers={job.get('n_workers', 1)}  "
            f"restarts={job.get('restarts', 0)}  "
            f"epoch={job.get('checkpoint_epoch', 0)}")
    tenant = job.get("tenant")
    if tenant and tenant != "default":
        head += f"  tenant={tenant}"
    if job.get("state") == "Queued":
        # multi-tenant fleet: the job waits in its tenant's admission
        # queue; the position comes from the persisted fleet snapshot
        pos = job.get("queue_position")
        head += ("  queue_pos=" + (str(pos) if pos else "?"))
        return head + "\n  (queued for fleet admission; no worker set yet)"
    if job.get("state") == "Evolving":
        # live evolution: the v1 set drains behind a final checkpoint;
        # the evolved plan restores from it once the carry-over is proven
        head += "  evolving" + (" (redeploy pending)"
                                if job.get("desired_query") else "")
    if not metrics:
        return head + "\n  (no metrics snapshot yet)"
    rows: list[tuple[str, ...]] = []

    def not_compiled(m: dict) -> str:
        # the stored reason may carry the plan-reject boilerplate prefix;
        # strip it so the truncated cell keeps the actionable part
        reason = m["segment_reason"]
        if reason.startswith("not compilable: "):
            reason = reason[len("not compilable: "):]
        return f" [not compiled: {reason[:48]}]"

    for op in sorted(metrics):
        m = metrics[op]
        if not isinstance(m, dict):
            continue
        p99 = m.get("queue_transit_p99_ms")
        busy = m.get("busy_pct")
        srows = m.get("state_rows") or {}
        sbytes = m.get("state_bytes") or {}
        state = ("-" if not srows else
                 f"{sum(srows.values()):,}r/"
                 f"{_fmt_bytes(sum(sbytes.values()))}")
        hot = (m.get("hot_keys") or [{}])[0]
        hot_s = (f"{hot['key'][:6]}.. {100 * hot.get('share', 0):.0f}%"
                 if hot.get("key") else "-")
        rows.append((
            # whole-segment compilation: this chained operator's batches run
            # as one jitted dispatch (its busy% is not a per-member sum);
            # an uncompiled segment names its plan-time reject or runtime
            # fallback reason instead (truncated to keep the table narrow)
            # [mesh] = the dispatch is one shard_map'd program fusing the
            # segment with the sharded aggregate's keyed exchange
            op + ((" [mesh]" if m.get("segment_mesh") else "")
                  + " [compiled]" if m.get("segment_compiled")
                  else not_compiled(m)
                  if m.get("segment_reason") else ""),
            str(m.get("subtasks", len(m.get("per_subtask", {})) or 1)),
            _fmt_rate(m.get("messages_recv_per_sec")),
            _fmt_rate(m.get("messages_per_sec")),
            "-" if busy is None else f"{float(busy):.1f}",
            f"{float(m.get('backpressure', 0.0)):.2f}",
            "-" if p99 is None else f"{float(p99):.1f}ms",
            _fmt_secs(m.get("watermark_lag_seconds")),
            _fmt_secs(m.get("sink_event_latency_p99_s")),
            state,
            str(int(m.get("late_rows") or 0)),
            hot_s,
        ))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(_COLUMNS)]
    lines = [head, "  ".join(c.ljust(w) for c, w in zip(_COLUMNS, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    epoch_line = last_epoch_line(checkpoints or [])
    if epoch_line:
        lines.append(epoch_line)
    return "\n".join(lines)
