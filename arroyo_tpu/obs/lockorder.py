"""Runtime lock-order witness: the dynamic half of the LR402 audit.

The static concurrency auditor (analysis/concurrency_audit.py) builds an
acquires-while-holding graph over ``Class.attr`` lock nodes and flags
cycles. A static model is only as good as its ground truth, so this
module provides the FastTrack-style witness: production locks created
through :func:`make_lock` record, per thread, which named locks were
already held at every acquire, and the resulting edge set is compared to
the static graph by the test suite — an observed edge missing from the
static graph means the model (or the code) is wrong.

Design constraints:

- **Zero overhead when off.** ``make_lock`` returns a plain
  ``threading.Lock``/``RLock``/``Condition`` unless the witness is
  enabled (or a fault plan targets ``lock_contend``) at construction
  time, so steady-state code pays nothing — no ``settrace``, no proxy.
- **Witness mode.** Under :func:`enable`, locks constructed afterwards
  are tracked proxies: each acquire records (held -> acquired) edges
  against a thread-local held stack. Reentrant re-acquires of the same
  named lock record no edge (RLock semantics are not an ordering fact).
- **Chaos hook.** Every tracked acquire fires the ``lock_contend`` fault
  site with ``key=<name>`` *after* taking the inner lock, so a plan like
  ``lock_contend:delay=25@match=FleetManager`` widens the critical
  section of every FleetManager lock — turning a statically-suspected
  race window into a schedulable one.

Names follow the static graph's node grammar exactly: ``Class.attr``
(e.g. ``"FleetManager._lock"``), so the cross-check needs no mapping.
``Condition`` objects share their underlying tracked lock via the
``lock=`` kwarg and therefore alias to its node, matching the static
Condition-aliasing rule.
"""

from __future__ import annotations

import threading
from typing import Optional

_enabled = False
_edges: set = set()  # (held_name, acquired_name)
_edges_lock = threading.Lock()
_tls = threading.local()


def enable(reset: bool = True) -> None:
    """Track locks created from now on; optionally clear recorded edges."""
    global _enabled
    if reset:
        with _edges_lock:
            _edges.clear()
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def edges() -> set:
    """Snapshot of observed (held, acquired) edges."""
    with _edges_lock:
        return set(_edges)


def reset() -> None:
    with _edges_lock:
        _edges.clear()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class _TrackedLock:
    """Proxy over a threading lock that records acquire-order edges and
    fires the ``lock_contend`` fault site inside the critical section.
    Duck-typed to the Lock protocol so ``threading.Condition`` can wrap
    it (wait/notify go through acquire/release on this object)."""

    __slots__ = ("_name", "_inner")

    def __init__(self, name: str, inner) -> None:
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        stack = _held_stack()
        if _enabled and self._name not in stack:
            new = [(h, self._name) for h in stack if h != self._name]
            if new:
                with _edges_lock:
                    _edges.update(new)
        stack.append(self._name)
        from ..faults import fault_point

        fault_point("lock_contend", key=self._name)
        return True

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self._name:
                del stack[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def _should_track() -> bool:
    if _enabled:
        return True
    # a fault plan targeting lock_contend needs instrumented critical
    # sections even without the witness (chaos runs install the plan
    # before building the pipeline, i.e. before locks are constructed)
    from ..faults import active

    inj = active()
    return inj is not None and any(
        getattr(s, "site", None) == "lock_contend"
        for s in getattr(inj, "specs", ()))


def make_lock(name: str, kind: str = "lock", lock=None):
    """Construct a (possibly tracked) lock named after its static graph
    node. ``kind`` is ``"lock"`` | ``"rlock"`` | ``"cond"``; for a
    condition, pass the owning lock via ``lock=`` to share (and alias to)
    it, matching ``threading.Condition(self._lock)``."""
    if kind == "cond":
        return threading.Condition(
            lock if lock is not None else make_lock(name))
    inner = threading.RLock() if kind == "rlock" else threading.Lock()
    if _should_track():
        return _TrackedLock(name, inner)
    return inner
