"""Key-skew sketches: deterministic space-saving top-k over routing hashes.

Skew-adaptive shuffle (ROADMAP item 3, PanJoin arXiv:1811.05065) and the
spill backend both need to know WHICH keys are hot before they can act; the
per-operator emit/queue histograms only say that *something* is hot. This
module is the detection layer: a space-saving heavy-hitter summary fed at
the shuffle/key boundaries (ShuffleCollector key hashing, keyed window/join
inserts via the task run loop), cheap enough to leave on in production.

Design constraints, in order:

  deterministic   replay after checkpoint restore must rebuild the same
                  summary — no randomness anywhere. Batch sampling uses a
                  counter whose phase is seeded from the subtask index
                  (decorrelates subtasks) and is part of the checkpointed
                  state, so a restored run resumes the exact sampling
                  cadence the original would have had. At the default
                  ``sample_every=1`` every row is counted exactly once, so
                  the summary is row-deterministic no matter how the
                  coalescing layer re-draws batch boundaries; sampling >1
                  is cheaper but boundary-sensitive (time-based coalesce
                  flushes can shift WHICH batches land on the sampled
                  phase), so it trades exact replay equality for cost.
  cheap           one np.unique per SAMPLED batch (1/``sample_every``),
                  dict updates over the batch's unique keys only. Skipped
                  batches cost one integer increment.
  mergeable       rescale restore can hand one subtask several prior
                  subtasks' summaries; ``merge_state`` implements the
                  standard space-saving merge (absent keys are compensated
                  with the other summary's eviction threshold), so the
                  union never under-counts a heavy hitter.

Counts are over the 64-bit routing hash (``_key``), not the user key value:
that is what exists at every shuffle boundary, and it is enough to detect
and act on skew (split/replicate by hash). ``error`` per entry is the
standard space-saving overestimate bound — ``count - error`` is a
guaranteed lower bound on the key's true traffic.
"""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np


class KeySketch:
    """Space-saving top-k summary of uint64 routing-hash traffic."""

    __slots__ = ("capacity", "sample_every", "counts", "errors", "threshold",
                 "total", "_tick")

    def __init__(self, capacity: int = 64, sample_every: int = 1,
                 seed: int = 0):
        self.capacity = max(1, int(capacity))
        self.sample_every = max(1, int(sample_every))
        self.counts: dict[int, int] = {}   # key hash -> estimated count
        self.errors: dict[int, int] = {}   # key hash -> overestimate bound
        # max count ever evicted: an absent key may have accumulated up to
        # this much traffic before eviction, so re-entries start from here
        self.threshold = 0
        self.total = 0  # rows represented (sampled rows x sample_every)
        # deterministic sampling phase; the seed (subtask index) decorrelates
        # which batches different subtasks sample without randomness (LR103)
        self._tick = int(seed) % self.sample_every

    # ------------------------------------------------------------------ feed

    def observe(self, keys: np.ndarray) -> None:
        """Count one batch's routing keys (1/sample_every batches counted;
        the rest cost a single increment)."""
        self._tick += 1
        if self._tick % self.sample_every:
            return
        n = len(keys)
        if n == 0:
            return
        scale = self.sample_every
        self.total += n * scale
        u, c = np.unique(np.asarray(keys, dtype=np.uint64), return_counts=True)
        counts = self.counts
        errors = self.errors
        thr = self.threshold
        for k, add in zip(u.tolist(), c.tolist()):
            add *= scale
            cur = counts.get(k)
            if cur is not None:
                counts[k] = cur + add
            else:
                # space-saving entry: a new key inherits the eviction
                # threshold as both starting mass and error bound
                counts[k] = add + thr
                if thr:
                    errors[k] = thr
        self._evict()

    def _evict(self) -> None:
        over = len(self.counts) - self.capacity
        if over <= 0:
            return
        # deterministic order: evict the smallest counts, ties by key asc.
        # nsmallest keeps a mostly-unique batch (counts grown to U entries)
        # at O(U log over) instead of a full O(U log U) sort per batch
        for k, v in heapq.nsmallest(over, self.counts.items(),
                                    key=lambda kv: (kv[1], kv[0])):
            if v > self.threshold:
                self.threshold = v
            del self.counts[k]
            self.errors.pop(k, None)

    # ----------------------------------------------------------------- views

    def topk(self, k: int = 8) -> list[dict]:
        """[{key, count, error, share}] by count desc (ties key asc);
        ``share`` is count/total traffic, ``count - error`` a guaranteed
        lower bound on the key's true rows."""
        order = sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))
        total = self.total or 1
        return [
            {"key": key, "count": cnt, "error": self.errors.get(key, 0),
             "share": round(cnt / total, 4)}
            for key, cnt in order[:k]
        ]

    # ------------------------------------------------------ checkpoint state

    def state(self) -> dict:
        """Plain-python snapshot for the checkpointed ``__sketch`` table."""
        return {
            "counts": dict(self.counts),
            "errors": dict(self.errors),
            "threshold": self.threshold,
            "total": self.total,
            "tick": self._tick,
            "sample_every": self.sample_every,
        }

    def merge_state(self, state: Optional[dict]) -> None:
        """Fold a persisted summary in (restore; rescale may fold several).
        Space-saving merge: keys absent from one side are compensated with
        that side's threshold, so the union never under-counts."""
        if not state:
            return
        other_counts = {int(k): int(v) for k, v in state.get("counts", {}).items()}
        other_errors = {int(k): int(v) for k, v in state.get("errors", {}).items()}
        other_thr = int(state.get("threshold", 0))
        mine = self.counts
        merged_fresh = not mine and not self.total
        for k, v in other_counts.items():
            if k in mine:
                mine[k] += v
                if other_errors.get(k) or self.errors.get(k):
                    self.errors[k] = self.errors.get(k, 0) + other_errors.get(k, 0)
            else:
                mine[k] = v + self.threshold
                err = other_errors.get(k, 0) + self.threshold
                if err:
                    self.errors[k] = err
        if other_thr:
            # keys the other summary evicted may include any of ours: every
            # key absent from it gets its threshold as compensation too
            for k in mine:
                if k not in other_counts:
                    mine[k] += other_thr
                    self.errors[k] = self.errors.get(k, 0) + other_thr
        self.threshold += other_thr
        self.total += int(state.get("total", 0))
        if merged_fresh:
            # restoring our own prior state: resume the exact sampling phase
            self._tick = int(state.get("tick", self._tick))
        self._evict()


def merge_topk(topks, total: int, k: int = 8) -> list[dict]:
    """Merge exported per-subtask top-k lists ([{key, count, error, share}],
    keys already hex-encoded by the metrics export) into one per-operator
    list. Counts for a key absent from some subtask's list are lower bounds
    (that subtask's below-top-k mass is not exported), which is the safe
    direction for skew detection: a key this merge calls hot IS hot.
    ``total`` is the summed per-subtask traffic, for the merged share."""
    counts: dict[str, int] = {}
    errors: dict[str, int] = {}
    for lst in topks:
        for e in lst or ():
            key = e["key"]
            counts[key] = counts.get(key, 0) + int(e["count"])
            err = int(e.get("error", 0))
            if err:
                errors[key] = errors.get(key, 0) + err
    # fixed-width hex sorts lexically == numerically: deterministic ties
    order = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    t = total or 1
    return [{"key": key, "count": c, "error": errors.get(key, 0),
             "share": round(c / t, 4)}
            for key, c in order[:k]]
