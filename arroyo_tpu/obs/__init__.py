"""Observability plane: epoch-lifecycle tracing + the live job view.

``obs.trace`` records every checkpoint epoch's span tree (trigger ->
per-subtask alignment -> snapshot -> ack -> metadata durable -> commit
fan-out) into a bounded in-memory ring and exports it as Chrome trace-event
JSON; ``obs.topview`` renders the controller-DB-backed per-operator table
behind ``python -m arroyo_tpu top``. The watermark-lag gauge, sink
end-to-end latency, and checkpoint phase histograms live in
``arroyo_tpu.metrics`` next to the existing task counters.
"""

from .trace import (  # noqa: F401 - public API
    EpochTraceRecorder,
    chrome_trace,
    dominant_phase,
    phase_durations,
    recorder,
    timeline_report,
)
