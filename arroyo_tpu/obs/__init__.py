"""Observability plane: tracing, cost attribution, and the live job views.

``obs.trace`` records every checkpoint epoch's span tree (trigger ->
per-subtask alignment -> snapshot -> ack -> metadata durable -> commit
fan-out) into a bounded in-memory ring and exports it as Chrome trace-event
JSON; ``obs.profile`` is the runtime cost-attribution layer (per-operator
self-time, state-size gauges, key-skew sketches via ``obs.sketch``, the
``/profile`` snapshot, and the EXPLAIN ANALYZE renderer behind
``python -m arroyo_tpu explain``); ``obs.topview`` renders the
controller-DB-backed per-operator table behind ``python -m arroyo_tpu
top``. The watermark-lag gauge, sink end-to-end latency, and checkpoint
phase histograms live in ``arroyo_tpu.metrics`` next to the task counters.

``obs.events`` is the third pillar: the structured per-job event log
(operator panics, restores, wedged epochs, commit re-deliveries, rescales,
health transitions) behind ``GET /api/v1/jobs/<id>/events`` and
``python -m arroyo_tpu logs``; ``obs.health`` holds the controller-side
health monitors (rule set + hysteresis over the merged job metrics) whose
state surfaces as ``arroyo_job_health``, the jobs API ``health`` field,
and ``GET /api/v1/jobs/<id>/health``.
"""

from .trace import (  # noqa: F401 - public API
    EpochTraceRecorder,
    chrome_trace,
    dominant_phase,
    phase_durations,
    recorder,
    timeline_report,
)
