"""arroyo-tpu: a TPU-native distributed stream processing framework.

SQL pipelines over unbounded streams with event-time watermarks, windowed
aggregates/joins lowered to JAX/XLA, exactly-once Parquet
checkpointing, and keyed exchange over TPU ICI collectives. Built new against
the capabilities of the reference engine surveyed in SURVEY.md.
"""

__version__ = "0.1.0"

from .batch import Batch, Field, Schema  # noqa: F401
from .graph import EdgeType, Graph, Node, OpName  # noqa: F401
from .types import (  # noqa: F401
    CheckpointBarrier,
    Signal,
    SignalKind,
    TaskInfo,
    Watermark,
)


def _load_operators() -> None:
    """Import all operator/connector modules so constructors register."""
    from .utils import ensure_parquet_initialized

    ensure_parquet_initialized()  # see utils/arrow.py: must happen before
    # any engine task thread touches parquet
    from . import connectors
    from .operators import builtin  # noqa: F401

    connectors.load_all()
    from .operators import async_udf, chained, joins, updating_aggregate, window_fn  # noqa: F401
    from .windows import session, sliding, tumbling  # noqa: F401
