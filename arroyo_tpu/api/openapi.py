"""OpenAPI 3.0 description of the REST API.

Equivalent of the reference's utoipa-generated spec that feeds
crates/arroyo-openapi (the generated client) and the web UI's typed
bindings (webui/src/gen). The spec is built from the same route table the
server dispatches on, so paths can't drift from the implementation; a
test asserts the client (client.py) covers every operation.
Served at GET /api/v1/openapi.json.
"""

from __future__ import annotations

_OBJ = {"type": "object"}
_STR = {"type": "string"}
_INT = {"type": "integer"}


def _op(op_id: str, summary: str, params: list[str] = (),
        body: dict | None = None, response: dict | None = None) -> dict:
    out: dict = {
        "operationId": op_id,
        "summary": summary,
        "parameters": [
            {"name": p, "in": "path", "required": True, "schema": _STR}
            for p in params
        ],
        "responses": {
            "200": {
                "description": "success",
                "content": {"application/json": {"schema": response or _OBJ}},
            }
        },
    }
    if body is not None:
        out["requestBody"] = {
            "required": True,
            "content": {"application/json": {"schema": body}},
        }
    return out


PIPELINE = {
    "type": "object",
    "properties": {"id": _STR, "name": _STR, "query": _STR, "parallelism": _INT},
}
JOB = {
    "type": "object",
    "properties": {
        "id": _STR, "pipeline_id": _STR, "state": _STR,
        "restarts": _INT, "checkpoint_epoch": _INT,
        "n_workers": _INT,  # size of the job's running worker set
        # ok | degraded | critical (controller health monitors)
        "health": _STR,
        # multi-tenant fleet: the tenant keying admission queues/quotas,
        # and (Queued jobs only) the 1-based admission-queue position
        "tenant": _STR,
        "queue_position": _INT,
    },
}
FLEET = {
    "type": "object",
    "properties": {
        # null pool_slots/slots_free = unlimited (fleet pass-through)
        "pool_slots": _INT,
        "slots_used": _INT,
        "slots_free": _INT,
        # the fleet autoscaler's pool target — for externally sized pools
        # (node daemons, k8s node pools) this is the scaling knob
        "target_workers": _INT,
        "queue_depth": {"type": "object",
                        "additionalProperties": _INT},
        "queue": {"type": "array", "items": {
            "type": "object",
            "properties": {"job_id": _STR, "tenant": _STR,
                           "slots": _INT, "position": _INT}}},
        "tenants": {"type": "object", "additionalProperties": {
            "type": "object",
            "properties": {"slots_used": _INT, "jobs_running": _INT,
                           "queued": _INT}}},
    },
}
JOB_EVENT = {
    "type": "object",
    "properties": {
        "seq": _INT, "ts_us": _INT,
        "level": {"type": "string",
                  "enum": ["DEBUG", "INFO", "WARN", "ERROR"]},
        "code": _STR,  # stable EventCode (see README "Events & health")
        "node": _STR, "subtask": _INT, "worker": _INT, "epoch": _INT,
        "message": _STR, "data": {"type": "object"},
    },
}
JOB_HEALTH = {
    "type": "object",
    "properties": {
        "job_id": _STR,
        "state": {"type": "string", "enum": ["ok", "degraded", "critical"]},
        "rules": {"type": "array", "items": {
            "type": "object",
            "properties": {
                "rule": _STR, "severity": _STR, "description": _STR,
                "value": {"type": "number"}, "threshold": {"type": "number"},
                "breaching": {"type": "boolean"},
                "firing": {"type": "boolean"},
            }}},
        # elastic-autoscaler readout (controller/autoscaler.py): rail
        # state, live signals, and the last decision
        "autoscaler": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean"},
                "parallelism": {"type": "integer"},
                "target": {"type": "integer"},
                "in_flight": {"type": "boolean"},
                "up_ticks": {"type": "integer"},
                "down_ticks": {"type": "integer"},
                "cooldown_remaining_s": {"type": "number"},
                "backoff_remaining_s": {"type": "number"},
                "failures": {"type": "integer"},
                "signals": {"type": "array", "items": {
                    "type": "object",
                    "properties": {
                        "signal": _STR, "value": {"type": "number"},
                        "threshold": {"type": "number"},
                        # pressure rows carry `breaching` (true = bad);
                        # the headroom row carries `proven` (true = idle
                        # enough to scale down) — opposite polarity
                        "breaching": {"type": "boolean"},
                        "proven": {"type": "boolean"},
                    }}},
                "last_decision": {"type": "object"},
            },
        },
    },
}
UDF = {
    "type": "object",
    "properties": {
        "name": _STR, "language": {"type": "string", "enum": ["cpp", "python"]},
        "source": _STR, "arg_dtypes": {"type": "array", "items": _STR},
        "return_dtype": _STR,
    },
    "required": ["name", "source"],
}
NODE = {
    "type": "object",
    "properties": {"node_id": _STR, "addr": _STR, "slots": _INT},
    "required": ["node_id", "addr"],
}
CONNECTION_PROFILE = {
    "type": "object",
    "properties": {"name": _STR, "connector": _STR,
                   "config": {"type": "object"}},
    "required": ["name", "connector"],
}
CONNECTION_TABLE = {
    "type": "object",
    "properties": {
        "name": _STR, "connector": _STR,
        "table_type": {"type": "string", "enum": ["source", "sink"]},
        "profile_id": _STR,
        "config": {"type": "object"},
        "schema_fields": {"type": "array", "items": {
            "type": "object",
            "properties": {"name": _STR, "type": _STR,
                           "nullable": {"type": "boolean"}},
            "required": ["name", "type"]}},
    },
    "required": ["name", "connector"],
}


def spec() -> dict:
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "arroyo-tpu REST API",
            "version": "1.0.0",
            "description": "Pipeline/job/UDF/node management for the "
                           "TPU-native streaming engine.",
        },
        "paths": {
            "/api/v1/ping": {"get": _op("ping", "liveness probe")},
            "/api/v1/pipelines/validate": {
                "post": _op("validate_query", "validate SQL without creating",
                            body={"type": "object", "properties": {"query": _STR},
                                  "required": ["query"]})},
            "/api/v1/pipelines": {
                "post": _op("create_pipeline", "create pipeline + job",
                            body={"type": "object",
                                  "properties": {"name": _STR, "query": _STR,
                                                 "parallelism": _INT,
                                                 # fleet admission/quota key
                                                 "tenant": _STR},
                                  "required": ["query"]}),
                "get": _op("list_pipelines", "list pipelines",
                           response={"type": "object",
                                     "properties": {"data": {"type": "array",
                                                             "items": PIPELINE}}})},
            "/api/v1/pipelines/{pipeline_id}": {
                "get": _op("get_pipeline", "fetch one pipeline", ["pipeline_id"],
                           response=PIPELINE),
                "delete": _op("delete_pipeline", "delete pipeline + jobs",
                              ["pipeline_id"])},
            "/api/v1/pipelines/{pipeline_id}/graph": {
                "get": _op("pipeline_graph", "planned dataflow DAG",
                           ["pipeline_id"],
                           response={"type": "object", "properties": {
                               "nodes": {"type": "array", "items": {
                                   "type": "object", "properties": {
                                       "id": _STR, "op": _STR,
                                       "description": _STR,
                                       "parallelism": _INT,
                                       # chained run marked for whole-
                                       # segment compilation (plan-time;
                                       # runtime truth is the profile's
                                       # segment_compiled flag)
                                       "compilable": {"type": "boolean"}}}},
                               "edges": {"type": "array", "items": {
                                   "type": "object", "properties": {
                                       "src": _STR, "dst": _STR,
                                       "type": _STR}}}}})},
            "/api/v1/pipelines/{pipeline_id}/evolve": {
                "post": _op(
                    "evolve_pipeline",
                    "live evolution (versioned redeploy): plan-diff the "
                    "evolved SQL against the current plan; on success the "
                    "running job drains behind a final checkpoint, carries "
                    "proven state, and cuts over blue/green — an "
                    "incompatible change is rejected here with AR-series "
                    "diagnostics and never touches the job",
                    ["pipeline_id"],
                    body={"type": "object",
                          "properties": {"query": _STR},
                          "required": ["query"]},
                    response={"type": "object", "properties": {
                        "id": _STR, "job_id": _STR, "version": _INT,
                        "classifications": {"type": "array", "items": {
                            "type": "object", "properties": {
                                "node_id": _STR,
                                "action": {"type": "string",
                                           "enum": ["carried", "rebuilt",
                                                    "dropped", "stateless",
                                                    "incompatible"]},
                                "from": _STR, "detail": _STR}}},
                        "diagnostics": {"type": "array", "items": {
                            "type": "object", "properties": {
                                "rule": _STR, "severity": _STR,
                                "site": _STR, "message": _STR,
                                "hint": _STR}}}}})},
            "/api/v1/pipelines/{pipeline_id}/jobs": {
                "get": _op("pipeline_jobs", "jobs of a pipeline", ["pipeline_id"],
                           response={"type": "object",
                                     "properties": {"data": {"type": "array",
                                                             "items": JOB}}})},
            "/api/v1/jobs": {
                "get": _op("list_jobs", "list all jobs")},
            "/api/v1/jobs/{job_id}": {
                "get": _op("get_job", "fetch one job", ["job_id"], response=JOB),
                "patch": _op("patch_job", "stop / rescale a job", ["job_id"],
                             body={"type": "object",
                                   "properties": {"stop": {"type": "string",
                                                           "enum": ["checkpoint",
                                                                    "immediate",
                                                                    "none"]},
                                                  "parallelism": _INT}})},
            "/api/v1/jobs/{job_id}/checkpoints": {
                "get": _op("job_checkpoints", "checkpoint history", ["job_id"])},
            "/api/v1/jobs/{job_id}/output": {
                "get": _op("job_output", "preview sink rows", ["job_id"])},
            "/api/v1/jobs/{job_id}/metrics": {
                "get": _op("job_metrics", "operator metric groups", ["job_id"])},
            "/api/v1/jobs/{job_id}/profile": {
                "get": _op("job_profile", "runtime cost profile (per-operator "
                           "busy%, self-time, state sizes, hot keys)",
                           ["job_id"])},
            "/api/v1/jobs/{job_id}/traces": {
                "get": _op("job_traces", "checkpoint epoch traces "
                           "(Chrome trace-event JSON; ?format=events for "
                           "raw spans, ?epoch=N to restrict)", ["job_id"])},
            "/api/v1/jobs/{job_id}/events": {
                "get": _op("job_events", "structured job event feed "
                           "(?level= minimum level, ?since= unix seconds, "
                           "?after= seq cursor for tailing)", ["job_id"],
                           response={"type": "object", "properties": {
                               "job_id": _STR,
                               "data": {"type": "array", "items": JOB_EVENT},
                           }})},
            "/api/v1/jobs/{job_id}/health": {
                "get": _op("job_health", "job health state with per-rule "
                           "detail (hysteresis-filtered monitors over the "
                           "merged job metrics) plus the elastic "
                           "autoscaler's rail state and last decision",
                           ["job_id"],
                           response=JOB_HEALTH)},
            "/api/v1/jobs/{job_id}/fsck": {
                "get": _op("job_fsck", "offline checkpoint-chain "
                           "verification: marker checksums, sidecar and "
                           "table-file envelopes, spill-run liveness, "
                           "evolution-mapping pairing, orphans — FS-series "
                           "diagnostics; clean is false iff any ERROR",
                           ["job_id"],
                           response={"type": "object", "properties": {
                               "job_id": _STR,
                               "storage_url": _STR,
                               "clean": {"type": "boolean"},
                               "diagnostics": {"type": "array", "items": {
                                   "type": "object", "properties": {
                                       "rule": _STR, "severity": _STR,
                                       "site": _STR, "message": _STR,
                                       "hint": _STR}}}}})},
            "/api/v1/fleet": {
                "get": _op("fleet_status", "multi-tenant fleet snapshot: "
                           "pool occupancy, per-tenant usage, and the "
                           "admission queue with positions",
                           response=FLEET)},
            "/api/v1/connectors": {
                "get": _op("list_connectors", "available connectors")},
            "/api/v1/connection_profiles": {
                "post": _op("create_connection_profile",
                            "register shared connector options",
                            body=CONNECTION_PROFILE),
                "get": _op("list_connection_profiles",
                           "list connection profiles")},
            "/api/v1/connection_profiles/{id}": {
                "delete": _op("delete_connection_profile",
                              "drop an unreferenced profile", ["id"])},
            "/api/v1/connection_tables": {
                "post": _op("create_connection_table",
                            "register a named source/sink usable in SQL",
                            body=CONNECTION_TABLE),
                "get": _op("list_connection_tables", "list connection tables")},
            "/api/v1/connection_tables/{id}": {
                "delete": _op("delete_connection_table",
                              "drop a connection table", ["id"])},
            "/api/v1/connection_tables/test": {
                "post": _op("test_connection_table",
                            "validate a connection-table spec",
                            body=CONNECTION_TABLE)},
            "/api/v1/udfs": {
                "post": _op("create_udf", "compile/register a UDF", body=UDF),
                "get": _op("list_udfs", "list registered UDFs")},
            "/api/v1/udfs/{name}": {
                "delete": _op("delete_udf", "drop a UDF", ["name"])},
            "/api/v1/nodes/register": {
                "post": _op("register_node", "node daemon registration", body=NODE)},
            "/api/v1/nodes/{node_id}/heartbeat": {
                "post": _op("node_heartbeat", "node liveness beat", ["node_id"])},
            "/api/v1/nodes": {
                "get": _op("list_nodes", "registered node daemons")},
        },
    }
