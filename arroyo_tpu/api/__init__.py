"""REST API server (reference crates/arroyo-api)."""

from .server import ApiServer

__all__ = ["ApiServer"]
