"""REST API: pipeline/job CRUD over the shared DB.

Reference: crates/arroyo-api/src/rest.rs:127-181 route table (axum). Same
resource model: pipelines are validated SQL; creating one starts a job; jobs
are stopped by PATCHing desired_stop; checkpoints are queryable. Served with
the stdlib ThreadingHTTPServer — the API is off the data path.

Routes:
  GET    /api/v1/ping
  POST   /api/v1/pipelines/validate   {"query"}           -> {"valid", "errors"}
  POST   /api/v1/pipelines            {"name","query","parallelism"}
  GET    /api/v1/pipelines
  GET    /api/v1/pipelines/{id}
  DELETE /api/v1/pipelines/{id}
  GET    /api/v1/pipelines/{id}/jobs
  POST   /api/v1/pipelines/{id}/evolve {"query"}           -> classification
  GET    /api/v1/jobs
  GET    /api/v1/jobs/{id}
  PATCH  /api/v1/jobs/{id}            {"stop": "checkpoint"|"immediate"} |
                                      {"action": "restart"}
  GET    /api/v1/jobs/{id}/checkpoints
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..controller.db import Database


class ApiServer:
    """Trust model: by default the API trusts its network — anyone who can
    reach the port can register UDFs (which execute user code on the
    cluster, same exposure as the reference's UDF surface) and manage
    pipelines. Deployments beyond localhost should set ``api.auth-token``
    (ARROYO_TPU__API__AUTH_TOKEN): every mutating request (non-GET) must
    then carry ``Authorization: Bearer <token>``; reads stay open for
    dashboards. The node daemon and typed client pick the token up from
    the same config."""

    def __init__(self, db: Database, port: int = 0, host: str = "127.0.0.1"):
        from ..config import config

        self.db = db
        self.auth_token = config().get("api.auth-token")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence default stderr spam
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                if not n:
                    return {}
                try:
                    return json.loads(self.rfile.read(n))
                except json.JSONDecodeError:
                    return {}

            def do_GET(self):
                outer._route(self, "GET")

            def do_POST(self):
                outer._route(self, "POST")

            def do_PATCH(self):
                outer._route(self, "PATCH")

            def do_DELETE(self):
                outer._route(self, "DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- routing

    _ROUTES = [
        ("GET", r"^/$", "_webui"),
        ("GET", r"^/webui/([A-Za-z0-9_.-]+)$", "_webui_asset"),
        ("GET", r"^/api/v1/openapi\.json$", "_openapi"),
        ("GET", r"^/api/v1/ping$", "_ping"),
        ("POST", r"^/api/v1/pipelines/validate$", "_validate"),
        ("POST", r"^/api/v1/pipelines$", "_create_pipeline"),
        ("GET", r"^/api/v1/pipelines$", "_list_pipelines"),
        ("GET", r"^/api/v1/pipelines/([^/]+)$", "_get_pipeline"),
        ("DELETE", r"^/api/v1/pipelines/([^/]+)$", "_delete_pipeline"),
        ("GET", r"^/api/v1/pipelines/([^/]+)/jobs$", "_pipeline_jobs"),
        ("GET", r"^/api/v1/pipelines/([^/]+)/graph$", "_pipeline_graph"),
        ("POST", r"^/api/v1/pipelines/([^/]+)/evolve$", "_evolve_pipeline"),
        ("GET", r"^/api/v1/jobs$", "_list_jobs"),
        ("GET", r"^/api/v1/jobs/([^/]+)$", "_get_job"),
        ("PATCH", r"^/api/v1/jobs/([^/]+)$", "_patch_job"),
        ("GET", r"^/api/v1/jobs/([^/]+)/checkpoints$", "_job_checkpoints"),
        ("GET", r"^/api/v1/jobs/([^/]+)/output$", "_job_output"),
        ("GET", r"^/api/v1/jobs/([^/]+)/metrics$", "_job_metrics"),
        ("GET", r"^/api/v1/jobs/([^/]+)/profile$", "_job_profile"),
        ("GET", r"^/api/v1/jobs/([^/]+)/traces$", "_job_traces"),
        ("GET", r"^/api/v1/jobs/([^/]+)/events$", "_job_events"),
        ("GET", r"^/api/v1/jobs/([^/]+)/health$", "_job_health"),
        ("GET", r"^/api/v1/jobs/([^/]+)/fsck$", "_job_fsck"),
        ("GET", r"^/api/v1/fleet$", "_fleet"),
        ("GET", r"^/api/v1/connectors$", "_connectors"),
        ("POST", r"^/api/v1/connection_profiles$", "_create_profile"),
        ("GET", r"^/api/v1/connection_profiles$", "_list_profiles"),
        ("DELETE", r"^/api/v1/connection_profiles/([^/]+)$", "_delete_profile"),
        ("POST", r"^/api/v1/connection_tables$", "_create_conn_table"),
        ("GET", r"^/api/v1/connection_tables$", "_list_conn_tables"),
        ("DELETE", r"^/api/v1/connection_tables/([^/]+)$", "_delete_conn_table"),
        ("POST", r"^/api/v1/connection_tables/test$", "_test_conn_table"),
        ("POST", r"^/api/v1/nodes/register$", "_register_node"),
        ("POST", r"^/api/v1/nodes/([^/]+)/heartbeat$", "_node_heartbeat"),
        ("GET", r"^/api/v1/nodes$", "_list_nodes"),
        ("POST", r"^/api/v1/udfs$", "_create_udf"),
        ("GET", r"^/api/v1/udfs$", "_list_udfs"),
        ("DELETE", r"^/api/v1/udfs/([^/]+)$", "_delete_udf"),
    ]

    def _route(self, h, method: str) -> None:
        path = h.path.split("?", 1)[0]
        if self.auth_token and method != "GET":
            # shared-token gate on every mutating endpoint (ADVICE r4: the
            # UDF surface is remote code execution by design; see class
            # docstring for the trust model)
            if h.headers.get("Authorization") != f"Bearer {self.auth_token}":
                h._json(401, {"error": "missing or invalid bearer token"})
                return
        for m, pat, name in self._ROUTES:
            if m != method:
                continue
            match = re.match(pat, path)
            if match:
                try:
                    getattr(self, name)(h, *match.groups())
                except Exception as e:  # noqa: BLE001
                    h._json(500, {"error": str(e)})
                return
        h._json(404, {"error": f"no route {method} {path}"})

    # ------------------------------------------------------------ handlers

    def _ping(self, h):
        h._json(200, {"pong": True})

    def _openapi(self, h):
        from .openapi import spec

        h._json(200, spec())

    _WEBUI_TYPES = {".html": "text/html; charset=utf-8",
                    ".js": "text/javascript; charset=utf-8",
                    ".css": "text/css; charset=utf-8",
                    ".svg": "image/svg+xml"}

    def _serve_webui_file(self, h, name: str) -> None:
        import os

        base = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "webui")
        path = os.path.join(base, name)
        # route regex forbids path separators; keep the normpath guard anyway
        if not os.path.normpath(path).startswith(base) or not os.path.isfile(path):
            h._json(404, {"error": f"no asset {name!r}"})
            return
        with open(path, "rb") as f:
            data = f.read()
        ext = os.path.splitext(name)[1]
        h.send_response(200)
        h.send_header("Content-Type",
                      self._WEBUI_TYPES.get(ext, "application/octet-stream"))
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)

    def _webui(self, h):
        self._serve_webui_file(h, "index.html")

    def _webui_asset(self, h, name):
        self._serve_webui_file(h, name)

    def _activate_udfs(self) -> None:
        from ..compiler import activate_udf_specs

        rows = self.db.list_udfs()
        # registry is process-global: re-executing N user sources on every
        # validate/create request is waste — only re-activate on change
        fp = tuple(sorted((r["name"], r["created_at"], r["source"]) for r in rows))
        if fp == getattr(self, "_udf_fingerprint", None):
            return
        activate_udf_specs(rows)
        self._udf_fingerprint = fp

    def _validate(self, h):
        from ..sql import plan_query
        from ..sql.lexer import SqlError

        body = h._body()
        try:
            self._activate_udfs()
            plan_query(body.get("query", ""),
                       connection_tables=self.db.list_connection_tables())
            h._json(200, {"valid": True, "errors": []})
        except SqlError as e:
            h._json(200, {"valid": False, "errors": [str(e)]})

    def _register_node(self, h):
        body = h._body()
        self.db.register_node(body["node_id"], body["addr"], int(body.get("slots", 16)))
        h._json(200, {"registered": body["node_id"]})

    def _node_heartbeat(self, h, node_id):
        if self.db.node_heartbeat(node_id):
            h._json(200, {})
        else:
            h._json(404, {"error": "unknown node (re-register)"})

    def _list_nodes(self, h):
        h._json(200, {"nodes": self.db.list_nodes()})

    def _create_udf(self, h):
        """Create a UDF: cpp sources compile through the CompileService
        (artifact pushed to storage); python sources are stored and executed
        at plan/worker start (reference: POST /udfs + compiler service)."""
        from ..compiler import activate_udf_specs, compile_udf

        body = h._body()
        name = body.get("name")
        language = body.get("language", "cpp")
        source = body.get("source")
        if not name or not source:
            h._json(400, {"error": "name and source are required"})
            return
        artifact = None
        arg_dtypes = list(body.get("arg_dtypes", []))
        return_dtype = body.get("return_dtype", "float64")
        try:
            if language == "cpp":
                # remote compile service when compiler.endpoint is set
                spec = compile_udf(name, source, arg_dtypes, return_dtype)
                artifact = spec.artifact_url
            self.db.create_udf(name, language, source, arg_dtypes, return_dtype, artifact)
            try:
                # a source that fails to activate must not stay persisted, or
                # it would poison every later validate/create
                activate_udf_specs([{
                    "name": name, "language": language, "source": source,
                    "arg_dtypes": arg_dtypes, "return_dtype": return_dtype,
                    "artifact_url": artifact,
                }])
            except Exception:
                self.db.delete_udf(name)
                raise
        except Exception as e:  # user code raises anything
            h._json(400, {"error": f"UDF rejected: {e}"})
            return
        h._json(200, {"name": name, "language": language, "artifact_url": artifact})

    def _list_udfs(self, h):
        h._json(200, {"udfs": [
            {k: u[k] for k in ("name", "language", "return_dtype", "arg_dtypes", "artifact_url")}
            for u in self.db.list_udfs()
        ]})

    def _delete_udf(self, h, name):
        from ..udf import drop_udaf, drop_udf

        self.db.delete_udf(name)
        drop_udf(name)
        drop_udaf(name)
        h._json(200, {"deleted": name})

    def _create_pipeline(self, h):
        from ..sql import plan_query
        from ..sql.lexer import SqlError

        body = h._body()
        name = body.get("name") or "pipeline"
        query = body.get("query")
        if not query:
            h._json(400, {"error": "query is required"})
            return
        try:
            self._activate_udfs()
            plan_query(query, connection_tables=self.db.list_connection_tables())
        except SqlError as e:
            h._json(400, {"error": f"invalid query: {e}"})
            return
        parallelism = int(body.get("parallelism", 1))
        pid = self.db.create_pipeline(name, query, parallelism)
        # tenant keys the fleet's per-tenant admission queues and quotas
        jid = self.db.create_job(pid, tenant=str(body.get("tenant")
                                                 or "default"))
        h._json(200, {"id": pid, "name": name, "job_id": jid})

    def _list_pipelines(self, h):
        h._json(200, {"data": self.db.list_pipelines()})

    def _get_pipeline(self, h, pid):
        p = self.db.get_pipeline(pid)
        h._json(200, p) if p else h._json(404, {"error": "not found"})

    def _delete_pipeline(self, h, pid):
        for job in self.db.list_jobs(pid):
            if job["state"] not in ("Failed", "Finished", "Stopped"):
                h._json(409, {"error": "stop the pipeline's jobs first"})
                return
        self.db.delete_pipeline(pid)
        h._json(200, {"deleted": pid})

    def _pipeline_jobs(self, h, pid):
        h._json(200, {"data": self.db.list_jobs(pid)})

    def _pipeline_graph(self, h, pid):
        """Planned dataflow DAG for the UI's graph view (reference
        PipelineGraph.tsx consumes the pipeline's edges/nodes)."""
        from ..sql.lexer import SqlError
        from ..sql.planner import executed_graph_view

        p = self.db.get_pipeline(pid)
        if not p:
            h._json(404, {"error": "not found"})
            return
        try:
            self._activate_udfs()
            # the DAG as it EXECUTES (parallelism + chaining), so node ids
            # line up with runtime metric/profile keys — see the helper
            nodes, edges = executed_graph_view(
                p["query"], int(p.get("parallelism") or 1),
                connection_tables=self.db.list_connection_tables())
        except SqlError as e:
            h._json(400, {"error": str(e)})
            return
        h._json(200, {"nodes": nodes, "edges": edges})

    def _evolve_pipeline(self, h, pid):
        """Live evolution (versioned redeploy): validate the evolved SQL,
        run the plan-diff pass against the CURRENT query, and — only when
        no AR-series ERROR rejects the carry-over — hand the controller a
        ``desired_query`` to actuate (drain behind a final checkpoint,
        restore the evolved plan through the proven mapping, blue/green
        cutover). An incompatible evolution is rejected HERE, at plan
        time: it never reaches Scheduling and the running job is never
        touched."""
        from ..analysis.plan_diff import diff_plans
        from ..sql import plan_query
        from ..sql.lexer import SqlError

        p = self.db.get_pipeline(pid)
        if not p:
            h._json(404, {"error": "not found"})
            return
        body = h._body()
        query = body.get("query")
        if not query:
            h._json(400, {"error": "query is required"})
            return
        try:
            self._activate_udfs()
            scope = self.db.list_connection_tables()
            old_graph = plan_query(p["query"],
                                   connection_tables=scope).graph
            new_graph = plan_query(query, connection_tables=scope).graph
        except SqlError as e:
            h._json(400, {"error": f"invalid query: {e}"})
            return
        diff = diff_plans(old_graph, new_graph)
        payload = {
            "classifications": [c.to_json() for c in diff.classifications],
            "diagnostics": [d.to_dict() for d in diff.diagnostics],
        }
        if diff.rejected:
            errs = "; ".join(f"{d.rule_id}: {d.message}"
                             for d in diff.diagnostics
                             if d.severity.name == "ERROR")
            h._json(400, {"error": f"evolution rejected: {errs}", **payload})
            return
        live = [j for j in self.db.list_jobs(pid)
                if j["state"] not in ("Failed", "Finished", "Stopped")]
        if not live:
            h._json(409, {"error": "pipeline has no live job to evolve; "
                                   "restart it first"})
            return
        jid = live[-1]["id"]
        if query == p["query"]:
            h._json(200, {"id": pid, "job_id": jid, "noop": True, **payload})
            return
        self.db.update_job(jid, desired_query=query)
        h._json(200, {"id": pid, "job_id": jid,
                      "version": int(p.get("version") or 1) + 1, **payload})

    def _list_jobs(self, h):
        h._json(200, {"data": self.db.list_jobs()})

    def _get_job(self, h, jid):
        j = self.db.get_job(jid)
        if not j:
            h._json(404, {"error": "not found"})
            return
        if j.get("state") == "Queued":
            # surface the admission-queue position from the controller's
            # persisted fleet snapshot (cross-process: the API only has
            # the DB)
            pos = self.db.fleet_queue_position(jid)
            if pos is not None:
                j["queue_position"] = pos
        h._json(200, j)

    def _fleet(self, h):
        """Multi-tenant fleet snapshot (controller/fleet.py): pool size,
        used/free slots, per-tenant usage + quota queue depth, and the
        admission queue with positions."""
        h._json(200, self.db.get_fleet_state() or {
            "pool_slots": None, "slots_used": 0, "slots_free": None,
            "target_workers": 0, "queue_depth": {}, "queue": [],
            "tenants": {}})

    def _patch_job(self, h, jid):
        j = self.db.get_job(jid)
        if not j:
            h._json(404, {"error": "not found"})
            return
        body = h._body()
        if body.get("action") == "restart":
            self.db.update_job(jid, state="Restarting", desired_stop=None)
            h._json(200, {"id": jid, "state": "Restarting"})
            return
        if "parallelism" in body:
            # live rescale (reference jobs.rs parallelism patch +
            # states/rescaling.rs): the controller checkpoints-and-stops the
            # running worker, then reschedules at the new parallelism
            want = body["parallelism"]
            # bool is an int subclass; floats must not silently truncate
            if isinstance(want, bool) or not isinstance(want, int):
                h._json(400, {"error": "parallelism must be an integer"})
                return
            if want < 1:
                h._json(400, {"error": "parallelism must be >= 1"})
                return
            if j["state"] not in ("Running", "Scheduling", "Created", "Compiling"):
                h._json(409, {"error": f"cannot rescale a {j['state']} job"})
                return
            self.db.update_job(jid, desired_parallelism=want)
            h._json(200, {"id": jid, "desired_parallelism": want})
            return
        stop = body.get("stop")
        if stop not in ("checkpoint", "immediate"):
            h._json(400, {"error": "stop must be 'checkpoint' or 'immediate'"})
            return
        self.db.update_job(jid, desired_stop=stop)
        h._json(200, {"id": jid, "desired_stop": stop})

    def _job_checkpoints(self, h, jid):
        h._json(200, {"data": self.db.list_checkpoints(jid)})

    def _job_output(self, h, jid):
        # ?after=<seq> for incremental tailing (reference SubscribeToOutput)
        after = -1
        if "?" in h.path:
            from urllib.parse import parse_qs

            q = parse_qs(h.path.split("?", 1)[1])
            after = int(q.get("after", ["-1"])[0])
        h._json(200, {"data": self.db.list_outputs(jid, after_seq=after)})

    def _job_traces(self, h, jid):
        """Epoch-lifecycle traces (obs.trace): Chrome trace-event JSON by
        default (loads directly in chrome://tracing / Perfetto's legacy-UI
        importer); ``?format=events`` returns the raw span events (the
        `trace --report` CLI renders timelines from these); ``?epoch=N``
        restricts either form to one epoch."""
        from urllib.parse import parse_qs

        from ..obs import events as obs_events
        from ..obs import trace as obs_trace

        q = parse_qs(h.path.split("?", 1)[1]) if "?" in h.path else {}
        epoch = int(q["epoch"][0]) if q.get("epoch") else None
        # DB-persisted rows (written by the controller) cover every
        # scheduler; the in-process recorder — when this process has one for
        # the job — is always at least as complete (DB rows are snapshots of
        # it taken at checkpoint-complete time, before late commit spans), so
        # recorder events win per epoch
        rows = self.db.list_traces(jid, epoch=epoch)
        by_epoch = {r["epoch"]: r["events"] for r in rows}
        for e in obs_trace.recorder.epochs(jid):
            if epoch is None or e == epoch:
                by_epoch[e] = obs_trace.recorder.events(jid, e)
        if q.get("format", [""])[0] == "events":
            h._json(200, {"job_id": jid, "epochs": {
                str(e): evs for e, evs in sorted(by_epoch.items())}})
            return
        # epoch-scoped job events render as instant markers on the same
        # timeline, so spans and the event feed correlate in one view
        job_events = self.db.list_events(jid) or obs_events.recorder.events(jid)
        h._json(200, obs_trace.chrome_trace(jid, by_epoch,
                                            job_events=job_events))

    def _job_events(self, h, jid):
        """Structured job event feed (obs.events): the controller-persisted
        rows, oldest first. ``?level=WARN`` filters to a minimum level,
        ``?since=<unix seconds>`` to a wall-time floor, ``?after=<seq>`` is
        the incremental-tail cursor the `logs --follow` CLI uses. Falls
        back to the in-process ring for jobs whose controller shares this
        process and has not flushed yet."""
        from urllib.parse import parse_qs

        from ..obs import events as obs_events

        q = parse_qs(h.path.split("?", 1)[1]) if "?" in h.path else {}
        level = q.get("level", [None])[0]
        since = float(q["since"][0]) if q.get("since") else None
        after = int(q.get("after", ["0"])[0])
        data = self.db.list_events(jid, level=level, since=since,
                                   after_seq=after)
        if not data:
            data = obs_events.recorder.events(
                jid, level=level,
                since_us=None if since is None else int(since * 1e6),
                after_seq=after or None)
        h._json(200, {"job_id": jid, "data": data})

    def _job_health(self, h, jid):
        """Job health with per-rule detail (obs.health): state plus each
        rule's observed value, threshold, and firing flag — what the
        autoscaler (and `top`'s header) read."""
        job = self.db.get_job(jid)
        if not job:
            h._json(404, {"error": "not found"})
            return
        detail = self.db.get_health(jid) or {
            "state": job.get("health") or "ok", "rules": []}
        h._json(200, {"job_id": jid, **detail})

    def _job_fsck(self, h, jid):
        """Offline checkpoint-chain verification (state.integrity.fsck_job):
        walks every epoch's artifacts — marker checksum, sidecar and
        table-file envelopes, spill-run liveness and footers,
        evolution-mapping pairing, orphans — and returns the FS-series
        diagnostics. ``clean`` is False iff any ERROR finding exists (the
        same predicate as `arroyo_tpu fsck`'s exit code);
        ``?storage_url=`` overrides the configured checkpoint store."""
        from urllib.parse import parse_qs

        from ..analysis import Severity
        from ..config import config
        from ..state.integrity import fsck_job

        q = parse_qs(h.path.split("?", 1)[1]) if "?" in h.path else {}
        storage_url = (q["storage_url"][0] if q.get("storage_url")
                       else str(config().get("checkpoint.storage-url")))
        diags = fsck_job(storage_url, jid)
        h._json(200, {
            "job_id": jid,
            "storage_url": storage_url,
            "clean": not any(d.severity == Severity.ERROR for d in diags),
            "diagnostics": [d.to_dict() for d in diags],
        })

    def _job_metrics(self, h, jid):
        # DB-persisted snapshots (shipped from workers over the control
        # protocol) cover the process scheduler; fall back to the local
        # registry for an in-flight embedded job
        data = self.db.get_metrics(jid)
        if data is None:
            from ..metrics import registry as metrics_registry

            data = metrics_registry.job_metrics(jid)
        h._json(200, {"data": data})

    def _job_profile(self, h, jid):
        """Runtime cost profile (obs.profile): per-operator busy%, self-time
        by category, state rows/bytes per table, merged top-k hot keys, and
        late-row drops — the controller-persisted snapshot, falling back to
        a live derivation from the local registry for embedded jobs."""
        data = self.db.get_profile(jid)
        if data is None:
            from ..metrics import registry as metrics_registry
            from ..obs.profile import job_profile

            data = job_profile(metrics_registry.job_metrics(jid))
        h._json(200, {"data": data})

    def _connectors(self, h):
        from ..connectors import connectors

        h._json(200, connectors())

    # ------------------------------------------- connection tables/profiles
    # (reference arroyo-api/src/rest.rs:144-158 connection_profiles +
    # connection_tables CRUD; registered tables are usable by name in
    # pipeline SQL with no inline DDL)

    def _create_profile(self, h):
        body = h._body()
        for field in ("name", "connector"):
            if not body.get(field):
                h._json(400, {"error": f"missing {field!r}"})
                return
        if any(p["name"] == body["name"]
               for p in self.db.list_connection_profiles()):
            h._json(409, {"error": f"profile {body['name']!r} already exists"})
            return
        cid = self.db.create_connection_profile(
            body["name"], body["connector"], body.get("config") or {})
        h._json(200, {"id": cid, "name": body["name"]})

    def _list_profiles(self, h):
        h._json(200, {"data": self.db.list_connection_profiles()})

    def _delete_profile(self, h, cid):
        if not self.db.delete_connection_profile(cid):
            h._json(409, {"error": "profile is referenced by connection tables"})
            return
        h._json(200, {"deleted": cid})

    def _validate_conn_table(self, body) -> Optional[str]:
        """Reason the spec is invalid, or None when usable."""
        from ..connectors import connectors

        for field in ("name", "connector"):
            if not body.get(field):
                return f"missing {field!r}"
        ttype = body.get("table_type", "source")
        if ttype not in ("source", "sink"):
            return "table_type must be 'source' or 'sink'"
        avail = connectors()
        reg = avail["sources"] if ttype == "source" else avail["sinks"]
        if body["connector"] not in reg:
            return (f"unknown {ttype} connector {body['connector']!r} "
                    f"(have {sorted(reg)})")
        fields = body.get("schema_fields") or []
        if ttype == "source" and not fields and body["connector"] not in (
                "impulse", "nexmark"):
            return "source connection tables need at least one schema field"
        from ..sql.compile import sql_type_to_dtype
        from ..sql.lexer import SqlError

        for f in fields:
            try:
                sql_type_to_dtype(str(f.get("type", "")))
            except SqlError as e:
                return f"field {f.get('name')!r}: {e}"
        return None

    def _test_conn_table(self, h):
        err = self._validate_conn_table(h._body())
        h._json(200, {"ok": err is None, "error": err})

    def _create_conn_table(self, h):
        body = h._body()
        err = self._validate_conn_table(body)
        if err:
            h._json(400, {"error": err})
            return
        if any(t["name"] == body["name"]
               for t in self.db.list_connection_tables()):
            h._json(409, {"error": f"connection table {body['name']!r} "
                          "already exists"})
            return
        profile_id = body.get("profile_id")
        config = dict(body.get("config") or {})
        if profile_id:
            prof = next((p for p in self.db.list_connection_profiles()
                         if p["id"] == profile_id), None)
            if prof is None:
                h._json(404, {"error": "unknown connection profile"})
                return
            # table options override the profile's shared options
            config = {**prof["config"], **config}
        tid = self.db.create_connection_table(
            body["name"], body["connector"], body.get("table_type", "source"),
            config, body.get("schema_fields") or [], profile_id)
        h._json(200, {"id": tid, "name": body["name"]})

    def _list_conn_tables(self, h):
        h._json(200, {"data": self.db.list_connection_tables()})

    def _delete_conn_table(self, h, tid):
        self.db.delete_connection_table(tid)
        h._json(200, {"deleted": tid})

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="api-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
