"""Typed Python client for the REST API.

Equivalent of crates/arroyo-openapi (the client generated from the API's
OpenAPI spec and used by the integration tests, integ/tests/api_tests.rs).
One method per spec operationId; test_openapi.py asserts full coverage of
the spec so the client cannot drift from the server.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Any, Optional


class ApiError(RuntimeError):
    def __init__(self, status: int, payload: Any):
        super().__init__(f"HTTP {status}: {payload}")
        self.status = status
        self.payload = payload


class ArroyoClient:
    """client = ArroyoClient("http://localhost:5115")"""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 auth_token: Optional[str] = None):
        from ..config import config

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # explicit token, else the cluster config's (api.auth-token)
        self.auth_token = auth_token or config().get("api.auth-token")

    # ------------------------------------------------------------- plumbing

    def _req(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method, headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = e.reason
            raise ApiError(e.code, payload) from None

    # ----------------------------------------------------------- operations

    def ping(self) -> dict:
        return self._req("GET", "/api/v1/ping")

    def validate_query(self, query: str) -> dict:
        return self._req("POST", "/api/v1/pipelines/validate", {"query": query})

    def create_pipeline(self, query: str, name: str = "pipeline",
                        parallelism: int = 1,
                        tenant: Optional[str] = None) -> dict:
        body = {"name": name, "query": query, "parallelism": parallelism}
        if tenant is not None:
            # keys the fleet's per-tenant admission queues and quotas
            body["tenant"] = tenant
        return self._req("POST", "/api/v1/pipelines", body)

    def list_pipelines(self) -> list[dict]:
        return self._req("GET", "/api/v1/pipelines")["data"]

    def get_pipeline(self, pipeline_id: str) -> dict:
        return self._req("GET", f"/api/v1/pipelines/{pipeline_id}")

    def delete_pipeline(self, pipeline_id: str) -> dict:
        return self._req("DELETE", f"/api/v1/pipelines/{pipeline_id}")

    def pipeline_jobs(self, pipeline_id: str) -> list[dict]:
        return self._req("GET", f"/api/v1/pipelines/{pipeline_id}/jobs")["data"]

    def pipeline_graph(self, pipeline_id: str) -> dict:
        """Planned dataflow DAG: {nodes: [...], edges: [...]}."""
        return self._req("GET", f"/api/v1/pipelines/{pipeline_id}/graph")

    def evolve_pipeline(self, pipeline_id: str, query: str) -> dict:
        """Live evolution (versioned redeploy): plan-diff the evolved SQL
        against the running plan.  Compatible changes drain the job behind a
        final checkpoint, carry proven state, and cut over blue/green; an
        incompatible change raises ApiError(400) with AR-series diagnostics
        and never touches the job."""
        return self._req("POST", f"/api/v1/pipelines/{pipeline_id}/evolve",
                         {"query": query})

    def list_jobs(self) -> list[dict]:
        return self._req("GET", "/api/v1/jobs")["data"]

    def get_job(self, job_id: str) -> dict:
        return self._req("GET", f"/api/v1/jobs/{job_id}")

    def patch_job(self, job_id: str, stop: Optional[str] = None,
                  parallelism: Optional[int] = None) -> dict:
        body: dict = {}
        if stop is not None:
            body["stop"] = stop
        if parallelism is not None:
            body["parallelism"] = parallelism
        return self._req("PATCH", f"/api/v1/jobs/{job_id}", body)

    def job_checkpoints(self, job_id: str) -> dict:
        return self._req("GET", f"/api/v1/jobs/{job_id}/checkpoints")

    def job_output(self, job_id: str) -> dict:
        return self._req("GET", f"/api/v1/jobs/{job_id}/output")

    def job_metrics(self, job_id: str) -> dict:
        return self._req("GET", f"/api/v1/jobs/{job_id}/metrics")

    def job_profile(self, job_id: str) -> dict:
        """Runtime cost profile: per-operator busy%, self-time, state
        rows/bytes, hot keys (what `arroyo_tpu explain` renders)."""
        return self._req("GET", f"/api/v1/jobs/{job_id}/profile")

    def job_traces(self, job_id: str, epoch: "Optional[int]" = None,
                   raw_events: bool = False) -> dict:
        """Checkpoint epoch traces: Chrome trace-event JSON by default,
        or the raw span events with raw_events=True."""
        q = []
        if epoch is not None:
            q.append(f"epoch={epoch}")
        if raw_events:
            q.append("format=events")
        suffix = f"?{'&'.join(q)}" if q else ""
        return self._req("GET", f"/api/v1/jobs/{job_id}/traces{suffix}")

    def job_events(self, job_id: str, level: Optional[str] = None,
                   since: Optional[float] = None,
                   after: Optional[int] = None) -> dict:
        """Structured job event feed (operator panics, restores, wedged
        epochs, health transitions); ``after`` is the seq cursor for
        incremental tailing."""
        q = []
        if level is not None:
            q.append(f"level={level}")
        if since is not None:
            q.append(f"since={since}")
        if after is not None:
            q.append(f"after={after}")
        suffix = f"?{'&'.join(q)}" if q else ""
        return self._req("GET", f"/api/v1/jobs/{job_id}/events{suffix}")

    def job_health(self, job_id: str) -> dict:
        """Job health (ok/degraded/critical) with per-rule observed value,
        threshold, and firing flag, plus the elastic autoscaler's rail
        state and last decision under the ``autoscaler`` key."""
        return self._req("GET", f"/api/v1/jobs/{job_id}/health")

    def job_fsck(self, job_id: str,
                 storage_url: "Optional[str]" = None) -> dict:
        """Offline checkpoint-chain verification: FS-series diagnostics
        over every epoch's artifacts; ``clean`` is False iff any ERROR
        finding (same predicate as the `fsck` CLI's exit code)."""
        suffix = ""
        if storage_url:
            from urllib.parse import quote

            suffix = f"?storage_url={quote(storage_url, safe='')}"
        return self._req("GET", f"/api/v1/jobs/{job_id}/fsck{suffix}")

    def fleet_status(self) -> dict:
        """Multi-tenant fleet snapshot: pool occupancy, per-tenant usage,
        and the admission queue with positions."""
        return self._req("GET", "/api/v1/fleet")

    def list_connectors(self) -> dict:
        return self._req("GET", "/api/v1/connectors")

    # ------------------------------------------- connection tables/profiles

    def create_connection_profile(self, name: str, connector: str,
                                  config: Optional[dict] = None) -> dict:
        return self._req("POST", "/api/v1/connection_profiles",
                         {"name": name, "connector": connector,
                          "config": config or {}})

    def list_connection_profiles(self) -> list[dict]:
        return self._req("GET", "/api/v1/connection_profiles")["data"]

    def delete_connection_profile(self, profile_id: str) -> dict:
        return self._req("DELETE", f"/api/v1/connection_profiles/{profile_id}")

    def create_connection_table(self, name: str, connector: str,
                                table_type: str = "source",
                                config: Optional[dict] = None,
                                schema_fields: Optional[list[dict]] = None,
                                profile_id: Optional[str] = None) -> dict:
        body: dict = {"name": name, "connector": connector,
                      "table_type": table_type, "config": config or {},
                      "schema_fields": schema_fields or []}
        if profile_id:
            body["profile_id"] = profile_id
        return self._req("POST", "/api/v1/connection_tables", body)

    def list_connection_tables(self) -> list[dict]:
        return self._req("GET", "/api/v1/connection_tables")["data"]

    def delete_connection_table(self, table_id: str) -> dict:
        return self._req("DELETE", f"/api/v1/connection_tables/{table_id}")

    def test_connection_table(self, **spec) -> dict:
        return self._req("POST", "/api/v1/connection_tables/test", spec)

    def create_udf(self, name: str, source: str, language: str = "cpp",
                   arg_dtypes: Optional[list[str]] = None,
                   return_dtype: str = "float64") -> dict:
        return self._req("POST", "/api/v1/udfs", {
            "name": name, "source": source, "language": language,
            "arg_dtypes": arg_dtypes or [], "return_dtype": return_dtype,
        })

    def list_udfs(self) -> dict:
        return self._req("GET", "/api/v1/udfs")

    def delete_udf(self, name: str) -> dict:
        return self._req("DELETE", f"/api/v1/udfs/{urllib.parse.quote(name)}")

    def register_node(self, node_id: str, addr: str, slots: int = 16) -> dict:
        return self._req("POST", "/api/v1/nodes/register",
                         {"node_id": node_id, "addr": addr, "slots": slots})

    def node_heartbeat(self, node_id: str) -> dict:
        return self._req("POST", f"/api/v1/nodes/{node_id}/heartbeat", {})

    def list_nodes(self) -> list[dict]:
        return self._req("GET", "/api/v1/nodes")["nodes"]

    # ----------------------------------------------------------- convenience

    def run_to_state(self, job_id: str, *states: str, timeout: float = 120.0):
        """Poll until the job reaches one of ``states`` (client-side analog
        of the integ tests' wait loops)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get_job(job_id)
            if job.get("state") in states:
                return job
            if job.get("state") == "Failed" and "Failed" not in states:
                raise RuntimeError(f"job failed: {job.get('failure_message')}")
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} never reached {states}")
