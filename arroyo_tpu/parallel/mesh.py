"""Device mesh construction for key-space sharding.

The reference scales by hash-partitioning the key space across subtasks
connected by a TCP shuffle (crates/arroyo-worker/src/network_manager.rs).
The TPU-native equivalent shards the key space across a 1-D device mesh
("data" axis); the repartition becomes an all_to_all over ICI inside a
shard_map'd step (see sharded_agg.py). Multi-host extends the same mesh over
DCN via jax.distributed — same program, bigger mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

KEY_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, axis: str = KEY_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def can_make(n_devices: int) -> bool:
    """True when the runtime has enough devices for an ``n_devices``-way
    mesh — the gate tests/bench use to skip (not fail) on small hosts."""
    return len(jax.devices()) >= int(n_devices)
