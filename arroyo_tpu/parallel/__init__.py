from .mesh import make_mesh  # noqa: F401
from .sharded_agg import ShardedAggregator  # noqa: F401
