from .mesh import can_make, make_mesh  # noqa: F401
from .sharded_agg import ShardedAggregator  # noqa: F401
