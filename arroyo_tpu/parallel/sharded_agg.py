"""Multi-chip keyed window aggregation: shard_map over a device mesh.

This replaces the reference's repartition shuffle (hash keys -> sort ->
slice per destination -> TCP, crates/arroyo-operator/src/context.rs:502-556 +
arroyo-worker/src/network_manager.rs) with an in-program exchange over ICI:

  per device (shard_map over the "data" mesh axis):
    1. sort_reduce the LOCAL micro-batch -> unique (bin, key) partials
       (pre-aggregation before the wire, like the reference's partial plans)
    2. owner = key-range map (same contiguous u64 ranges as
       arroyo-types/src/lib.rs:621 server_for_hash, so host and device
       agree on ownership)
    3. bucket partials into a fixed [n_dev, per_dest_cap] send buffer
       (sort by owner + rank-in-owner scatter, drop+count overflow)
    4. jax.lax.all_to_all over the mesh axis  <- the ICI shuffle
    5. sort_reduce the received rows (combining duplicates of the same
       (bin, key) arriving from different shards)
    6. probe_merge into this device's HBM hash-table shard

  The whole thing is ONE jitted XLA program per step: hashing, partials,
  exchange, and state update all fuse; XLA schedules the all_to_all on ICI.

State layout: every table array gains a leading mesh dimension
[n_dev, cap] sharded on the "data" axis; extraction (window close) is a
per-shard compaction producing [n_dev, emit_cap] outputs.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops.aggregate import _identity, drain_extract, probe_merge, sort_reduce
from .mesh import KEY_AXIS

_U64_MAX = (1 << 64) - 1


def _shard_map(fn, mesh, in_specs, out_specs):
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


class ShardedAggregator:
    """Key-space-sharded (bin, key) -> accumulators store over a mesh.

    update_sharded: [n_dev, B]-shaped per-device batches -> one fused step
    (local partials + all_to_all + merge). extract_all: per-shard compaction
    of closed bins, gathered to host.
    """

    def __init__(
        self,
        mesh,
        acc_kinds: Sequence[str],
        acc_dtypes: Sequence[np.dtype],
        cap: int = 65536,
        batch_cap: int = 8192,
        per_dest_cap: Optional[int] = None,
        max_probes: int = 64,
        emit_cap: int = 8192,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PS

        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.acc_kinds = tuple(acc_kinds)
        self.acc_dtypes = tuple(np.dtype(d) for d in acc_dtypes)
        self.cap = cap
        self.batch_cap = batch_cap
        # room for skew: by default each destination can receive up to half
        # the local batch from every source shard
        self.per_dest_cap = per_dest_cap or max(batch_cap // max(self.n_dev // 2, 1), 64)
        self.max_probes = max_probes
        self.emit_cap = emit_cap

        n_dev = self.n_dev
        dest_cap = self.per_dest_cap
        acc_kinds_t = self.acc_kinds
        acc_dtypes_t = self.acc_dtypes
        recv_cap = n_dev * dest_cap

        def unpack(state):
            keys_t, bins_t, occ_t, accs_t, oflow_t = state
            return (
                keys_t[0], bins_t[0], occ_t[0],
                tuple(a[0] for a in accs_t), oflow_t[0],
            )

        def pack(keys_t, bins_t, occ_t, accs_t, oflow_t):
            return (
                keys_t[None], bins_t[None], occ_t[None],
                tuple(a[None] for a in accs_t), oflow_t[None],
            )

        def local_step(state, key, bins, valid, vals):
            """Per-device body under shard_map (leading mesh dim is 1)."""
            keys_t, bins_t, occ_t, accs_t, oflow_t = unpack(state)
            key, bins, valid = key[0], bins[0], valid[0]
            vals = tuple(v[0] for v in vals)
            # --- 1. local pre-aggregation
            u_key, u_bin, active, u_accs = sort_reduce(
                acc_kinds_t, key, bins, valid, vals, batch_cap
            )
            # --- 2. owners via contiguous u64 ranges (matching host
            # servers_for_hashes, including its n == 1 special case —
            # _U64_MAX // 1 + 1 would overflow uint64)
            if n_dev == 1:
                owner = jnp.zeros(batch_cap, dtype=jnp.int32)
            else:
                range_size = jnp.uint64(_U64_MAX // n_dev + 1)
                owner = jnp.minimum(
                    u_key.astype(jnp.uint64) // range_size, jnp.uint64(n_dev - 1)
                ).astype(jnp.int32)
            owner = jnp.where(active, owner, n_dev)  # sentinel sorts last
            # --- 3. bucket into [n_dev * dest_cap] send buffers
            order = jnp.argsort(owner)
            o_s = owner[order]
            starts = jnp.searchsorted(o_s, jnp.arange(n_dev, dtype=jnp.int32))
            rank = jnp.arange(batch_cap, dtype=jnp.int32) - starts[
                jnp.clip(o_s, 0, n_dev - 1)
            ]
            sendable = (o_s < n_dev) & (rank < dest_cap)
            slot = jnp.where(sendable, o_s * dest_cap + rank, recv_cap)
            dropped = jnp.sum((o_s < n_dev) & (rank >= dest_cap), dtype=jnp.int32)

            def scatter(src, fill):
                buf = jnp.full((recv_cap,), fill, dtype=src.dtype)
                return buf.at[slot].set(src[order], mode="drop")

            s_key = scatter(u_key, jnp.int64(0))
            s_bin = scatter(u_bin, jnp.int32(0))
            s_valid = jnp.zeros((recv_cap,), dtype=bool).at[slot].set(
                sendable, mode="drop"
            )
            s_accs = tuple(
                scatter(u_accs[i], jnp.asarray(_identity(acc_kinds_t[i], acc_dtypes_t[i])))
                for i in range(len(acc_kinds_t))
            )

            # --- 4. ICI exchange
            def a2a(x):
                return jax.lax.all_to_all(
                    x.reshape(n_dev, dest_cap, *x.shape[1:]),
                    KEY_AXIS, split_axis=0, concat_axis=0,
                ).reshape(recv_cap, *x.shape[1:])

            r_key = a2a(s_key)
            r_bin = a2a(s_bin)
            r_valid = a2a(s_valid)
            r_accs = tuple(a2a(a) for a in s_accs)
            # --- 5. combine duplicates across source shards
            c_key, c_bin, c_active, c_accs = sort_reduce(
                acc_kinds_t, r_key, r_bin, r_valid, r_accs, recv_cap
            )
            # --- 6. merge into the local table shard
            (keys_t, bins_t, occ_t, accs_t), still_active = probe_merge(
                acc_kinds_t, (keys_t, bins_t, occ_t, accs_t),
                c_key, c_bin, c_active, c_accs, cap, max_probes,
            )
            oflow_t = oflow_t + jnp.sum(still_active, dtype=jnp.int32) + dropped
            return pack(keys_t, bins_t, occ_t, accs_t, oflow_t)

        spec_state = (
            PS(KEY_AXIS, None), PS(KEY_AXIS, None), PS(KEY_AXIS, None),
            tuple(PS(KEY_AXIS, None) for _ in self.acc_kinds), PS(KEY_AXIS),
        )
        spec_batch = PS(KEY_AXIS, None)
        self._step = jax.jit(
            _shard_map(
                local_step, mesh,
                in_specs=(spec_state, spec_batch, spec_batch, spec_batch,
                          tuple(spec_batch for _ in self.acc_kinds)),
                out_specs=spec_state,
            ),
            donate_argnums=0,
        )

        emit_cap_ = self.emit_cap

        def local_extract(state, emit_lo, emit_hi, free_below):
            keys_t, bins_t, occ_t, accs_t, oflow_t = unpack(state)
            emit_mask = occ_t & (bins_t >= emit_lo) & (bins_t < emit_hi)
            total = jnp.sum(emit_mask, dtype=jnp.int32)
            order = jnp.argsort(~emit_mask)
            sel = order[:emit_cap_]
            out_valid = emit_mask[sel]
            out_key = keys_t[sel]
            out_bin = bins_t[sel]
            out_accs = tuple(a[sel] for a in accs_t)
            free_mask = occ_t & (bins_t < free_below) & ~emit_mask
            emitted_free = out_valid & (out_bin < free_below)
            occ_t = occ_t & ~free_mask
            occ_t = occ_t.at[jnp.where(emitted_free, sel, cap)].set(False, mode="drop")
            return (
                pack(keys_t, bins_t, occ_t, accs_t, oflow_t),
                (out_key[None], out_bin[None], out_valid[None],
                 tuple(a[None] for a in out_accs), total[None]),
            )

        spec_out = (
            PS(KEY_AXIS, None), PS(KEY_AXIS, None), PS(KEY_AXIS, None),
            tuple(PS(KEY_AXIS, None) for _ in self.acc_kinds), PS(KEY_AXIS),
        )
        self._extract = jax.jit(
            _shard_map(
                local_extract, mesh,
                in_specs=(spec_state, PS(), PS(), PS()),
                out_specs=(spec_state, spec_out),
            ),
            donate_argnums=0,
        )
        self.state = self._init_state()

    def _init_state(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as PS

        shard = NamedSharding(self.mesh, PS(KEY_AXIS, None))
        shard1 = NamedSharding(self.mesh, PS(KEY_AXIS))
        n, cap = self.n_dev, self.cap
        return (
            jax.device_put(jnp.zeros((n, cap), dtype=jnp.int64), shard),
            jax.device_put(jnp.zeros((n, cap), dtype=jnp.int32), shard),
            jax.device_put(jnp.zeros((n, cap), dtype=bool), shard),
            tuple(
                jax.device_put(jnp.full((n, cap), _identity(k, d), dtype=d), shard)
                for k, d in zip(self.acc_kinds, self.acc_dtypes)
            ),
            jax.device_put(jnp.zeros((n,), dtype=jnp.int32), shard1),
        )

    # ------------------------------------------------------------------

    def update_sharded(self, key_i64, bins, valid, vals) -> None:
        """key_i64/bins/valid: [n_dev, batch_cap] (device-local rows);
        vals: one [n_dev, batch_cap] array per accumulator."""
        self.state = self._step(self.state, key_i64, bins, valid, tuple(vals))

    def extract_all(self, emit_lo: int, emit_hi: int, free_below: int):
        """Close bins across all shards; returns host (key_u64, bin, accs).
        Drains per emit_cap chunk until every shard is empty; shard outputs
        are [n_dev, emit_cap] and flattened before the shared drain logic."""

        def extract_once():
            self.state, (k, b, v, accs, total) = self._extract(
                self.state, np.int32(emit_lo), np.int32(emit_hi), np.int32(free_below)
            )
            return (
                np.asarray(k).reshape(-1),
                np.asarray(b).reshape(-1),
                np.asarray(v).reshape(-1),
                [np.asarray(a).reshape(-1) for a in accs],
                int(np.asarray(total).max()),
            )

        out = drain_extract(extract_once, self.emit_cap, self.acc_kinds,
                            self.acc_dtypes, emit_lo, free_below)
        overflow = int(np.asarray(self.state[4]).sum())
        if overflow > 0:
            raise RuntimeError(
                f"sharded aggregate overflow ({overflow} entries dropped) — raise "
                f"table capacity or per_dest_cap"
            )
        return out
