"""Multi-chip keyed window aggregation: shard_map over a device mesh.

This replaces the reference's repartition shuffle (hash keys -> sort ->
slice per destination -> TCP, crates/arroyo-operator/src/context.rs:502-556 +
arroyo-worker/src/network_manager.rs) with an in-program exchange over ICI:

  per device (shard_map over the "data" mesh axis):
    1. sort_reduce the LOCAL micro-batch -> unique (bin, key) partials
       (pre-aggregation before the wire, like the reference's partial plans)
    2. owner = key-range map (same contiguous u64 ranges as
       arroyo-types/src/lib.rs:621 server_for_hash, so host and device
       agree on ownership)
    3. bucket partials into a fixed [n_dev, per_dest_cap] send buffer
       (sort by owner + rank-in-owner scatter); partials past a
       destination's cap are NOT dropped — they stay resident on the
       producing shard (skew tolerance: window close combines across
       shards on host, so non-owner residency is harmless)
    4. jax.lax.all_to_all over the mesh axis  <- the ICI shuffle
    5. sort_reduce the received rows + the kept-local overflow together
    6. probe_merge into this device's HBM hash-table shard; rows the table
       cannot place (probe exhaustion / table pressure) append into a
       per-shard HBM spill buffer instead of erroring — the sharded
       mirror of the single-chip host-spill tier (SURVEY §7 hard-part 1)

  The whole thing is ONE jitted XLA program per step: hashing, partials,
  exchange, and state update all fuse; XLA schedules the all_to_all on ICI.
  The overflow counter trips only when even the spill buffer is full.

State layout: every table array gains a leading mesh dimension
[n_dev, cap] sharded on the "data" axis; extraction (window close) is a
per-shard compaction producing [n_dev, emit_cap] outputs, combined with the
spill rows on host.

The host-facing surface (update / extract / extract_start / scan_range /
free_bins_below / snapshot / restore) matches SlotAggregator so window
operators construct either interchangeably (windows/tumbling.py mesh mode).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..ops.aggregate import (
    _identity,
    combine_by_key_bin,
    drain_extract,
    probe_merge,
    sort_reduce,
)
from .mesh import KEY_AXIS

_U64_MAX = (1 << 64) - 1

# process-wide dispatch counters: how many jitted step programs ran, split
# by entry path. bench.py --mesh-ab reads these to PROVE "one jitted call
# per micro-batch step" from the artifact (a fused step is one program for
# segment prefix + exchange + merge; a host step is one program for
# exchange + merge with the prefix done on host).
_DISPATCH = {"host_steps": 0, "fused_steps": 0}


def dispatch_counts() -> dict:
    return dict(_DISPATCH)


def reset_dispatch_counts() -> None:
    for k in _DISPATCH:
        _DISPATCH[k] = 0


def _shard_map(fn, mesh, in_specs, out_specs):
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


class _ReadyHandle:
    """Synchronous stand-in for SlotExtractHandle: the sharded close gathers
    on the spot (the all_to_all path has no per-region async transport yet),
    so the pipelined emission path sees an always-ready handle."""

    def __init__(self, value):
        self._value = value

    def is_ready(self) -> bool:
        return True

    def result(self):
        return self._value


class ShardedAggregator:
    """Key-space-sharded (bin, key) -> accumulators store over a mesh.

    update_sharded: [n_dev, B]-shaped per-device batches -> one fused step
    (local partials + all_to_all + merge). extract_all: per-shard compaction
    of closed bins, gathered to host. update/extract/snapshot/restore: the
    host-row surface shared with SlotAggregator.
    """

    backend = "jax"

    def __init__(
        self,
        mesh,
        acc_kinds: Sequence[str],
        acc_dtypes: Sequence[np.dtype],
        cap: int = 65536,
        batch_cap: int = 8192,
        per_dest_cap: Optional[int] = None,
        max_probes: int = 64,
        emit_cap: int = 8192,
        spill_cap: int = 2048,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PS

        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.acc_kinds = tuple(acc_kinds)
        self.acc_dtypes = tuple(np.dtype(d) for d in acc_dtypes)
        self.cap = cap
        self.batch_cap = batch_cap
        # room for skew: by default each destination can receive up to half
        # the local batch from every source shard
        self.per_dest_cap = per_dest_cap or max(batch_cap // max(self.n_dev // 2, 1), 64)
        self.max_probes = max_probes
        self.emit_cap = emit_cap
        self.spill_cap = spill_cap

        n_dev = self.n_dev
        dest_cap = self.per_dest_cap
        acc_kinds_t = self.acc_kinds
        acc_dtypes_t = self.acc_dtypes
        recv_cap = n_dev * dest_cap
        spill_cap_ = spill_cap

        def unpack(state):
            (keys_t, bins_t, occ_t, accs_t, oflow_t,
             sp_key, sp_bin, sp_fill, sp_accs) = state
            return (
                keys_t[0], bins_t[0], occ_t[0],
                tuple(a[0] for a in accs_t), oflow_t[0],
                sp_key[0], sp_bin[0], sp_fill[0],
                tuple(a[0] for a in sp_accs),
            )

        def pack(keys_t, bins_t, occ_t, accs_t, oflow_t,
                 sp_key, sp_bin, sp_fill, sp_accs):
            return (
                keys_t[None], bins_t[None], occ_t[None],
                tuple(a[None] for a in accs_t), oflow_t[None],
                sp_key[None], sp_bin[None], sp_fill[None],
                tuple(a[None] for a in sp_accs),
            )

        def exchange_merge(parts, key, bins, valid, vals, blen):
            """The per-device exchange+merge body (steps 1-7), parametrized
            by the STATIC per-shard row count ``blen`` so the same code
            serves both the host-fed step (blen = batch_cap) and the fused
            segment step (blen = the traced prefix's padded shard length).
            ``parts`` is the unpacked (leading-dim-stripped) state tuple;
            returns the updated parts."""
            (keys_t, bins_t, occ_t, accs_t, oflow_t,
             sp_key, sp_bin, sp_fill, sp_accs) = parts
            # --- 1. local pre-aggregation
            u_key, u_bin, active, u_accs = sort_reduce(
                acc_kinds_t, key, bins, valid, vals, blen
            )
            # --- 2. owners via contiguous u64 ranges (matching host
            # servers_for_hashes, including its n == 1 special case —
            # _U64_MAX // 1 + 1 would overflow uint64)
            if n_dev == 1:
                owner = jnp.zeros(blen, dtype=jnp.int32)
            else:
                range_size = jnp.uint64(_U64_MAX // n_dev + 1)
                owner = jnp.minimum(
                    u_key.astype(jnp.uint64) // range_size, jnp.uint64(n_dev - 1)
                ).astype(jnp.int32)
            owner = jnp.where(active, owner, n_dev)  # sentinel sorts last
            # --- 3. bucket into [n_dev * dest_cap] send buffers
            order = jnp.argsort(owner)
            o_s = owner[order]
            starts = jnp.searchsorted(o_s, jnp.arange(n_dev, dtype=jnp.int32))
            rank = jnp.arange(blen, dtype=jnp.int32) - starts[
                jnp.clip(o_s, 0, n_dev - 1)
            ]
            sendable = (o_s < n_dev) & (rank < dest_cap)
            # skew: partials past the destination cap stay LOCAL (merged into
            # this shard's table below); close-time host combine makes
            # non-owner residency correct, so hot keys degrade, not crash
            keep_local = (o_s < n_dev) & (rank >= dest_cap)
            slot = jnp.where(sendable, o_s * dest_cap + rank, recv_cap)

            def scatter(src, fill):
                buf = jnp.full((recv_cap,), fill, dtype=src.dtype)
                return buf.at[slot].set(src[order], mode="drop")

            s_key = scatter(u_key, jnp.int64(0))
            s_bin = scatter(u_bin, jnp.int32(0))
            s_valid = jnp.zeros((recv_cap,), dtype=bool).at[slot].set(
                sendable, mode="drop"
            )
            s_accs = tuple(
                scatter(u_accs[i], jnp.asarray(_identity(acc_kinds_t[i], acc_dtypes_t[i])))
                for i in range(len(acc_kinds_t))
            )

            # --- 4. ICI exchange
            def a2a(x):
                return jax.lax.all_to_all(
                    x.reshape(n_dev, dest_cap, *x.shape[1:]),
                    KEY_AXIS, split_axis=0, concat_axis=0,
                ).reshape(recv_cap, *x.shape[1:])

            r_key = a2a(s_key)
            r_bin = a2a(s_bin)
            r_valid = a2a(s_valid)
            r_accs = tuple(a2a(a) for a in s_accs)
            # --- 5. combine received rows + kept-local overflow together
            m_key = jnp.concatenate([r_key, u_key[order]])
            m_bin = jnp.concatenate([r_bin, u_bin[order]])
            m_valid = jnp.concatenate([r_valid, keep_local])
            m_accs = tuple(
                jnp.concatenate([r_accs[i], u_accs[i][order]])
                for i in range(len(acc_kinds_t))
            )
            c_key, c_bin, c_active, c_accs = sort_reduce(
                acc_kinds_t, m_key, m_bin, m_valid, m_accs, recv_cap + blen
            )
            # --- 6. merge into the local table shard
            (keys_t, bins_t, occ_t, accs_t), still_active = probe_merge(
                acc_kinds_t, (keys_t, bins_t, occ_t, accs_t),
                c_key, c_bin, c_active, c_accs, cap, max_probes,
            )
            # --- 7. table-pressure spill: unplaced rows append into the
            # per-shard HBM spill buffer; only spill-buffer exhaustion counts
            # as overflow
            sidx = sp_fill + jnp.cumsum(still_active.astype(jnp.int32)) - 1
            ok = still_active & (sidx < spill_cap_)
            pos = jnp.where(ok, sidx, spill_cap_)
            sp_key = sp_key.at[pos].set(c_key, mode="drop")
            sp_bin = sp_bin.at[pos].set(c_bin, mode="drop")
            sp_accs = tuple(
                sp_accs[i].at[pos].set(c_accs[i], mode="drop")
                for i in range(len(acc_kinds_t))
            )
            n_spilled = jnp.sum(ok, dtype=jnp.int32)
            n_lost = jnp.sum(still_active, dtype=jnp.int32) - n_spilled
            sp_fill = jnp.minimum(sp_fill + n_spilled, spill_cap_)
            oflow_t = oflow_t + n_lost
            return (keys_t, bins_t, occ_t, accs_t, oflow_t,
                    sp_key, sp_bin, sp_fill, sp_accs)

        def local_step(state, key, bins, valid, vals):
            """Per-device body under shard_map (leading mesh dim is 1)."""
            parts = unpack(state)
            key, bins, valid = key[0], bins[0], valid[0]
            vals = tuple(v[0] for v in vals)
            return pack(*exchange_merge(parts, key, bins, valid, vals,
                                        batch_cap))

        def spec_state():
            return (
                PS(KEY_AXIS, None), PS(KEY_AXIS, None), PS(KEY_AXIS, None),
                tuple(PS(KEY_AXIS, None) for _ in self.acc_kinds), PS(KEY_AXIS),
                PS(KEY_AXIS, None), PS(KEY_AXIS, None), PS(KEY_AXIS),
                tuple(PS(KEY_AXIS, None) for _ in self.acc_kinds),
            )

        spec_batch = PS(KEY_AXIS, None)
        self._step = jax.jit(
            _shard_map(
                local_step, mesh,
                in_specs=(spec_state(), spec_batch, spec_batch, spec_batch,
                          tuple(spec_batch for _ in self.acc_kinds)),
                out_specs=spec_state(),
            ),
            donate_argnums=0,
        )
        # fused-segment hook points (fused_step): the exchange+merge body,
        # the state (un)packers, and the state/batch specs
        self._exchange_merge = exchange_merge
        self._unpack = unpack
        self._pack = pack
        self._spec_state = spec_state
        self._spec_batch = spec_batch
        # observability (mesh_stats -> arroyo_mesh_* series): rows fed
        # through the keyed exchange, and the current spill-buffer residency
        # (refreshed opportunistically wherever sp_fill is already on host —
        # never a dedicated device sync)
        self.exchange_rows = 0
        self.overflow_rows = 0

        emit_cap_ = self.emit_cap

        def local_extract(state, emit_lo, emit_hi, free_below):
            (keys_t, bins_t, occ_t, accs_t, oflow_t,
             sp_key, sp_bin, sp_fill, sp_accs) = unpack(state)
            emit_mask = occ_t & (bins_t >= emit_lo) & (bins_t < emit_hi)
            total = jnp.sum(emit_mask, dtype=jnp.int32)
            order = jnp.argsort(~emit_mask)
            sel = order[:emit_cap_]
            out_valid = emit_mask[sel]
            out_key = keys_t[sel]
            out_bin = bins_t[sel]
            out_accs = tuple(a[sel] for a in accs_t)
            free_mask = occ_t & (bins_t < free_below) & ~emit_mask
            emitted_free = out_valid & (out_bin < free_below)
            occ_t = occ_t & ~free_mask
            occ_t = occ_t.at[jnp.where(emitted_free, sel, cap)].set(False, mode="drop")
            return (
                pack(keys_t, bins_t, occ_t, accs_t, oflow_t,
                     sp_key, sp_bin, sp_fill, sp_accs),
                (out_key[None], out_bin[None], out_valid[None],
                 tuple(a[None] for a in out_accs), total[None]),
            )

        spec_out = (
            PS(KEY_AXIS, None), PS(KEY_AXIS, None), PS(KEY_AXIS, None),
            tuple(PS(KEY_AXIS, None) for _ in self.acc_kinds), PS(KEY_AXIS),
        )
        self._extract = jax.jit(
            _shard_map(
                local_extract, mesh,
                in_specs=(spec_state(), PS(), PS(), PS()),
                out_specs=(spec_state(), spec_out),
            ),
            donate_argnums=0,
        )
        self.state = self._init_state()

    def _init_state(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as PS

        shard = NamedSharding(self.mesh, PS(KEY_AXIS, None))
        shard1 = NamedSharding(self.mesh, PS(KEY_AXIS))
        n, cap, sc = self.n_dev, self.cap, self.spill_cap
        return (
            jax.device_put(jnp.zeros((n, cap), dtype=jnp.int64), shard),
            jax.device_put(jnp.zeros((n, cap), dtype=jnp.int32), shard),
            jax.device_put(jnp.zeros((n, cap), dtype=bool), shard),
            tuple(
                jax.device_put(jnp.full((n, cap), _identity(k, d), dtype=d), shard)
                for k, d in zip(self.acc_kinds, self.acc_dtypes)
            ),
            jax.device_put(jnp.zeros((n,), dtype=jnp.int32), shard1),
            jax.device_put(jnp.zeros((n, sc), dtype=jnp.int64), shard),
            jax.device_put(jnp.zeros((n, sc), dtype=jnp.int32), shard),
            jax.device_put(jnp.zeros((n,), dtype=jnp.int32), shard1),
            tuple(
                jax.device_put(jnp.full((n, sc), _identity(k, d), dtype=d), shard)
                for k, d in zip(self.acc_kinds, self.acc_dtypes)
            ),
        )

    # ------------------------------------------------------- sharded surface

    def update_sharded(self, key_i64, bins, valid, vals) -> None:
        """key_i64/bins/valid: [n_dev, batch_cap] (device-local rows);
        vals: one [n_dev, batch_cap] array per accumulator."""
        _DISPATCH["host_steps"] += 1
        self.state = self._step(self.state, key_i64, bins, valid, tuple(vals))

    # ------------------------------------------------------- fused segments

    def fused_step(self, prefix_fn, n_inputs: int, n_aux: int):
        """Build ONE shard_map'd jitted program fusing a traced segment
        prefix (engine/segment.py mesh path) with this store's exchange+
        merge: per-shard projection/key-hash -> owner bucketing ->
        all_to_all -> sort_reduce/probe_merge, with no host round trip
        between projection and state update.

        ``prefix_fn(arrays, valid, base_bin, ontime) -> (key_i64, bins_i32,
        insert_valid, vals_tuple, aux_tuple)`` runs per shard on
        [P_dev]-length arrays (``n_inputs`` of them); ``aux_tuple`` is a
        flat tuple of ``n_aux`` scalars (watermark max/count pairs over
        PRE-late rows). Row validity (padding tail) is computed HERE from
        the global row count so the prefix stays mesh-agnostic.

        Returns ``step(state, n, base_bin, ontime2d, *arrays2d) ->
        (state', aux_shards)`` — jitted, state donated, aux gathered as
        one [n_dev] array per scalar. The caller runs it via
        ``update_fused`` so counters stay correct.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as PS

        exchange = self._exchange_merge
        unpack, pack = self._unpack, self._pack

        def local(state, n, base_bin, ontime, *arrays):
            parts = unpack(state)
            ontime = ontime[0]
            arrays = tuple(a[0] for a in arrays)
            pd = ontime.shape[0]
            # this shard owns global rows [d*pd, (d+1)*pd); rows >= n are
            # the padding tail (dtype pinned: LR304)
            row0 = jax.lax.axis_index(KEY_AXIS).astype(jnp.int64) * pd
            valid = (row0 + jnp.arange(pd, dtype=jnp.int64)) < n
            key_i64, bins, ins_valid, vals, aux = prefix_fn(
                arrays, valid, base_bin, ontime)
            parts = exchange(parts, key_i64, bins, ins_valid, vals, pd)
            return pack(*parts), tuple(jnp.asarray(a)[None] for a in aux)

        sb = self._spec_batch
        step = jax.jit(
            _shard_map(
                local, self.mesh,
                in_specs=(self._spec_state(), PS(), PS(), sb)
                + tuple(sb for _ in range(n_inputs)),
                out_specs=(self._spec_state(),
                           tuple(PS(KEY_AXIS) for _ in range(n_aux))),
            ),
            donate_argnums=0,
        )
        return step

    def update_fused(self, step, n: int, base_bin: int, ontime, arrays):
        """Run one fused segment+exchange program built by ``fused_step``;
        ``ontime``/``arrays`` are [n_dev, P_dev]-shaped. Returns the
        per-shard aux arrays ([n_dev] each, host numpy)."""
        _DISPATCH["fused_steps"] += 1
        self.exchange_rows += int(n)
        self.state, aux = step(self.state, np.int64(n), np.int64(base_bin),
                               ontime, *arrays)
        return [np.asarray(a) for a in aux]

    def mesh_stats(self) -> dict:
        """Counters behind the arroyo_mesh_* series (obs/profile.py reads
        this through the operator's mesh_stats hook)."""
        return {"exchange_rows": self.exchange_rows,
                "overflow_rows": self.overflow_rows}

    def _drain_spill(self, emit_lo: int, emit_hi: int, free_below: int):
        """Host-side spill-buffer drain: gather the (small) per-shard spill
        arrays, emit rows in range, drop rows below free_below, write the
        compacted remainder back (sharded)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as PS

        (keys_t, bins_t, occ_t, accs_t, oflow_t,
         sp_key, sp_bin, sp_fill, sp_accs) = self.state
        fill = np.asarray(sp_fill)
        if int(fill.sum()) == 0:
            self.overflow_rows = 0
            return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32),
                    [np.empty(0, dtype=d) for d in self.acc_dtypes])
        k = np.asarray(sp_key)
        b = np.asarray(sp_bin)
        accs = [np.asarray(a) for a in sp_accs]
        n, sc = self.n_dev, self.spill_cap
        in_fill = np.arange(sc)[None, :] < fill[:, None]
        emit = in_fill & (b >= emit_lo) & (b < emit_hi)
        keep = in_fill & ~(b < free_below)
        out = (k[emit].view(np.uint64), b[emit].astype(np.int32),
               [a[emit] for a in accs])
        # compact kept rows per shard and write back
        new_k = np.zeros((n, sc), dtype=np.int64)
        new_b = np.zeros((n, sc), dtype=np.int32)
        new_accs = [np.full((n, sc), _identity(kk, d), dtype=d)
                    for kk, d in zip(self.acc_kinds, self.acc_dtypes)]
        new_fill = np.zeros(n, dtype=np.int32)
        for d_i in range(n):
            sel = np.flatnonzero(keep[d_i])
            m = len(sel)
            new_fill[d_i] = m
            new_k[d_i, :m] = k[d_i, sel]
            new_b[d_i, :m] = b[d_i, sel]
            for j in range(len(accs)):
                new_accs[j][d_i, :m] = accs[j][d_i, sel]
        self.overflow_rows = int(new_fill.sum())
        shard = NamedSharding(self.mesh, PS(KEY_AXIS, None))
        shard1 = NamedSharding(self.mesh, PS(KEY_AXIS))
        self.state = (
            keys_t, bins_t, occ_t, accs_t, oflow_t,
            jax.device_put(new_k, shard),
            jax.device_put(new_b, shard),
            jax.device_put(new_fill, shard1),
            tuple(jax.device_put(a, shard) for a in new_accs),
        )
        return out

    def extract_all(self, emit_lo: int, emit_hi: int, free_below: int):
        """Close bins across all shards; returns host (key_u64, bin, accs).
        Drains per emit_cap chunk until every shard is empty; shard outputs
        are [n_dev, emit_cap] and flattened before the shared drain logic.
        Spill-buffer rows for the range are combined in on host."""

        def extract_once():
            self.state, (k, b, v, accs, total) = self._extract(
                self.state, np.int32(emit_lo), np.int32(emit_hi), np.int32(free_below)
            )
            return (
                np.asarray(k).reshape(-1),
                np.asarray(b).reshape(-1),
                np.asarray(v).reshape(-1),
                [np.asarray(a).reshape(-1) for a in accs],
                int(np.asarray(total).max()),
            )

        out = drain_extract(extract_once, self.emit_cap, self.acc_kinds,
                            self.acc_dtypes, emit_lo, free_below)
        sk, sb, saccs = self._drain_spill(emit_lo, emit_hi, free_below)
        if len(sk):
            out = combine_by_key_bin(
                self.acc_kinds,
                np.concatenate([out[0], sk]),
                np.concatenate([out[1], sb]),
                [np.concatenate([a, s]) for a, s in zip(out[2], saccs)],
            )
        overflow = int(np.asarray(self.state[4]).sum())
        if overflow > 0:
            raise RuntimeError(
                f"sharded aggregate overflow ({overflow} entries lost: table and "
                f"spill buffer both full) — raise table capacity or spill_cap"
            )
        return out

    # ---------------------------------------------------- SlotAggregator API

    def _distribute(self, key_i64, bins, vals):
        """Round-robin host rows into [n_dev, batch_cap] chunks (initial
        placement is arbitrary — the in-program all_to_all re-routes by key
        ownership, like the reference's source->shuffle edge)."""
        n = len(key_i64)
        n_dev, B = self.n_dev, self.batch_cap
        per_step = n_dev * B
        for lo in range(0, n, per_step):
            hi = min(lo + per_step, n)
            m = hi - lo
            k = np.zeros((n_dev, B), dtype=np.int64)
            b = np.zeros((n_dev, B), dtype=np.int32)
            valid = np.zeros((n_dev, B), dtype=bool)
            vs = [np.full((n_dev, B), _identity(kk, d), dtype=d)
                  for kk, d in zip(self.acc_kinds, self.acc_dtypes)]
            rows = np.arange(lo, hi)
            dev = (rows - lo) % n_dev
            pos = (rows - lo) // n_dev
            k[dev, pos] = key_i64[lo:hi]
            b[dev, pos] = bins[lo:hi]
            valid[dev, pos] = True
            for j, v in enumerate(vals):
                vs[j][dev, pos] = v[lo:hi]
            yield k, b, valid, vs

    def update(self, key_u64, bins, vals) -> None:
        self.exchange_rows += len(key_u64)
        key_i64 = np.ascontiguousarray(key_u64, dtype=np.uint64).view(np.int64)
        bins = np.asarray(bins, dtype=np.int32)
        vals = [np.asarray(v, dtype=d) for v, d in zip(vals, self.acc_dtypes)]
        for k, b, valid, vs in self._distribute(key_i64, bins, vals):
            self.update_sharded(k, b, valid, vs)

    def extract(self, emit_lo: int, emit_hi: int, free_below: int):
        return self.extract_all(emit_lo, emit_hi, free_below)

    def extract_start(self, emit_lo: int, emit_hi: int, free_below: int):
        return _ReadyHandle(self.extract_all(emit_lo, emit_hi, free_below))

    def free_bins_below(self, below: int) -> None:
        # empty emit range: frees every table + spill row with bin < below
        self.extract_all(below, below, below)

    def scan_range(self, emit_lo: int, emit_hi: int):
        k, b, accs = self.snapshot()
        sel = (b >= emit_lo) & (b < emit_hi)
        return k[sel], b[sel], [a[sel] for a in accs]

    def snapshot(self):
        """Exact non-destructive state readout: gather the sharded table +
        spill buffers and combine on host (checkpoint path; off the hot
        loop, so a full [n_dev, cap] gather is acceptable)."""
        (keys_t, bins_t, occ_t, accs_t, _oflow_t,
         sp_key, sp_bin, sp_fill, sp_accs) = self.state
        occ = np.asarray(occ_t)
        keys = np.asarray(keys_t)[occ].view(np.uint64)
        bins = np.asarray(bins_t)[occ].astype(np.int32)
        accs = [np.asarray(a)[occ] for a in accs_t]
        fill = np.asarray(sp_fill)
        self.overflow_rows = int(fill.sum())
        if int(fill.sum()):
            in_fill = np.arange(self.spill_cap)[None, :] < fill[:, None]
            keys = np.concatenate([keys, np.asarray(sp_key)[in_fill].view(np.uint64)])
            bins = np.concatenate([bins, np.asarray(sp_bin)[in_fill].astype(np.int32)])
            accs = [np.concatenate([a, np.asarray(s)[in_fill]])
                    for a, s in zip(accs, sp_accs)]
        if not len(keys):
            return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32),
                    [np.empty(0, dtype=d) for d in self.acc_dtypes])
        return combine_by_key_bin(self.acc_kinds, keys, bins, accs)

    def restore(self, key_u64, bins, accs) -> None:
        """Merge snapshotted partials back in: the sharded kernel combines
        count like sum (partials arrive as values), so update() is the
        correct merge path — unlike SlotAggregator's constant-increment hot
        step, no separate merge mode is needed."""
        self.state = self._init_state()
        self.update(np.asarray(key_u64, dtype=np.uint64),
                    np.asarray(bins, dtype=np.int32), accs)
