"""Shared resilience layer: backoff, deadlines, budgets, circuit breaking.

Every retry loop in the engine goes through this module instead of hand-rolled
``time.sleep`` loops (reference: the Rust engine leans on tower/backoff
middleware plus object_store's built-in retry policy; here the equivalent is
one shared policy object so storage, connectors, and the control plane all
back off the same way and chaos tests can reason about recovery timing).

Pieces:

- ``RetryPolicy``       declarative knobs (attempts, delays, deadline),
                        loadable from config (``retry.*`` keys).
- ``Backoff``           the delay sequence as an object, for loops that
                        cannot be phrased as a retried callable (e.g. the
                        Kinesis per-shard sweep, partial PutRecords retries).
- ``retry_call``        run a callable under a policy, retrying transient
                        failures with decorrelated jitter.
- ``RetryBudget``       token bucket shared across call sites so a broken
                        dependency cannot multiply load.
- ``CircuitBreaker``    fail-fast after repeated failures, with a cooldown
                        half-open probe.

Fault-injection note: ``arroyo_tpu.faults`` raises ``InjectedFault`` (marked
transient) at instrumented call sites; ``default_transient`` classifies those
as retryable, which is how the chaos suite proves "transient storage fault
recovers without job restart".
"""

from __future__ import annotations

import logging
import random
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

_log = logging.getLogger("arroyo_tpu.retry")


class CircuitOpenError(RuntimeError):
    """Raised instead of attempting a call while a circuit is open."""


def default_transient(exc: BaseException) -> bool:
    """Conservative cross-backend classification of retryable failures."""
    # injected chaos faults declare themselves
    transient = getattr(exc, "transient", None)
    if transient is not None:
        return bool(transient)
    if isinstance(exc, (ConnectionError, TimeoutError, socket.timeout)):
        return True
    import urllib.error

    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in (408, 429) or exc.code >= 500
    if isinstance(exc, urllib.error.URLError):
        return True  # DNS / refused / reset — all worth one more try
    # botocore-style errors carry a response dict
    resp = getattr(exc, "response", None)
    if isinstance(resp, dict):
        code = str(resp.get("Error", {}).get("Code", ""))
        status = resp.get("ResponseMetadata", {}).get("HTTPStatusCode") or 0
        return code in ("SlowDown", "Throttling", "ThrottlingException",
                        "RequestTimeout", "InternalError",
                        "ServiceUnavailable") or int(status) >= 500
    return False


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter, bounded by attempts AND an
    optional wall-clock deadline across all attempts."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # fraction of each delay that is randomized away
    deadline_s: Optional[float] = None

    @classmethod
    def from_config(cls, prefix: str = "retry") -> "RetryPolicy":
        from ..config import config

        c = config()

        def g(key, default):
            v = c.get(f"{prefix}.{key}")
            return default if v is None else v

        return cls(
            max_attempts=int(g("max-attempts", cls.max_attempts)),
            base_delay_s=float(g("base-delay-ms", cls.base_delay_s * 1000)) / 1000,
            max_delay_s=float(g("max-delay-ms", cls.max_delay_s * 1000)) / 1000,
            multiplier=float(g("multiplier", cls.multiplier)),
            jitter=float(g("jitter", cls.jitter)),
            deadline_s=(float(g("deadline-ms", -1)) / 1000) if g("deadline-ms", None) else None,
        )


class Backoff:
    """The policy's delay sequence as a stateful object. Loops that interleave
    other work between failures (shard sweeps, partial batch retries) use this
    directly; ``reset()`` on success."""

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 rng: Optional[random.Random] = None):
        self.policy = policy or RetryPolicy()
        self.rng = rng or random.Random()
        self.attempts = 0
        self._started = time.monotonic()

    def reset(self) -> None:
        self.attempts = 0
        self._started = time.monotonic()

    def next_delay(self) -> float:
        """Delay to sleep before the next attempt (0 jitters downward)."""
        p = self.policy
        # clamp the exponent: retry-forever loops (max_attempts ~ 2**30)
        # would overflow float at multiplier**1024 long before max_delay
        # stops mattering
        exp = min(self.attempts, 64)
        raw = min(p.base_delay_s * (p.multiplier ** exp), p.max_delay_s)
        self.attempts += 1
        if p.jitter:
            raw -= self.rng.random() * p.jitter * raw
        return max(raw, 0.0)

    def exhausted(self) -> bool:
        p = self.policy
        if self.attempts >= p.max_attempts:
            return True
        if p.deadline_s is not None and time.monotonic() - self._started >= p.deadline_s:
            return True
        return False

    def delays(self) -> Iterable[float]:
        while not self.exhausted():
            yield self.next_delay()


class RetryBudget:
    """Token bucket spent by retries (not first attempts). When a dependency
    is hard-down, every caller burning its full local retry schedule
    multiplies load; a shared budget lets the first few callers retry and
    fails the rest fast."""

    def __init__(self, capacity: float = 10.0, refill_per_s: float = 1.0):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._tokens = float(capacity)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._last) * self.refill_per_s)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class CircuitBreaker:
    """Consecutive-failure breaker. Closed -> open after ``threshold``
    failures; open calls raise ``CircuitOpenError`` immediately until
    ``cooldown_s`` passes, then one probe is allowed (half-open)."""

    def __init__(self, threshold: int = 6, cooldown_s: float = 5.0,
                 name: str = "circuit"):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.name = name
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at >= self.cooldown_s:
                return True  # half-open probe
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._failures >= self.threshold and self._opened_at is None:
                self._opened_at = time.monotonic()
                _log.warning("circuit %s opened after %d consecutive failures",
                             self.name, self._failures)

    @property
    def open(self) -> bool:
        return not self.allow()

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    retry_on: Callable[[BaseException], bool] = default_transient,
    description: str = "",
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    budget: Optional[RetryBudget] = None,
    breaker: Optional[CircuitBreaker] = None,
    rng: Optional[random.Random] = None,
    **kwargs,
):
    """Call ``fn`` retrying transient failures per ``policy``.

    Non-transient failures (per ``retry_on``) raise immediately. On retry
    exhaustion the LAST failure raises — callers see the real error, not a
    wrapper. ``breaker``/``budget`` compose: an open breaker fails fast, a
    drained budget turns the first failure terminal.
    """
    if breaker is not None and not breaker.allow():
        raise CircuitOpenError(
            f"{breaker.name} open; refusing {description or getattr(fn, '__name__', 'call')}")
    backoff = Backoff(policy, rng=rng)
    while True:
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 - classified below
            if not retry_on(e):
                # application-level error (404, FileNotFoundError, logic
                # bug): not a dependency-health signal, the breaker must
                # not count it
                raise
            if backoff.exhausted() or (budget is not None
                                       and not budget.try_spend()):
                if breaker is not None:
                    breaker.record_failure()
                raise
            delay = backoff.next_delay()
            if on_retry is not None:
                on_retry(e, backoff.attempts, delay)
            _log.debug("retrying %s after %s (attempt %d, sleeping %.3fs)",
                       description or getattr(fn, "__name__", "call"), e,
                       backoff.attempts, delay)
            sleep(delay)
            continue
        if breaker is not None:
            breaker.record_success()
        return result
