"""Shared utilities (host-runtime helpers)."""

from .arrow import ensure_parquet_initialized

__all__ = ["ensure_parquet_initialized"]
