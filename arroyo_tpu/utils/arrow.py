"""pyarrow compatibility shims.

This image's pyarrow build segfaults (deterministically, inside
parquet read/write) when its parquet machinery is *first* initialized from a
non-main thread and later used from another thread — the exact pattern of
engine task threads writing sink part-files. A one-time in-memory
write+read from whichever thread gets there first (normally the main thread,
during package init) pins the lazy global state safely; all later
cross-thread use is then stable. Verified empirically: without the warmup
the 2-engine filesystem-parquet round trip crashes in pq.read_table; with
it, the identical run passes.
"""

from __future__ import annotations

import threading

_once = threading.Lock()
_initialized = False


def ensure_parquet_initialized() -> None:
    global _initialized
    if _initialized:
        return
    with _once:
        if _initialized:
            return
        try:
            import io

            import pyarrow as pa
            import pyarrow.parquet as pq

            buf = io.BytesIO()
            pq.write_table(pa.table({"_warmup": [1]}), buf)
            pq.read_table(io.BytesIO(buf.getvalue()), use_threads=False)
        except ImportError:
            pass  # no pyarrow: parquet formats are unavailable anyway
        _initialized = True
