"""Slot-directory windowed aggregation: scatter-only device path.

Round-1's device hash table probed (bin, key) pairs ON DEVICE with a
fori_loop of gather rounds. Measured on TPU (v5e over the driver tunnel),
dynamic gathers are the one slow XLA primitive (~13 ms per 8k-from-64k
gather) while scatters with combiners run in ~0.03 ms — so a probing hash
table is the worst possible design for this hardware, and the 2.2%-of-numpy
round-1 bench (VERDICT.md "What's weak" #1) was almost entirely probe-round
gathers plus synchronous per-close transfers.

This redesign splits the work by what each side is good at:

  host (vectorized numpy directory; the C++ runtime owns hashing already):
      (bin, key) -> device slot assignment. Slots live in fixed-size
      REGIONS; each window bin owns a chain of regions, so a window close
      maps to contiguous device slices, never a table compaction. The
      directory is open-addressing over 64-bit mixed codes with monotone
      bin-boundary liveness (window close is always "bin < boundary", so
      dead entries need no tombstones).

  device (one jitted step per operator config):
      state = one [cap] array per accumulator, nothing else in HBM.
      update = n_acc scatter-combines (.at[slots].add/min/max) — no gather,
      no sort, no probe loop. Window close = dynamic_slice of the closing
      bin's regions packed into ONE int64 buffer (single host round trip,
      fetched asynchronously), plus a dynamic_update_slice clear.

  spill tier: when every region is in use, new (bin, key) groups aggregate
      into a host dict store instead of erroring — the overflow-to-host
      policy SURVEY.md hard-part #1 calls for (round 1 raised
      RuntimeError).

Reference behavior being replaced: the per-bin DataFusion partial
aggregation plans of crates/arroyo-worker/src/arrow/
tumbling_aggregating_window.rs:49 and sliding_aggregating_window.rs:45.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from ..hashing import splitmix64
from .aggregate import (
    _I32_MAX,
    DeviceHashAggregator,
    _identity,
    combine_by_key_bin,
)

_BIN_MIX = np.uint64(0x9E3779B97F4A7C15)
_DEAD_BIN = -(2**62)


class BinSlotDirectory:
    """Host-side (bin, key) -> device-slot map with region-chained bins.

    Probing is vectorized numpy over the batch's unique codes: each round
    gathers one candidate directory row per pending code and resolves
    match / claim / advance, so cost is O(rounds) numpy passes, not a
    Python loop per key."""

    def __init__(self, cap: int, region_size: int):
        assert cap % region_size == 0
        self.cap = cap
        self.R = region_size
        self.n_regions = cap // region_size
        self.free_regions = list(range(self.n_regions - 1, -1, -1))
        self.bin_regions: dict[int, list[int]] = {}
        self.region_fill = np.zeros(self.n_regions, dtype=np.int64)
        # per-slot identity (for emission: device stores only accumulators)
        self.slot_keys = np.zeros(cap, dtype=np.int64)
        self.slot_bins = np.full(cap, _DEAD_BIN, dtype=np.int64)
        # open-addressing directory: mixed code -> slot
        self.hcap = 1 << (cap.bit_length() + 1)  # ~4x cap
        self.hmask = np.uint64(self.hcap - 1)
        self.hcode = np.zeros(self.hcap, dtype=np.uint64)
        self.hbin = np.full(self.hcap, _DEAD_BIN, dtype=np.int64)
        self.hslot = np.full(self.hcap, -1, dtype=np.int64)
        self.boundary = _DEAD_BIN  # bins below this are closed (monotone)

    # ------------------------------------------------------------- alloc

    def _alloc(self, b: int, n: int) -> np.ndarray:
        """Up to n device slots for bin b, chaining regions; may return fewer
        than n when capacity runs out (caller spills the remainder)."""
        regs = self.bin_regions.get(b)
        if regs is None:
            regs = self.bin_regions[b] = []
        chunks = []
        while n > 0:
            if regs and self.region_fill[regs[-1]] < self.R:
                r = regs[-1]
                fill = int(self.region_fill[r])
                take = min(n, self.R - fill)
                chunks.append(r * self.R + np.arange(fill, fill + take, dtype=np.int64))
                self.region_fill[r] = fill + take
                n -= take
            elif self.free_regions:
                r = self.free_regions.pop()
                self.region_fill[r] = 0
                regs.append(r)
            else:
                break
        if not regs:
            del self.bin_regions[b]
        if not chunks:
            return np.empty(0, dtype=np.int64)
        return np.concatenate(chunks) if len(chunks) > 1 else chunks[0]

    def live_bins(self) -> list[int]:
        return sorted(self.bin_regions)

    def close_bin(self, b: int) -> list[int]:
        """Release bin b's regions for reuse; returns the region ids (the
        caller must have dispatched the device-side clear first)."""
        regs = self.bin_regions.pop(b, [])
        for r in regs:
            self.free_regions.append(r)
        return regs

    # ------------------------------------------------------------- lookup

    def lookup_or_assign(
        self, codes: np.ndarray, keys: np.ndarray, bins: np.ndarray
    ) -> np.ndarray:
        """codes: unique uint64 mixed (bin,key) codes; keys/bins: the exact
        identities behind each code. Returns int64 slots; -1 = spill."""
        m = len(codes)
        out = np.full(m, -1, dtype=np.int64)
        if m == 0:
            return out
        h = (codes & self.hmask).astype(np.int64)
        pending = np.arange(m)
        spill_blocked = False
        for _ in range(self.hcap):
            if len(pending) == 0:
                break
            hp = h[pending]
            cp = codes[pending]
            hc = self.hcode[hp]
            live = (self.hslot[hp] >= 0) & (self.hbin[hp] >= self.boundary)
            match = live & (hc == cp)
            if match.any():
                mi = pending[match]
                s = self.hslot[h[mi]]
                bad = (self.slot_keys[s] != keys[mi]) | (self.slot_bins[s] != bins[mi])
                if bad.any():
                    raise RuntimeError(
                        "64-bit (bin,key) code collision in slot directory"
                    )
                out[mi] = s
            empty = ~live
            claim = pending[empty]
            if len(claim):
                # claim conflicts within the batch: first code per position
                # wins, the rest advance and keep probing
                hcl = h[claim]
                uniq, first = np.unique(hcl, return_index=True)
                winners = claim[first]
                if not spill_blocked:
                    order = np.argsort(bins[winners], kind="stable")
                    winners_sorted = winners[order]
                    wb = bins[winners_sorted]
                    seg = np.ones(len(wb), dtype=bool)
                    seg[1:] = wb[1:] != wb[:-1]
                    starts = np.flatnonzero(seg)
                    ends = np.append(starts[1:], len(wb))
                    for s0, s1 in zip(starts, ends):
                        grp = winners_sorted[s0:s1]
                        slots = self._alloc(int(wb[s0]), len(grp))
                        if len(slots) < len(grp):
                            spill_blocked = True  # unallocated stay -1
                            grp = grp[: len(slots)]
                        if len(grp) == 0:
                            continue
                        self.slot_keys[slots] = keys[grp]
                        self.slot_bins[slots] = bins[grp]
                        pos = h[grp]
                        self.hcode[pos] = codes[grp]
                        self.hbin[pos] = bins[grp]
                        self.hslot[pos] = slots
                        out[grp] = slots
            # still pending: not matched and not successfully claimed
            resolved = out[pending] >= 0
            give_up = np.zeros(len(pending), dtype=bool)
            if spill_blocked:
                give_up = ~resolved & empty  # nothing left to allocate
            keep = ~resolved & ~give_up
            nxt = pending[keep]
            h[nxt] = (h[nxt] + 1) & int(self.hmask)
            pending = nxt
        return out


class SlotExtractHandle:
    """In-flight window close: per-region packed buffers are streaming to
    host; identities (key hash, bin) were snapshotted host-side at dispatch
    so region reuse can't race the fetch."""

    def __init__(self, agg: "SlotAggregator", groups, spill):
        self._agg = agg
        # groups: list of (regs, int_buf|None, float_buf|None) where regs is
        # [(bin, keys_i64_copy, fill), ...] in buffer order
        self._groups = groups
        self._spill = spill  # (keys_u64, bins_i32, [acc arrays]) or None

    def is_ready(self) -> bool:
        return all(
            (ib is None or ib.is_ready()) and (fb is None or fb.is_ready())
            for (_regs, ib, fb) in self._groups
        )

    def result(self) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        from .prefetch import wait_buffers_ready

        wait_buffers_ready([b for (_r, ib, fb) in self._groups for b in (ib, fb)])
        agg = self._agg
        R = agg.region_size
        int_idx = [i for i, d in enumerate(agg.acc_dtypes)
                   if not np.issubdtype(d, np.floating)]
        flt_idx = [i for i, d in enumerate(agg.acc_dtypes)
                   if np.issubdtype(d, np.floating)]
        keys_out, bins_out = [], []
        accs_out: list[list[np.ndarray]] = [[] for _ in agg.acc_dtypes]
        for regs, ibuf, fbuf in self._groups:
            # a zero-length fetch still pays a full tunnel round trip, so
            # absent lane classes are never materialized (buf is None); the
            # padded tail regions (bases duplicated) are simply not in regs
            ilanes = flanes = None
            if ibuf is not None:
                a = np.asarray(ibuf)
                ilanes = a.reshape(-1, len(int_idx), R)
            if fbuf is not None:
                a = np.asarray(fbuf)
                flanes = a.reshape(-1, len(flt_idx), R)
            for pos, (b, keys_i64, fill) in enumerate(regs):
                if fill == 0:
                    continue
                keys_out.append(keys_i64.view(np.uint64))
                bins_out.append(np.full(fill, b, dtype=np.int32))
                for j, i in enumerate(int_idx):
                    accs_out[i].append(ilanes[pos, j, :fill].astype(agg.acc_dtypes[i]))
                for j, i in enumerate(flt_idx):
                    accs_out[i].append(flanes[pos, j, :fill].astype(agg.acc_dtypes[i]))
        if self._spill is not None and len(self._spill[0]):
            sk, sb, sa = self._spill
            keys_out.append(sk)
            bins_out.append(sb)
            for i, a in enumerate(sa):
                accs_out[i].append(a)
        if not keys_out:
            return (
                np.empty(0, dtype=np.uint64),
                np.empty(0, dtype=np.int32),
                [np.empty(0, dtype=d) for d in agg.acc_dtypes],
            )
        return combine_by_key_bin(
            agg.acc_kinds,
            np.concatenate(keys_out),
            np.concatenate(bins_out),
            [np.concatenate(a) for a in accs_out],
        )


@functools.lru_cache(maxsize=None)
def _build_slot_jax(acc_kinds: tuple, acc_dtypes: tuple, cap: int, region_size: int):
    import jax
    import jax.numpy as jnp

    idents = tuple(
        np.full(region_size, _identity(k, np.dtype(d)), dtype=d)
        for k, d in zip(acc_kinds, acc_dtypes)
    )

    def _mk_step(merge: bool):
        # hot path (merge=False): count lanes take no val array — the
        # increment is a constant 1, so shipping a batch-length ones lane
        # over the host->device link (256 KB/batch at 32k rows) would be
        # pure waste. Merge mode (restore / partial-combine) scatters the
        # provided partial counts instead; it compiles lazily on first
        # restore, never in the steady state.
        def step(state, slots, vals):
            out = []
            vi = 0
            for kind, a in zip(acc_kinds, state):
                if kind == "count" and not merge:
                    out.append(a.at[slots].add(np.asarray(1, a.dtype), mode="drop"))
                    continue
                v = vals[vi]
                vi += 1
                if kind in ("sum", "count"):
                    out.append(a.at[slots].add(v, mode="drop"))
                elif kind == "min":
                    out.append(a.at[slots].min(v, mode="drop"))
                else:
                    out.append(a.at[slots].max(v, mode="drop"))
            return tuple(out)

        return step

    step = _mk_step(merge=False)
    step_merge = _mk_step(merge=True)

    # 64-bit bitcasts are unsupported under TPU x64 emulation, so integer and
    # float accumulators travel in two separately-typed buffers (still one
    # fetch each, started together)
    def _pack(state, base):
        ilanes, flanes = [], []
        for a, d in zip(state, acc_dtypes):
            sl = jax.lax.dynamic_slice(a, (base,), (region_size,))
            if np.issubdtype(np.dtype(d), np.floating):
                flanes.append(sl.astype(jnp.float64))
            else:
                ilanes.append(sl.astype(jnp.int64))
        ibuf = jnp.concatenate(ilanes) if ilanes else jnp.zeros(0, jnp.int64)
        fbuf = jnp.concatenate(flanes) if flanes else jnp.zeros(0, jnp.float64)
        return ibuf, fbuf

    def _clear(state, base):
        return tuple(
            jax.lax.dynamic_update_slice(a, jnp.asarray(i), (base,))
            for a, i in zip(state, idents)
        )

    def clear(state, base):
        return _clear(state, base)

    # multi-region read: one device call + ONE host fetch per window close
    # regardless of how many bins/regions it spans (each fetch over the
    # remote-device tunnel costs a full round trip). k is static per jit;
    # callers bucket k and pad bases by duplicating bases[0] (duplicate
    # clears are idempotent, duplicate reads are ignored).
    @functools.lru_cache(maxsize=None)
    def make_read_multi(k: int, do_clear: bool):
        def go(state, bases):
            ibufs, fbufs = [], []
            for j in range(k):
                ibuf, fbuf = _pack(state, bases[j])
                ibufs.append(ibuf)
                fbufs.append(fbuf)
            ib = jnp.concatenate(ibufs) if ibufs[0].shape[0] else ibufs[0]
            fb = jnp.concatenate(fbufs) if fbufs[0].shape[0] else fbufs[0]
            if do_clear:
                for j in range(k):
                    state = _clear(state, bases[j])
                return state, ib, fb
            return ib, fb

        if do_clear:
            return jax.jit(go, donate_argnums=0)
        return jax.jit(go)

    # point reads for updating aggregates: one small gather of the touched
    # slots per flush interval (a bounded gather once a second is fine; the
    # per-batch hot loop stays scatter-only)
    @functools.lru_cache(maxsize=None)
    def make_read_slots(k: int):
        def go(state, slots):
            outs = []
            for a, d in zip(state, acc_dtypes):
                sl = a[slots]
                if np.issubdtype(np.dtype(d), np.floating):
                    outs.append(sl.astype(jnp.float64))
                else:
                    outs.append(sl.astype(jnp.int64))
            return tuple(outs)

        return jax.jit(go)

    return (
        jax.jit(step, donate_argnums=0),
        jax.jit(step_merge, donate_argnums=0),
        make_read_multi,
        jax.jit(clear, donate_argnums=0),
        make_read_slots,
    )


class SlotAggregator(DeviceHashAggregator):
    """Drop-in replacement for DeviceHashAggregator (same update / extract /
    extract_start / scan_range / free_bins_below / snapshot / restore
    surface) built on the host slot directory + scatter-only device step.
    backend="numpy" inherits the dict-store oracle unchanged."""

    def __init__(
        self,
        acc_kinds: Sequence[str],
        acc_dtypes: Sequence[np.dtype],
        cap: int = 65536,
        batch_cap: int = 8192,
        max_probes: int = 64,  # unused; kept for constructor compatibility
        emit_cap: int = 8192,  # unused; region_size bounds each transfer
        backend: str = "jax",
        region_size: int = 2048,
    ):
        self.region_size = region_size
        if backend == "jax":
            self.acc_kinds = tuple(acc_kinds)
            self.acc_dtypes = tuple(np.dtype(d) for d in acc_dtypes)
            self.cap = cap
            self.batch_cap = batch_cap
            self.max_probes = max_probes
            self.emit_cap = emit_cap
            self.backend = backend
            (self._step, self._step_merge, self._read_multi, self._clear,
             self._read_slots) = \
                _build_slot_jax(self.acc_kinds, self.acc_dtypes, cap, region_size)
            self._merge_mode = False
            self._n_flt_lanes = sum(
                1 for d in self.acc_dtypes if np.issubdtype(d, np.floating))
            self._n_int_lanes = len(self.acc_dtypes) - self._n_flt_lanes
            self.state = self._init_jax_state()
        else:
            super().__init__(acc_kinds, acc_dtypes, cap=cap, batch_cap=batch_cap,
                             max_probes=max_probes, emit_cap=emit_cap, backend=backend)

    def _init_jax_state(self):
        import jax.numpy as jnp

        self.directory = BinSlotDirectory(self.cap, self.region_size)
        # host spill store (bin, key) -> [acc parts]; fed when regions run out
        self.spill: dict[tuple[int, int], list] = {}
        return tuple(
            jnp.full(self.cap, _identity(k, d), dtype=d)
            for k, d in zip(self.acc_kinds, self.acc_dtypes)
        )

    # ------------------------------------------------------------- update

    def _update_chunk(self, key_u64, bins, vals) -> None:
        m = len(key_u64)
        ku = np.ascontiguousarray(key_u64, dtype=np.uint64)
        ks = ku.view(np.int64)
        b64 = np.ascontiguousarray(bins, dtype=np.int64)
        d = self.directory
        from .. import native

        res = native.dir_resolve(ks, b64, d.hcode, d.hbin, d.hslot,
                                 d.boundary, d.slot_keys, d.slot_bins)
        if res is not None:
            # native fast path: one C pass resolves every row whose (bin,key)
            # group already owns a slot; only first-seen groups (deduplicated
            # in C) go through the Python allocator
            row_slots, miss_ord, miss_codes, miss_keys, miss_bins = res
            if len(miss_codes):
                slots_new = d.lookup_or_assign(miss_codes, miss_keys, miss_bins)
                neg = row_slots < 0
                row_slots[neg] = slots_new[miss_ord[neg]]
        else:
            codes = splitmix64(ku ^ (b64.astype(np.uint64) * _BIN_MIX))
            uniq, first, inv = np.unique(codes, return_index=True, return_inverse=True)
            slots_u = self.directory.lookup_or_assign(uniq, ks[first], b64[first])
            row_slots = slots_u[inv]
        vals = [np.asarray(v) for v in vals]
        spill_rows = row_slots < 0
        if spill_rows.any():
            sel = np.flatnonzero(spill_rows)
            self._spill_update(ks[sel], b64[sel], [v[sel] for v in vals])
            keep = np.flatnonzero(~spill_rows)
            row_slots = row_slots[keep]
            vals = [v[keep] for v in vals]
            m = len(keep)
        B = self.batch_cap
        # int32 slot indices: halves the per-batch index transfer and keeps
        # the scatter index math native on TPU (int64 is x64-emulated)
        idx_dt = np.int32 if self.cap < _I32_MAX else np.int64
        merge = self._merge_mode
        if m == B:
            # full-width chunk (steady state): no padding copies needed
            slots = row_slots.astype(idx_dt, copy=False)
            vs = [np.asarray(v, dtype=dt)
                  for v, k, dt in zip(vals, self.acc_kinds, self.acc_dtypes)
                  if merge or k != "count"]
        else:
            slots = np.full(B, self.cap, dtype=idx_dt)  # pad -> dropped
            slots[:m] = row_slots
            vs = []
            for v, k, dt in zip(vals, self.acc_kinds, self.acc_dtypes):
                if not merge and k == "count":
                    continue
                arr = np.full(B, _identity(k, dt), dtype=dt)
                arr[:m] = v
                vs.append(arr)
        step = self._step_merge if merge else self._step
        self.state = step(self.state, slots, tuple(vs))

    def _spill_update(self, keys_i64, bins_i64, vals) -> None:
        order = np.lexsort((keys_i64, bins_i64))
        k_s, b_s = keys_i64[order], bins_i64[order]
        vs = [np.asarray(v)[order] for v in vals]
        newseg = np.ones(len(k_s), dtype=bool)
        newseg[1:] = (k_s[1:] != k_s[:-1]) | (b_s[1:] != b_s[:-1])
        starts = np.flatnonzero(newseg)
        ends = np.append(starts[1:], len(k_s))
        store = self.spill
        for s, e in zip(starts, ends):
            kk = (int(b_s[s]), int(k_s[s]))
            cur = store.get(kk)
            parts = []
            for i, kind in enumerate(self.acc_kinds):
                seg = vs[i][s:e]
                red = (seg.sum() if kind in ("sum", "count")
                       else (seg.min() if kind == "min" else seg.max()))
                if cur is not None:
                    red = (cur[i] + red if kind in ("sum", "count")
                           else (min(cur[i], red) if kind == "min" else max(cur[i], red)))
                parts.append(self.acc_dtypes[i].type(red))
            store[kk] = parts

    def _take_spill(self, emit_lo: int, emit_hi: int, free_below: int):
        hit = [kk for kk in self.spill if emit_lo <= kk[0] < emit_hi]
        if not hit:
            return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32),
                    [np.empty(0, dtype=d) for d in self.acc_dtypes])
        ks = np.array([k for (_b, k) in hit], dtype=np.int64).view(np.uint64)
        bs = np.array([b for (b, _k) in hit], dtype=np.int32)
        accs = [np.array([self.spill[kk][i] for kk in hit], dtype=d)
                for i, d in enumerate(self.acc_dtypes)]
        for kk in hit:
            if kk[0] < free_below:
                del self.spill[kk]
        return ks, bs, accs

    # ------------------------------------------------------------- extract

    def _collect_regions(self, emit_lo: int, emit_hi: int):
        """[(bin, base, fill, keys_copy)] for every region of bins in range."""
        d = self.directory
        out = []
        for b in d.live_bins():
            if not (emit_lo <= b < emit_hi):
                continue
            for r in d.bin_regions.get(b, ()):
                base = r * self.region_size
                fill = int(d.region_fill[r])
                out.append((b, base, fill, d.slot_keys[base : base + fill].copy()))
        return out

    def _read_regions(self, regs, do_clear: bool):
        """Batch region reads: <=16 regions per device call, k bucketed to a
        power of two (bases padded by duplication) so each close costs one
        fetch, not one per region."""
        groups = []
        i = 0
        while i < len(regs):
            chunk = regs[i : i + 16]
            i += 16
            k = 1
            while k < len(chunk):
                k *= 2
            bases = np.array(
                [c[1] for c in chunk] + [chunk[0][1]] * (k - len(chunk)),
                dtype=np.int64,
            )
            fn = self._read_multi(k, do_clear)
            if do_clear:
                self.state, ibuf, fbuf = fn(self.state, bases)
            else:
                ibuf, fbuf = fn(self.state, bases)
            ibuf = ibuf if self._n_int_lanes else None
            fbuf = fbuf if self._n_flt_lanes else None
            for buf in (ibuf, fbuf):
                if buf is None:
                    continue
                try:
                    buf.copy_to_host_async()
                except AttributeError:
                    pass
            groups.append(([(b, keys, fill) for (b, _base, fill, keys) in chunk],
                           ibuf, fbuf))
        return groups

    def extract_start(self, emit_lo: int, emit_hi: int, free_below: int) -> SlotExtractHandle:
        d = self.directory
        regs_destr = self._collect_regions(emit_lo, min(emit_hi, free_below))
        regs_keep = self._collect_regions(max(emit_lo, free_below), emit_hi)
        groups = self._read_regions(regs_destr, do_clear=True)
        groups += self._read_regions(regs_keep, do_clear=False)
        for b in [b for b in d.live_bins() if b < free_below]:
            if not (emit_lo <= b < emit_hi):
                # non-emitted expired bins: clear without reading
                for r in d.bin_regions.get(b, ()):
                    self.state = self._clear(self.state, np.int64(r * self.region_size))
            d.close_bin(b)
        spill = self._take_spill(emit_lo, emit_hi, free_below)
        for kk in [kk for kk in self.spill if kk[0] < free_below]:
            del self.spill[kk]
        if free_below > d.boundary:
            d.boundary = free_below
        return SlotExtractHandle(self, groups, spill)

    def extract(self, emit_lo: int, emit_hi: int, free_below: int):
        if self.backend == "numpy":
            return self._extract_numpy(emit_lo, emit_hi, free_below)
        return self.extract_start(emit_lo, emit_hi, free_below).result()

    def scan_range(self, emit_lo: int, emit_hi: int):
        if self.backend == "numpy":
            return super().scan_range(emit_lo, emit_hi)
        groups = self._read_regions(self._collect_regions(emit_lo, emit_hi),
                                    do_clear=False)
        spill = self._take_spill(emit_lo, emit_hi, free_below=_DEAD_BIN)
        return SlotExtractHandle(self, groups, spill).result()

    def free_bins_below(self, below: int) -> None:
        if self.backend == "numpy":
            return super().free_bins_below(below)
        d = self.directory
        for b in d.live_bins():
            if b < below:
                for r in d.bin_regions.get(b, ()):
                    self.state = self._clear(self.state, np.int64(r * self.region_size))
                d.close_bin(b)
        for kk in [kk for kk in self.spill if kk[0] < below]:
            del self.spill[kk]
        if below > d.boundary:
            d.boundary = below

    def read_slots(self, slots: np.ndarray) -> list[np.ndarray]:
        """Current accumulator values at the given device slots (one gather,
        one fetch; slot count bucketed to powers of two for jit reuse).
        Used by the updating-aggregate flush; window paths never gather."""
        n = len(slots)
        if n == 0:
            return [np.empty(0, dtype=d) for d in self.acc_dtypes]
        k = 64
        while k < n:
            k *= 2
        padded = np.zeros(k, dtype=np.int32 if self.cap < _I32_MAX else np.int64)
        padded[:n] = slots
        outs = self._read_slots(k)(self.state, padded)
        from .prefetch import wait_buffers_ready

        wait_buffers_ready(outs)
        return [np.asarray(o)[:n].astype(d, copy=False)
                for o, d in zip(outs, self.acc_dtypes)]

    def slots_of(self, key_u64: np.ndarray) -> np.ndarray:
        """Device slots currently assigned to these (bin=0) keys; -1 for
        keys living in the host spill tier. Read-only: never allocates."""
        from .. import native

        d = self.directory
        ks = np.ascontiguousarray(key_u64, dtype=np.uint64).view(np.int64)
        zeros = np.zeros(len(ks), dtype=np.int64)
        res = native.dir_resolve(ks, zeros, d.hcode, d.hbin, d.hslot,
                                 d.boundary, d.slot_keys, d.slot_bins)
        if res is not None:
            return res[0]  # misses stay -1 (unallocated)
        codes = splitmix64(key_u64.astype(np.uint64))
        out = np.full(len(ks), -1, dtype=np.int64)
        for i, (c, k) in enumerate(zip(codes, ks)):
            h = int(c & d.hmask)
            for _ in range(d.hcap):
                if d.hslot[h] < 0 or d.hbin[h] < d.boundary:
                    break
                if d.hcode[h] == c and d.slot_keys[d.hslot[h]] == k:
                    out[i] = d.hslot[h]
                    break
                h = (h + 1) & int(d.hmask)
        return out

    # ------------------------------------------------------------- state sync

    def restore(self, key_u64, bins, accs) -> None:
        if self.backend == "numpy":
            return super().restore(key_u64, bins, accs)
        self.state = self._init_jax_state()
        self._merge_mode = True
        try:
            self.update(key_u64, bins.astype(np.int32), accs)
        finally:
            self._merge_mode = False

    def snapshot(self):
        if self.backend == "numpy":
            return super().snapshot()
        d = self.directory
        live = d.live_bins()
        spill_bins = [b for (b, _k) in self.spill]
        if not live and not spill_bins:
            return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32),
                    [np.empty(0, dtype=dt) for dt in self.acc_dtypes])
        lo = min(live + spill_bins)
        hi = max(live + spill_bins) + 1
        return self.scan_range(lo, hi)
