"""Device-lowered hash-join index computation for windowed joins.

Reference behavior being replaced: the per-bin DataFusion join execs of
crates/arroyo-worker/src/arrow/instant_join.rs:38. The join's heavy phase —
sorting the build side and binary-searching every probe key — runs on the
device as one jitted program; only the data-dependent pair expansion (whose
output size XLA cannot represent statically) stays on host, where it is a
cheap repeat/cumsum.

Shapes are bucketed to powers of two so each (probe, build) size pair
compiles once; results stream back through copy_to_host_async and a
JoinHandle, so windowed-join operators can dispatch the close for window t
and emit when ready, without blocking the hot loop (same pipelining
discipline as ops/slot_agg.py window closes).
"""

from __future__ import annotations

import functools

import numpy as np

_SENTINEL = np.iinfo(np.int64).max


def host_join_indices(
    left_keys: np.ndarray, right_keys: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host (numpy) inner-join row index pairs (li, ri) where keys match:
    sort the right side once, binary-search each left key, expand ranges.
    The same sort/search phase the device path runs via _probe_jit."""
    order = np.argsort(right_keys, kind="stable")
    rk = right_keys[order]
    lo = np.searchsorted(rk, left_keys, side="left")
    hi = np.searchsorted(rk, left_keys, side="right")
    counts = hi - lo
    li = np.repeat(np.arange(len(left_keys)), counts)
    # for each left row, offsets lo[l]..hi[l] into the sorted right
    if len(li):
        within = np.arange(len(li)) - np.repeat(
            np.cumsum(counts) - counts, counts
        )
        ri = order[np.repeat(lo, counts) + within]
    else:
        ri = np.empty(0, dtype=np.int64)
    return li, ri


def fused_join_indices(
    left_keys: np.ndarray,
    right_keys: np.ndarray,
    l_bounds: np.ndarray,
    r_bounds: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """Inner-join pairs for W independent partitions (windows) in one call:
    partition w spans left rows l_bounds[w]:l_bounds[w+1] and right rows
    r_bounds[w]:r_bounds[w+1]. Each partition is probed with the shared
    sort/search join on its slice (still a Python loop over W — a true
    (partition, key) lexsort probe is a possible follow-up); the win is in
    the OUTPUT: pairs come back as GLOBAL row indices so the caller
    gathers and emits once for all windows instead of W tiny batches."""
    lis: list[np.ndarray] = []
    ris: list[np.ndarray] = []
    for w in range(len(l_bounds) - 1):
        l0, l1 = int(l_bounds[w]), int(l_bounds[w + 1])
        r0, r1 = int(r_bounds[w]), int(r_bounds[w + 1])
        li, ri = host_join_indices(left_keys[l0:l1], right_keys[r0:r1])
        if len(li):
            lis.append(li + l0)
            ris.append(ri + r0)
    if not lis:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    return np.concatenate(lis), np.concatenate(ris)


@functools.lru_cache(maxsize=1)
def _probe_jit():
    # one jitted callable; jax specializes per bucketed input shape
    import jax
    import jax.numpy as jnp

    def probe(lk, rk):
        order = jnp.argsort(rk)
        rk_s = rk[order]
        lo = jnp.searchsorted(rk_s, lk, side="left")
        hi = jnp.searchsorted(rk_s, lk, side="right")
        return order.astype(jnp.int32), lo.astype(jnp.int32), hi.astype(jnp.int32)

    return jax.jit(probe)


def _bucket(n: int) -> int:
    c = 64
    while c < n:
        c <<= 1
    return c


class JoinHandle:
    """In-flight device join for one window: order/lo/hi are streaming to
    host; result() expands them into (li, ri) inner-join index pairs."""

    def __init__(self, n_l: int, n_r: int, order, lo, hi):
        self._n_l = n_l
        self._n_r = n_r
        self._bufs = (order, lo, hi)

    def is_ready(self) -> bool:
        try:
            return all(b.is_ready() for b in self._bufs)
        except AttributeError:
            return True

    def result(self) -> tuple[np.ndarray, np.ndarray]:
        from .prefetch import wait_buffers_ready

        wait_buffers_ready(self._bufs)
        order, lo, hi = (np.asarray(b) for b in self._bufs)
        n_l, n_r = self._n_l, self._n_r
        lo = lo[:n_l].astype(np.int64)
        hi = hi[:n_l].astype(np.int64)
        counts = hi - lo
        li = np.repeat(np.arange(n_l), counts)
        if len(li):
            within = np.arange(len(li)) - np.repeat(np.cumsum(counts) - counts, counts)
            ri = order[np.repeat(lo, counts) + within].astype(np.int64)
            # padded build rows sort to the tail; a probe key equal to the
            # sentinel could reference them — drop those pairs exactly
            keep = ri < n_r
            if not keep.all():
                li, ri = li[keep], ri[keep]
        else:
            ri = np.empty(0, dtype=np.int64)
        return li, ri


def device_join_start(left_keys: np.ndarray, right_keys: np.ndarray) -> JoinHandle:
    """Dispatch the sort/search phase for an inner join on int64 keys;
    returns a JoinHandle whose result() yields (li, ri) pairs."""
    n_l, n_r = len(left_keys), len(right_keys)
    l_cap, r_cap = _bucket(n_l), _bucket(n_r)
    lk = np.full(l_cap, _SENTINEL, dtype=np.int64)
    lk[:n_l] = left_keys
    rk = np.full(r_cap, _SENTINEL, dtype=np.int64)
    rk[:n_r] = right_keys
    order, lo, hi = _probe_jit()(lk, rk)
    for b in (order, lo, hi):
        try:
            b.copy_to_host_async()
        except AttributeError:
            pass
    return JoinHandle(n_l, n_r, order, lo, hi)
