"""Device (JAX/XLA) compute runtime.

64-bit support is required: routing keys are 64-bit hashes and integer SUM
accumulators need i64 range. TPUs emulate i64 with i32 limb pairs under XLA;
enabling x64 here (before any jax arrays exist) keeps key comparisons exact.
"""

import jax

jax.config.update("jax_enable_x64", True)


def require_x64() -> None:
    """Idempotent pin for trace entry points living OUTSIDE this package.

    Importing ``arroyo_tpu.ops`` pins x64 as a side effect, but a module
    like ``engine/segment.py`` that jits traced code without ever touching
    a device kernel (a value/key/watermark-only chain) would otherwise
    trace under default 32-bit jax semantics: int64 inputs silently
    downcast, the uint64 routing hash truncates, and the first-batch
    verification fails into a permanent (and unexplained) interpreted
    fallback. Trace-safety rule LR304 requires every jit-root module to
    reach this pin before tracing."""
    jax.config.update("jax_enable_x64", True)

from .aggregate import (  # noqa: F401,E402
    AGG_KINDS,
    DeviceHashAggregator,
    acc_kinds_for,
    finalize_aggs,
)
