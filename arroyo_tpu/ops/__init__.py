"""Device (JAX/XLA) compute runtime.

64-bit support is required: routing keys are 64-bit hashes and integer SUM
accumulators need i64 range. TPUs emulate i64 with i32 limb pairs under XLA;
enabling x64 here (before any jax arrays exist) keeps key comparisons exact.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .aggregate import (  # noqa: F401,E402
    AGG_KINDS,
    DeviceHashAggregator,
    acc_kinds_for,
    finalize_aggs,
)
