"""Keyed windowed aggregation on device: HBM-resident hash-table state.

This replaces the reference's DataFusion partial/finish aggregate plans
(crates/arroyo-worker/src/arrow/tumbling_aggregating_window.rs:49,
sliding_aggregating_window.rs:45) with a TPU-native design:

  state (HBM, persistent across micro-batches, donated through jit):
      keys      int64[cap]   -- 64-bit key hash (uint64 bits viewed as int64)
      bins      int32[cap]   -- window bin index (timestamp // bin_width)
      occupied  bool[cap]
      accs      tuple of [cap] arrays, one per accumulator

  step (jit, one fused XLA program per operator config):
      1. lexsort incoming (bin, key) pairs -> adjacent duplicates
      2. segment-reduce each accumulator -> <=B unique (bin, key) partials
      3. merge partials into the table with linear probing: matches combine
         via scatter; empty-slot claims race-resolved with a scatter-max of
         the contender index (classic GPU hash-build, expressed as XLA
         scatter/gather under lax.fori_loop so it compiles to one program)

  extract (jit): compact closed bins out of the table with an argsort on the
      close mask; destructive (tumbling close) or range-scan (sliding).

Static shapes everywhere: batches padded to ``batch_cap``, table capacity and
probe count fixed at trace time; no data-dependent control flow inside jit.
A NumPy mirror backend provides the CPU oracle for differential tests and the
bench baseline.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

AGG_KINDS = ("sum", "count", "min", "max")

_I64_MAX = np.iinfo(np.int64).max
_I32_MAX = np.iinfo(np.int32).max


def acc_kinds_for(kind: str) -> tuple[str, ...]:
    """Accumulators backing one SQL aggregate (avg -> sum+count)."""
    if kind == "avg":
        return ("sum", "count")
    if kind in AGG_KINDS:
        return (kind,)
    raise ValueError(f"unsupported aggregate {kind}")


def finalize_aggs(kinds: Sequence[str], acc_arrays: list[np.ndarray]) -> list[np.ndarray]:
    """acc arrays (in acc_kinds_for order, flattened) -> one array per SQL agg."""
    out = []
    i = 0
    for kind in kinds:
        if kind == "avg":
            s, c = acc_arrays[i], acc_arrays[i + 1]
            i += 2
            out.append(np.divide(s, np.maximum(c, 1)).astype(np.float64))
        elif kind == "count_distinct":
            out.append(np.array([len(set(lst)) for lst in acc_arrays[i]],
                                dtype=np.int64))
            i += 1
        elif kind.startswith("udaf:"):
            from ..batch import Field
            from ..udf import lookup_udaf

            udaf = lookup_udaf(kind[len("udaf:"):])
            if udaf is None:
                raise RuntimeError(f"UDAF {kind[5:]!r} no longer registered")
            vals = [udaf.fn(np.asarray(lst)) for lst in acc_arrays[i]]
            i += 1
            if udaf.return_dtype == "string":
                from ..batch import object_column

                out.append(object_column(vals))
            else:
                out.append(np.array(vals, dtype=Field("_", udaf.return_dtype).numpy_dtype()))
        else:
            out.append(acc_arrays[i])
            i += 1
    return out


def drain_extract(extract_once, emit_cap: int, acc_kinds: Sequence[str],
                  acc_dtypes: Sequence[np.dtype], emit_lo: int, free_below: int):
    """Host-side drain loop shared by the single-chip and sharded
    aggregators. ``extract_once()`` performs one device extraction and
    returns (key_i64, bin, valid, accs, max_total) as numpy arrays/ints.

    Termination invariants: entries in the emit range are freed only when
    below ``free_below``, so a destructive close shrinks each round; a pure
    range scan (free_below <= emit_lo) must bail after one round or it would
    re-emit the same entries forever.

    The result is merged with combine_by_key_bin: in-place slot freeing
    punches holes in probe chains, so the table may hold duplicate (key, bin)
    entries whose accumulators each carry part of the total."""
    keys_out, bins_out = [], []
    accs_out: list[list[np.ndarray]] = [[] for _ in acc_dtypes]
    while True:
        k, b, valid, accs, max_total = extract_once()
        cnt = int(valid.sum())
        if cnt:
            keys_out.append(k[valid])
            bins_out.append(b[valid])
            for i, a in enumerate(accs):
                accs_out[i].append(a[valid])
        if max_total <= emit_cap or cnt == 0 or free_below <= emit_lo:
            break
    if not keys_out:
        return (
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int32),
            [np.empty(0, dtype=d) for d in acc_dtypes],
        )
    return combine_by_key_bin(
        acc_kinds,
        np.concatenate(keys_out).view(np.uint64),
        np.concatenate(bins_out),
        [np.concatenate(a) for a in accs_out],
    )


def combine_by_key_bin(
    acc_kinds: Sequence[str],
    keys: np.ndarray,
    bins: np.ndarray,
    accs: list[np.ndarray],
) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
    """Merge duplicate (key, bin) entries after a device extraction. The
    linear-probe table frees slots in place when bins close, which punches
    holes in probe chains: a later update of a live (key, bin) can claim a
    hole before reaching its original entry, leaving two entries whose
    accumulators each hold part of the total. Emission must re-combine them."""
    if len(keys) <= 1:
        return keys, bins, accs
    signed = keys.view(np.int64)
    order = np.lexsort((signed, bins))
    k_s, b_s = signed[order], bins[order]
    newseg = np.ones(len(k_s), dtype=bool)
    newseg[1:] = (k_s[1:] != k_s[:-1]) | (b_s[1:] != b_s[:-1])
    if newseg.all():
        return keys, bins, accs
    starts = np.flatnonzero(newseg)
    out_accs = []
    for kind, a in zip(acc_kinds, accs):
        a_s = a[order]
        if kind in ("sum", "count"):
            red = np.add.reduceat(a_s, starts)
        elif kind == "min":
            red = np.minimum.reduceat(a_s, starts)
        else:
            red = np.maximum.reduceat(a_s, starts)
        out_accs.append(red.astype(a.dtype))
    return k_s[starts].view(np.uint64), b_s[starts], out_accs


def combine_by_key(
    acc_kinds: Sequence[str], keys: np.ndarray, accs: list[np.ndarray]
) -> tuple[np.ndarray, list[np.ndarray]]:
    """Combine per-bin partials that share a key into one accumulator row per
    key (the sliding-window finish step: width/slide partial bins collapse to
    one output row — reference sliding_aggregating_window.rs:116-170). Host
    numpy: the input is already reduced to distinct (bin, key) pairs, so this
    is small relative to the event stream the device reduced."""
    if len(keys) == 0:
        return keys, accs
    signed = keys.view(np.int64)
    order = np.argsort(signed, kind="stable")
    k_s = signed[order]
    newseg = np.ones(len(k_s), dtype=bool)
    newseg[1:] = k_s[1:] != k_s[:-1]
    starts = np.flatnonzero(newseg)
    out_keys = k_s[starts].view(np.uint64)
    out_accs = []
    for kind, a in zip(acc_kinds, accs):
        a_s = a[order]
        if kind in ("sum", "count"):
            red = np.add.reduceat(a_s, starts)
        elif kind == "min":
            red = np.minimum.reduceat(a_s, starts)
        else:
            red = np.maximum.reduceat(a_s, starts)
        out_accs.append(red.astype(a.dtype))
    return out_keys, out_accs


def _identity(kind: str, dtype):
    if kind in ("sum", "count"):
        return np.array(0, dtype=dtype)
    if kind == "min":
        return np.array(np.iinfo(dtype).max if np.issubdtype(dtype, np.integer) else np.inf, dtype=dtype)
    if kind == "max":
        return np.array(np.iinfo(dtype).min if np.issubdtype(dtype, np.integer) else -np.inf, dtype=dtype)
    raise ValueError(kind)


# =========================================================================
# jax backend — traceable building blocks (shared by the single-chip step
# and the shard_map'd multi-chip step in arroyo_tpu.parallel)
# =========================================================================


def _combine_jnp(kind, a, b):
    import jax.numpy as jnp

    if kind in ("sum", "count"):
        return a + b
    if kind == "min":
        return jnp.minimum(a, b)
    return jnp.maximum(a, b)


def _seg_reduce_jnp(kind, vals, seg, valid, num_segments):
    import jax
    import jax.numpy as jnp

    if kind in ("sum", "count"):
        v = jnp.where(valid, vals, 0)
        return jax.ops.segment_sum(v, seg, num_segments=num_segments)
    if kind == "min":
        v = jnp.where(valid, vals, _identity("min", np.dtype(vals.dtype)))
        return jax.ops.segment_min(v, seg, num_segments=num_segments)
    v = jnp.where(valid, vals, _identity("max", np.dtype(vals.dtype)))
    return jax.ops.segment_max(v, seg, num_segments=num_segments)


def sort_reduce(acc_kinds, key, bins, valid, vals, batch_cap):
    """Collapse a padded batch to unique (bin, key) partials: lexsort so
    duplicates are adjacent, then segment-reduce each accumulator. Returns
    (u_key, u_bin, active_mask, u_accs), all of length batch_cap."""
    import jax
    import jax.numpy as jnp

    skey = jnp.where(valid, key, _I64_MAX)
    sbin = jnp.where(valid, bins, _I32_MAX)
    order = jnp.lexsort((sbin, skey))
    k_s = skey[order]
    b_s = sbin[order]
    valid_s = valid[order]
    newseg = jnp.concatenate(
        [jnp.ones(1, dtype=bool), (k_s[1:] != k_s[:-1]) | (b_s[1:] != b_s[:-1])]
    )
    seg = jnp.cumsum(newseg) - 1
    u_accs = tuple(
        _seg_reduce_jnp(acc_kinds[i], vals[i][order], seg, valid_s, batch_cap)
        for i in range(len(acc_kinds))
    )
    rows_per_seg = jax.ops.segment_sum(
        valid_s.astype(jnp.int32), seg, num_segments=batch_cap
    )
    # representative key/bin per segment (all rows in a segment are equal)
    u_key = jax.ops.segment_max(k_s, seg, num_segments=batch_cap)
    u_bin = jax.ops.segment_max(b_s, seg, num_segments=batch_cap)
    return u_key, u_bin, rows_per_seg > 0, u_accs


def probe_merge(acc_kinds, table, u_key, u_bin, active0, u_accs, cap, max_probes):
    """Merge unique partials into the (keys, bins, occ, accs) hash table with
    linear probing; empty-slot claim races resolved via scatter-max of the
    contender index. Returns (table', still_active_mask)."""
    import jax
    import jax.numpy as jnp

    keys_t, bins_t, occ_t, accs_t = table
    mask_cap = cap - 1
    n_acc = len(acc_kinds)
    batch_cap = u_key.shape[0]

    z = u_key.astype(jnp.uint64) ^ (u_bin.astype(jnp.uint64) * jnp.uint64(0xFF51AFD7ED558CCD))
    z = (z ^ (z >> jnp.uint64(33))) * jnp.uint64(0xC4CEB9FE1A85EC53)
    z = z ^ (z >> jnp.uint64(33))
    h0 = (z & jnp.uint64(mask_cap)).astype(jnp.int32)
    seg_pos = jnp.arange(batch_cap, dtype=jnp.int32)

    def probe(i, carry):
        keys_c, bins_c, occ_c, accs_c, active = carry
        cand = (h0 + i) & mask_cap
        cur_key = keys_c[cand]
        cur_bin = bins_c[cand]
        cur_occ = occ_c[cand]
        match = active & cur_occ & (cur_key == u_key) & (cur_bin == u_bin)
        empty_here = active & ~cur_occ
        claim_idx = jnp.where(empty_here, cand, cap)
        claims = jnp.full(cap, -1, dtype=jnp.int32).at[claim_idx].max(seg_pos, mode="drop")
        won = empty_here & (claims[cand] == seg_pos)
        write = match | won
        safe = jnp.where(write, cand, cap)
        keys_c = keys_c.at[safe].set(u_key, mode="drop")
        bins_c = bins_c.at[safe].set(u_bin, mode="drop")
        occ_c = occ_c.at[safe].set(True, mode="drop")
        new_accs = []
        for j in range(n_acc):
            merged = _combine_jnp(acc_kinds[j], accs_c[j][cand], u_accs[j])
            val = jnp.where(match, merged, u_accs[j])
            new_accs.append(accs_c[j].at[safe].set(val, mode="drop"))
        return (keys_c, bins_c, occ_c, tuple(new_accs), active & ~write)

    keys_t, bins_t, occ_t, accs_t, still_active = jax.lax.fori_loop(
        0, max_probes, probe, (keys_t, bins_t, occ_t, accs_t, active0)
    )
    return (keys_t, bins_t, occ_t, accs_t), still_active


@functools.lru_cache(maxsize=None)
def _build_jax(acc_kinds: tuple[str, ...], acc_dtypes: tuple, cap: int, batch_cap: int,
               max_probes: int, emit_cap: int):
    import jax
    import jax.numpy as jnp

    mask_cap = cap - 1
    assert cap & mask_cap == 0, "table capacity must be a power of two"

    def step(state, key, bins, valid, vals):
        keys_t, bins_t, occ_t, accs_t, oflow_t = state
        u_key, u_bin, active0, u_accs = sort_reduce(
            acc_kinds, key, bins, valid, vals, batch_cap
        )
        (keys_t, bins_t, occ_t, accs_t), still_active = probe_merge(
            acc_kinds, (keys_t, bins_t, occ_t, accs_t),
            u_key, u_bin, active0, u_accs, cap, max_probes,
        )
        # overflow accumulates in device state; the host checks it at the
        # next extract/snapshot boundary instead of syncing every batch
        oflow_t = oflow_t + jnp.sum(still_active, dtype=jnp.int32)
        return (keys_t, bins_t, occ_t, accs_t, oflow_t)

    def scan(state, emit_lo, emit_hi, chunk_start):
        """Non-destructive position-chunked read of entries with
        emit_lo <= bin < emit_hi. The host walks chunk_start over
        range(0, cap, emit_cap) so a range larger than emit_cap is never
        truncated (sliding-window combine reads the same bins repeatedly)."""
        keys_t, bins_t, occ_t, accs_t, _oflow = state
        sel = chunk_start + jnp.arange(emit_cap, dtype=jnp.int32)
        # out-of-bounds gathers clamp to cap-1 under jit, which would emit the
        # last slot once per clamped index when emit_cap doesn't divide cap
        in_bounds = sel < cap
        out_valid = in_bounds & occ_t[sel] & (bins_t[sel] >= emit_lo) & (bins_t[sel] < emit_hi)
        return keys_t[sel], bins_t[sel], out_valid, tuple(a[sel] for a in accs_t)

    def free(state, below):
        """Drop every entry with bin < below (sliding-window retention)."""
        keys_t, bins_t, occ_t, accs_t, oflow_t = state
        occ_t = occ_t & ~(bins_t < below)
        return (keys_t, bins_t, occ_t, accs_t, oflow_t)

    def extract(state, emit_lo, emit_hi, free_below):
        """Emit occupied entries with emit_lo <= bin < emit_hi (compacted to
        emit_cap rows); free entries with bin < free_below.

        Compaction is a cumsum-position scatter — O(cap) with cheap TPU
        scatters — instead of a full argsort of the table per window close
        (the previous design's dominant cost: extract fires on nearly every
        watermark under dense event-time streams)."""
        keys_t, bins_t, occ_t, accs_t, oflow_t = state
        emit_mask = occ_t & (bins_t >= emit_lo) & (bins_t < emit_hi)
        total = jnp.sum(emit_mask)
        pos = jnp.cumsum(emit_mask) - 1  # output slot per emitting entry
        # non-emitting entries and overflow beyond emit_cap scatter to the
        # dropped index emit_cap (the drain loop re-reads the leftovers)
        dest = jnp.where(emit_mask & (pos < emit_cap), pos, emit_cap)
        out_key = jnp.zeros(emit_cap, keys_t.dtype).at[dest].set(keys_t, mode="drop")
        out_bin = jnp.zeros(emit_cap, bins_t.dtype).at[dest].set(bins_t, mode="drop")
        out_accs = tuple(
            jnp.zeros(emit_cap, a.dtype).at[dest].set(a, mode="drop") for a in accs_t
        )
        out_valid = jnp.arange(emit_cap, dtype=jnp.int32) < jnp.minimum(total, emit_cap)
        # free expired entries OUTSIDE the emit range immediately; entries in
        # the emit range are freed only once actually emitted, so the drain
        # loop over emit_cap-sized chunks doesn't drop the tail
        emitted = emit_mask & (pos < emit_cap)
        free_mask = (occ_t & (bins_t < free_below) & ~emit_mask) | (
            emitted & (bins_t < free_below)
        )
        occ_t = occ_t & ~free_mask
        return (keys_t, bins_t, occ_t, accs_t, oflow_t), (out_key, out_bin, out_valid, out_accs, total)

    n_acc = len(acc_kinds)

    def _to_i64(a, dtype):
        """Lossless int64 lane for transport. Floats would need a 64-bit
        bitcast, which is unsupported under TPU x64 emulation — the host
        wrapper routes float accumulator sets to the unpacked extract/scan
        paths instead, so this only ever sees integer lanes there."""
        if np.issubdtype(np.dtype(dtype), np.floating):
            return jax.lax.bitcast_convert_type(a.astype(jnp.float64), jnp.int64)
        return a.astype(jnp.int64)

    def extract_packed(state, emit_lo, emit_hi, free_below):
        """Same semantics as extract, but the result is ONE int64 buffer:
        [total, overflow, keys[emit_cap], bins[emit_cap], acc0[emit_cap], ...]

        so the host pays a single device->host transfer per window close.
        Over a remote-device tunnel every sync is a full round trip; the
        unpacked extract's 6+ fetches per close were the round-1 bottleneck
        (~0.47 s per close vs 0.3 ms for the update step itself)."""
        keys_t, bins_t, occ_t, accs_t, oflow_t = state
        emit_mask = occ_t & (bins_t >= emit_lo) & (bins_t < emit_hi)
        total = jnp.sum(emit_mask)
        pos = jnp.cumsum(emit_mask) - 1
        dest = jnp.where(emit_mask & (pos < emit_cap), pos, emit_cap)
        outs = [
            jnp.zeros(emit_cap, jnp.int64).at[dest].set(keys_t, mode="drop"),
            jnp.zeros(emit_cap, jnp.int64).at[dest].set(
                bins_t.astype(jnp.int64), mode="drop"
            ),
        ]
        for a, d in zip(accs_t, acc_dtypes):
            outs.append(
                jnp.zeros(emit_cap, jnp.int64).at[dest].set(_to_i64(a, d), mode="drop")
            )
        emitted = emit_mask & (pos < emit_cap)
        free_mask = (occ_t & (bins_t < free_below) & ~emit_mask) | (
            emitted & (bins_t < free_below)
        )
        occ_t = occ_t & ~free_mask
        header = jnp.stack([total.astype(jnp.int64), oflow_t.astype(jnp.int64)])
        packed = jnp.concatenate([header] + outs)
        return (keys_t, bins_t, occ_t, accs_t, oflow_t), packed

    def scan_packed(state, emit_lo, emit_hi):
        """Non-destructive compacted read of bins in [emit_lo, emit_hi) as one
        packed buffer (sliding-window combine). If total > emit_cap the host
        falls back to the chunked scan."""
        keys_t, bins_t, occ_t, accs_t, oflow_t = state
        emit_mask = occ_t & (bins_t >= emit_lo) & (bins_t < emit_hi)
        total = jnp.sum(emit_mask)
        pos = jnp.cumsum(emit_mask) - 1
        dest = jnp.where(emit_mask & (pos < emit_cap), pos, emit_cap)
        outs = [
            jnp.zeros(emit_cap, jnp.int64).at[dest].set(keys_t, mode="drop"),
            jnp.zeros(emit_cap, jnp.int64).at[dest].set(
                bins_t.astype(jnp.int64), mode="drop"
            ),
        ]
        for a, d in zip(accs_t, acc_dtypes):
            outs.append(
                jnp.zeros(emit_cap, jnp.int64).at[dest].set(_to_i64(a, d), mode="drop")
            )
        header = jnp.stack([total.astype(jnp.int64), oflow_t.astype(jnp.int64)])
        return jnp.concatenate([header] + outs)

    step_j = jax.jit(step, donate_argnums=0)
    extract_j = jax.jit(extract, donate_argnums=0)
    scan_j = jax.jit(scan)
    free_j = jax.jit(free, donate_argnums=0)
    extract_packed_j = jax.jit(extract_packed, donate_argnums=0)
    scan_packed_j = jax.jit(scan_packed)
    return step_j, extract_j, scan_j, free_j, extract_packed_j, scan_packed_j


# =========================================================================
# host-facing wrapper
# =========================================================================


def _drain_extract_rounds(agg, first, next_round, emit_lo: int, free_below: int):
    """Shared drain loop for destructive extracts that return at most
    emit_cap rows per round. ``first`` is the already-fetched first round
    (keys_u64, bins, accs, total); ``next_round()`` dispatches + decodes one
    more round. Termination: a round that covered everything
    (total <= emit_cap), emitted nothing (no progress possible — all
    leftovers outside the emit range), or a non-destructive call
    (free_below <= emit_lo: re-reading would duplicate, not drain)."""
    keys_out, bins_out = [], []
    accs_out: list[list[np.ndarray]] = [[] for _ in agg.acc_dtypes]
    k, b, accs, total = first
    while True:
        if len(k):
            keys_out.append(k)
            bins_out.append(b)
            for i, a in enumerate(accs):
                accs_out[i].append(a)
        if total <= agg.emit_cap or len(k) == 0 or free_below <= emit_lo:
            break
        k, b, accs, total = next_round()
    if not keys_out:
        return (
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int32),
            [np.empty(0, dtype=d) for d in agg.acc_dtypes],
        )
    return combine_by_key_bin(
        agg.acc_kinds,
        np.concatenate(keys_out),
        np.concatenate(bins_out),
        [np.concatenate(a).astype(d) for a, d in zip(accs_out, agg.acc_dtypes)],
    )


class ExtractHandle:
    """In-flight window-close extraction: the device compaction has been
    dispatched and its packed result buffer is copying to host in the
    background. ``result()`` materializes (and runs rare overflow follow-up
    rounds synchronously); ``is_ready()`` is a non-blocking poll so the
    operator can pipeline emission behind subsequent update steps."""

    def __init__(self, agg: "DeviceHashAggregator", packed, emit_lo: int,
                 emit_hi: int, free_below: int):
        self._agg = agg
        self._packed = packed
        self._emit_lo = emit_lo
        self._emit_hi = emit_hi
        self._free_below = free_below

    def is_ready(self) -> bool:
        return self._packed.is_ready()

    def result(self) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        agg = self._agg

        def next_round():
            agg.state, packed = agg._extract_packed(
                agg.state, np.int32(self._emit_lo), np.int32(self._emit_hi),
                np.int32(self._free_below),
            )
            return agg._unpack(np.asarray(packed))

        return _drain_extract_rounds(
            agg, agg._unpack(np.asarray(self._packed)), next_round,
            self._emit_lo, self._free_below,
        )


class ReadyHandle:
    """ExtractHandle-compatible wrapper over an already-materialized result
    (synchronous fallback paths)."""

    def __init__(self, result):
        self._result = result

    def is_ready(self) -> bool:
        return True

    def result(self):
        return self._result


class DeviceHashAggregator:
    """Streaming (bin, key) -> accumulators store.

    backend="jax": state lives in HBM, update/extract are single XLA programs.
    backend="numpy": dict-based host mirror (differential-test oracle and the
    CPU baseline for bench vs_baseline).
    """

    def __init__(
        self,
        acc_kinds: Sequence[str],
        acc_dtypes: Sequence[np.dtype],
        cap: int = 65536,
        batch_cap: int = 8192,
        max_probes: int = 64,
        emit_cap: int = 8192,
        backend: str = "jax",
    ):
        self.acc_kinds = tuple(acc_kinds)
        self.acc_dtypes = tuple(np.dtype(d) for d in acc_dtypes)
        self.cap = cap
        self.batch_cap = batch_cap
        self.max_probes = max_probes
        self.emit_cap = emit_cap
        self.backend = backend
        # the single-buffer packed transport bitcasts float64 -> int64, which
        # TPU x64 emulation cannot compile; float accumulator sets use the
        # unpacked (multi-fetch) extract/scan paths instead
        self._packed_ok = not any(
            np.issubdtype(d, np.floating) for d in self.acc_dtypes
        )
        if backend == "jax":
            (self._step, self._extract, self._scan, self._free,
             self._extract_packed, self._scan_packed) = _build_jax(
                self.acc_kinds, self.acc_dtypes, cap, batch_cap, max_probes, emit_cap
            )
            self.state = self._init_jax_state()
        else:
            self.store: dict[tuple[int, int], list] = {}

    def _unpack(self, arr: np.ndarray) -> tuple[np.ndarray, np.ndarray, list[np.ndarray], int]:
        """Decode one packed extract/scan buffer -> (keys_u64, bins, accs, total)."""
        total, overflow = int(arr[0]), int(arr[1])
        if overflow > 0:
            raise RuntimeError(
                f"device aggregate table overflow ({overflow} entries dropped after "
                f"{self.max_probes} probes; cap={self.cap}) — raise device.table-capacity"
            )
        body = arr[2:].reshape(2 + len(self.acc_dtypes), self.emit_cap)
        cnt = min(total, self.emit_cap)
        keys = body[0, :cnt].copy().view(np.uint64)
        bins = body[1, :cnt].astype(np.int32)
        accs = []
        for i, d in enumerate(self.acc_dtypes):
            lane = body[2 + i, :cnt]
            if np.issubdtype(d, np.floating):
                accs.append(lane.copy().view(np.float64).astype(d))
            else:
                accs.append(lane.astype(d))
        return keys, bins, accs, total

    def _init_jax_state(self):
        import jax.numpy as jnp

        keys = jnp.zeros(self.cap, dtype=jnp.int64)
        bins = jnp.zeros(self.cap, dtype=jnp.int32)
        occ = jnp.zeros(self.cap, dtype=bool)
        accs = tuple(
            jnp.full(self.cap, _identity(k, d), dtype=d)
            for k, d in zip(self.acc_kinds, self.acc_dtypes)
        )
        return (keys, bins, occ, accs, jnp.zeros((), dtype=jnp.int32))

    # ------------------------------------------------------------- update

    def update(self, key_u64: np.ndarray, bins: np.ndarray, vals: Sequence[np.ndarray]) -> None:
        n = len(key_u64)
        if n == 0:
            return
        if self.backend == "numpy":
            self._update_numpy(key_u64, bins, vals)
            return
        for lo in range(0, n, self.batch_cap):
            hi = min(lo + self.batch_cap, n)
            self._update_chunk(key_u64[lo:hi], bins[lo:hi], [v[lo:hi] for v in vals])

    def _update_chunk(self, key_u64, bins, vals) -> None:
        m = len(key_u64)
        B = self.batch_cap
        key = np.zeros(B, dtype=np.int64)
        key[:m] = key_u64.astype(np.uint64).view(np.int64)
        b = np.zeros(B, dtype=np.int32)
        b[:m] = bins
        valid = np.zeros(B, dtype=bool)
        valid[:m] = True
        vs = []
        for v, dt in zip(vals, self.acc_dtypes):
            arr = np.zeros(B, dtype=dt)
            arr[:m] = v
            vs.append(arr)
        self.state = self._step(self.state, key, b, valid, tuple(vs))

    def _check_overflow(self) -> None:
        overflow = int(self.state[4])
        if overflow > 0:
            raise RuntimeError(
                f"device aggregate table overflow ({overflow} entries dropped after "
                f"{self.max_probes} probes; cap={self.cap}) — raise device.table-capacity"
            )

    def _update_numpy(self, key_u64, bins, vals) -> None:
        signed = key_u64.astype(np.uint64).view(np.int64)
        order = np.lexsort((signed, bins))
        k_s, b_s = signed[order], np.asarray(bins)[order]
        vs = [np.asarray(v)[order] for v in vals]
        newseg = np.ones(len(k_s), dtype=bool)
        newseg[1:] = (k_s[1:] != k_s[:-1]) | (b_s[1:] != b_s[:-1])
        starts = np.flatnonzero(newseg)
        ends = np.append(starts[1:], len(k_s))
        for s, e in zip(starts, ends):
            kk = (int(b_s[s]), int(k_s[s]))
            cur = self.store.get(kk)
            parts = []
            for i, kind in enumerate(self.acc_kinds):
                seg = vs[i][s:e]
                red = seg.sum() if kind in ("sum", "count") else (seg.min() if kind == "min" else seg.max())
                if cur is not None:
                    red = (
                        cur[i] + red
                        if kind in ("sum", "count")
                        else (min(cur[i], red) if kind == "min" else max(cur[i], red))
                    )
                parts.append(self.acc_dtypes[i].type(red))
            self.store[kk] = parts

    # ------------------------------------------------------------- extract

    def extract(
        self, emit_lo: int, emit_hi: int, free_below: int
    ) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        """Returns (key_u64, bin, acc_arrays) for bins in [emit_lo, emit_hi);
        frees all entries with bin < free_below. Host loops until drained."""
        if self.backend == "numpy":
            return self._extract_numpy(emit_lo, emit_hi, free_below)
        return self.extract_start(emit_lo, emit_hi, free_below).result()

    def _extract_unpacked(self, emit_lo: int, emit_hi: int, free_below: int):
        """Synchronous extract via the typed (non-packed) device path — used
        for float accumulator sets, where the packed int64 transport's
        float64 bitcast does not compile under TPU x64 emulation."""

        def round_():
            self.state, (k, b, valid, accs, total) = self._extract(
                self.state, np.int32(emit_lo), np.int32(emit_hi), np.int32(free_below)
            )
            valid = np.asarray(valid)
            return (
                np.asarray(k)[valid].view(np.uint64),
                np.asarray(b)[valid],
                [np.asarray(a)[valid] for a in accs],
                int(total),
            )

        out = _drain_extract_rounds(self, round_(), round_, emit_lo, free_below)
        self._check_overflow()
        return out

    def extract_start(self, emit_lo: int, emit_hi: int, free_below: int) -> ExtractHandle:
        """Dispatch a window-close extraction without blocking: the device
        compacts + frees immediately, the packed result streams to host in
        the background. The caller emits later via handle.result()."""
        if not self._packed_ok:
            return ReadyHandle(self._extract_unpacked(emit_lo, emit_hi, free_below))
        self.state, packed = self._extract_packed(
            self.state, np.int32(emit_lo), np.int32(emit_hi), np.int32(free_below)
        )
        try:
            packed.copy_to_host_async()
        except AttributeError:
            pass
        return ExtractHandle(self, packed, emit_lo, emit_hi, free_below)

    def scan_range(self, emit_lo: int, emit_hi: int) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        """Non-destructive read of every entry with bin in [emit_lo, emit_hi)
        — the sliding-window combine path (a bin participates in width/slide
        windows, so reads must not free)."""
        if self.backend == "numpy":
            ks, bs, accs = [], [], [[] for _ in self.acc_kinds]
            for (b, k), parts in self.store.items():
                if emit_lo <= b < emit_hi:
                    ks.append(k)
                    bs.append(b)
                    for i, p in enumerate(parts):
                        accs[i].append(p)
            return (
                np.array(ks, dtype=np.int64).view(np.uint64) if ks else np.empty(0, dtype=np.uint64),
                np.array(bs, dtype=np.int32),
                [np.array(a, dtype=d) for a, d in zip(accs, self.acc_dtypes)],
            )
        if self._packed_ok:
            # fast path: one packed transfer covers the whole range
            packed = np.asarray(self._scan_packed(
                self.state, np.int32(emit_lo), np.int32(emit_hi)))
            k, b, accs, total = self._unpack(packed)
            if total <= self.emit_cap:
                return combine_by_key_bin(self.acc_kinds, k, b, accs)
        else:
            self._check_overflow()
        keys_out, bins_out = [], []
        accs_out: list[list[np.ndarray]] = [[] for _ in self.acc_dtypes]
        for chunk in range(0, self.cap, self.emit_cap):
            k, b, valid, accs = self._scan(
                self.state, np.int32(emit_lo), np.int32(emit_hi), np.int32(chunk)
            )
            valid = np.asarray(valid)
            if valid.any():
                keys_out.append(np.asarray(k)[valid])
                bins_out.append(np.asarray(b)[valid])
                for i, a in enumerate(accs):
                    accs_out[i].append(np.asarray(a)[valid])
        if not keys_out:
            return (
                np.empty(0, dtype=np.uint64),
                np.empty(0, dtype=np.int32),
                [np.empty(0, dtype=d) for d in self.acc_dtypes],
            )
        return combine_by_key_bin(
            self.acc_kinds,
            np.concatenate(keys_out).view(np.uint64),
            np.concatenate(bins_out),
            [np.concatenate(a) for a in accs_out],
        )

    def free_bins_below(self, below: int) -> None:
        """Drop all entries with bin < below."""
        if self.backend == "numpy":
            for kk in [kk for kk in self.store if kk[0] < below]:
                del self.store[kk]
            return
        self.state = self._free(self.state, np.int32(below))

    def _extract_numpy(self, emit_lo, emit_hi, free_below):
        ks, bs, accs = [], [], [[] for _ in self.acc_kinds]
        for (b, k), parts in self.store.items():
            if emit_lo <= b < emit_hi:
                ks.append(k)
                bs.append(b)
                for i, p in enumerate(parts):
                    accs[i].append(p)
        for kk in [kk for kk in self.store if kk[0] < free_below]:
            del self.store[kk]
        return (
            np.array(ks, dtype=np.int64).view(np.uint64) if ks else np.empty(0, dtype=np.uint64),
            np.array(bs, dtype=np.int32),
            [np.array(a, dtype=d) for a, d in zip(accs, self.acc_dtypes)],
        )

    # ------------------------------------------------------------- state sync

    def snapshot(self) -> tuple[np.ndarray, np.ndarray, list[np.ndarray]]:
        """Full host copy of live entries (checkpoint path)."""
        if self.backend == "numpy":
            if not self.store:
                return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int32),
                        [np.empty(0, dtype=d) for d in self.acc_dtypes])
            items = list(self.store.items())
            ks = np.array([k for (_, k), _ in items], dtype=np.int64).view(np.uint64)
            bs = np.array([b for (b, _), _ in items], dtype=np.int32)
            accs = [np.array([p[i] for _, p in items], dtype=d)
                    for i, d in enumerate(self.acc_dtypes)]
            return ks, bs, accs
        keys_t, bins_t, occ_t, accs_t, oflow = self.state
        if int(oflow) > 0:
            self._check_overflow()
        occ = np.asarray(occ_t)
        return combine_by_key_bin(
            self.acc_kinds,
            np.asarray(keys_t)[occ].view(np.uint64),
            np.asarray(bins_t)[occ],
            [np.asarray(a)[occ] for a in accs_t],
        )

    def restore(self, key_u64: np.ndarray, bins: np.ndarray, accs: list[np.ndarray]) -> None:
        if self.backend == "numpy":
            signed = key_u64.astype(np.uint64).view(np.int64)
            self.store = {
                (int(bins[j]), int(signed[j])): [
                    self.acc_dtypes[i].type(accs[i][j]) for i in range(len(self.acc_kinds))
                ]
                for j in range(len(signed))
            }
            return
        self.state = self._init_jax_state()
        self.update(key_u64, bins.astype(np.int32), accs)
