"""Background materialization of device->host fetches.

Over a remote-device link (the TPU tunnel; same shape as a DCN-attached
host), a device->host fetch costs a full round trip even when the copy was
started with ``copy_to_host_async`` — measured 15-60 ms per sync point on
the driver tunnel regardless of buffer size. Materializing on the operator
thread therefore stalls the hot loop once per window close.

This module gives operators a single shared fetch thread: extraction handles
are submitted right after dispatch, the worker thread blocks on the round
trip (numpy/jax release the GIL during the transfer), and the operator polls
``Future.is_ready()`` — a plain Event check — emitting completed closes in
order. The reference has no analog (its operators and state share one
address space); this is the host-runtime half of SURVEY §7's "host-side
async stages feeding device steps".
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional


def wait_buffers_ready(bufs, deadline_s: float = 30.0) -> None:
    """Poll device buffers' is_ready before materializing. Blocking
    np.asarray on a buffer whose async copy is still in flight hits a
    pathological multi-second stall on the remote-device tunnel (measured:
    avg 1.8 s vs ~70 ms copy latency when polled); a 1 ms is_ready loop
    materializes in 0.1 ms once the copy lands. Bounded: past the deadline
    the caller's blocking asarray still raises if the device/link actually
    failed (a bare poll loop would spin forever on a dead tunnel)."""
    limit = time.monotonic() + deadline_s  # lint: waive LR109 — device-fetch wait deadline, not self-measurement
    try:
        for buf in bufs:
            if buf is None:
                continue
            while not buf.is_ready():
                if time.monotonic() > limit:  # lint: waive LR109 — device-fetch wait deadline, not self-measurement
                    return
                time.sleep(0.0002)
    except AttributeError:
        return  # backend without is_ready: fall through to asarray


class Future:
    def __init__(self, fn: Callable):
        self._fn = fn
        self._done = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

    def is_ready(self) -> bool:
        return self._done.is_set()

    def result(self):
        self._done.wait()
        if self._exc is not None:
            raise self._exc
        return self._value

    def _run(self) -> None:
        try:
            self._value = self._fn()
        except BaseException as e:  # noqa: BLE001 - re-raised at result()
            self._exc = e
        self._done.set()


class Prefetcher:
    """A small daemon pool draining a submit queue. Concurrent fetches
    overlap their round trips on the device link (measured ~6x on the
    driver tunnel: 16 ms/fetch serial -> 2.5 ms/fetch at 4 workers), so
    multiple workers matter even though each just blocks on a copy.
    Submitted callables must not mutate shared aggregator state
    (SlotExtractHandle.result reads only snapshotted identities + device
    buffers); completion order is unconstrained — consumers pop their own
    queues in program order and check ``is_ready`` per future."""

    def __init__(self, workers: int = 4):
        self._q: "queue.Queue[Future]" = queue.Queue()
        self._workers = workers
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()

    def _ensure_threads(self) -> None:
        if len(self._threads) < self._workers:
            with self._lock:
                while len(self._threads) < self._workers:
                    t = threading.Thread(
                        target=self._loop,
                        name=f"arroyo-prefetch-{len(self._threads)}",
                        daemon=True,
                    )
                    t.start()
                    self._threads.append(t)

    def _loop(self) -> None:
        while True:
            self._q.get()._run()

    def submit(self, fn: Callable) -> Future:
        self._ensure_threads()
        fut = Future(fn)
        self._q.put(fut)
        return fut


_shared: Optional[Prefetcher] = None
_shared_lock = threading.Lock()


def shared_prefetcher() -> Prefetcher:
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                from ..config import config

                _shared = Prefetcher(config().get("device.prefetch-workers", 8))
    return _shared
