"""Layered configuration.

Mirrors the reference's figment-style loader
(crates/arroyo-rpc/src/config.rs:29-92: compiled default.toml -> config files
-> env overrides) with Python's tomllib and ``ARROYO_TPU__SECTION__KEY``
environment variables. Defaults mirror crates/arroyo-rpc/default.toml.
"""

from __future__ import annotations

import copy
import os
import threading
from typing import Any

try:  # tomllib is stdlib from 3.11; tomli is the same parser for 3.10
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - depends on interpreter
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # type: ignore[assignment]

_DEFAULTS: dict[str, Any] = {
    "pipeline": {
        "source-batch-size": 512,  # default.toml: rows per source flush
        "source-batch-linger-ms": 100,
        "update-aggregate-flush-interval-ms": 1000,
        "allowed-restarts": 20,
        "healthy-duration-ms": 120_000,
        "worker-heartbeat-timeout-ms": 30_000,
        "default-checkpoint-interval-ms": 10_000,
        "chaining": {"enabled": False},
        "compaction": {"enabled": False, "checkpoints-to-compact": 4},
    },
    "worker": {
        "queue-size": 8192,  # rows of in-flight budget per input edge
        "task-slots": 16,
    },
    "engine": {
        # adaptive micro-batch coalescing on the emission path: sub-threshold
        # output batches accumulate in the collector (and, cross-worker, as
        # framed bytes in the data plane's send buffer) until a row/byte/time
        # limit trips or a signal (watermark/barrier/stop/EOF) flushes them.
        # Signals ALWAYS flush first, so ordering, barrier alignment, and
        # byte-exact checkpoint recovery are untouched by coalescing.
        "coalesce": {
            "enabled": True,
            "max-rows": 4096,      # flush once this many rows are pending
            "max-bytes": 1_048_576,  # ... or this many (approximate) bytes
            "max-delay-ms": 5,     # ... or the oldest pending row is this old
        },
    },
    "segment": {
        # whole-segment XLA compilation (engine/segment.py): chained runs
        # marked compilable at plan time trace into ONE jitted call per
        # micro-batch. A segment that fails to trace — or whose first-batch
        # verification is not bit-identical to the interpreted path — falls
        # back per segment with a SEGMENT_FALLBACK event, never a failure.
        "compile": {
            "enabled": True,
            # process-wide LRU of compiled (segment, schema) entries;
            # schema/parallelism changes key new entries rather than
            # mis-executing stale traces
            "cache-max": 32,
            # batches below this many rows (input, or survivors of the
            # hoisted leading filter) run interpreted: measured on the
            # 2-core CPU box, the jit dispatch + XLA call overhead beats
            # per-op numpy only from ~8k rows up (a 4096-row A/B lost 7%).
            # Both paths are verified interchangeable per batch, so mixing
            # by size is free; TPU deployments that stage full device
            # batches can lower this.
            "min-rows": 8192,
        },
    },
    "device": {
        # TPU runtime knobs (no reference equivalent; this is the jax backend)
        "enabled": True,  # lower window aggregates to jax when possible
        "batch-capacity": 8192,  # padded device batch size (rows)
        "table-capacity": 65536,  # slots in the keyed HBM state table
        "max-probes": 64,  # linear-probing rounds in the device hash table
        "emit-capacity": 8192,  # padded rows per window-close extraction
    },
    "checkpoint": {
        "storage-url": "/tmp/arroyo-tpu/checkpoints",
        "interval-ms": 10_000,
        # stuck-checkpoint watchdog: a triggered epoch not globally durable
        # within this window is declared failed, its torn shards subsumed,
        # and the checkpoint retried; after max-consecutive-failures the
        # worker set is restored from the last complete checkpoint. 0 = off.
        "timeout-ms": 600_000,
        "max-consecutive-failures": 3,
        # controller-driven GC: compact + drop old checkpoints every N
        # completed epochs (never past the newest complete one). 0 = off.
        "compaction": {"epochs": 0},
    },
    "state": {
        # tiered state backend (state/spill.py): keep the hot working set
        # in memory and spill cold hash-range partitions as parquet runs
        # (bloom filter + min/max zone maps per run) to checkpoint storage
        # once a subtask's resident state passes the budget. Off by
        # default: operators fall back to fully-resident state.
        "spill": {
            "enabled": False,
            # per-subtask resident-state budget, measured with the same
            # estimator that feeds the arroyo_state_bytes gauges
            "budget-bytes": 64 * 1024 * 1024,
            # hash-range partitions per subtask (rounded up to a power of
            # two); the spill/eviction granularity
            "partition-count": 16,
            # split spilled runs into files of roughly this size; also the
            # compaction output granularity
            "target-file-bytes": 4 * 1024 * 1024,
            # generations per partition before an online compaction merges
            # them (bounds probe read amplification)
            "max-runs": 4,
            # after a spill, keep shrinking until resident state is at or
            # below budget * headroom (a low-water mark, so every breach
            # does not trigger a new spill immediately)
            "headroom": 0.75,
        },
        # checkpoint-artifact checksum verification (state/tables.py,
        # state/integrity.py): "restore" verifies envelopes only on the
        # restore path (the read that matters for correctness), "always"
        # also verifies hot reads (spill probes, compaction inputs),
        # "off" trusts storage end to end
        "integrity": {"verify": "restore"},
    },
    "storage": {
        # shared resilience layer (utils/retry.py) for object-store ops
        "retry": {
            "max-attempts": 4,
            "base-delay-ms": 50,
            "max-delay-ms": 2000,
            "multiplier": 2.0,
            "jitter": 0.5,
        },
    },
    "faults": {
        # deterministic fault injection (arroyo_tpu.faults); empty = off.
        # e.g. "storage.put:fail_once@epoch=2,worker:crash@barrier=3"
        "plan": "",
        "seed": 0,
    },
    "controller": {
        "scheduler": "embedded",
        # size of each job's worker set (start_workers); >1 enables the
        # controller-owned cross-worker checkpoint coordination
        "workers-per-job": 1,
    },
    "fleet": {
        # multi-tenant shared worker pool (controller/fleet.py). A job's
        # slot demand is max(n_workers, parallelism) — one slot per
        # parallel pipeline lane, at least one per worker process. 0 =
        # UNLIMITED synthetic pool: admission always grants and the whole
        # fleet layer is pass-through (the single-tenant default). The
        # node scheduler derives capacity from registered node daemons'
        # live /status slots instead when this is 0.
        "slots": 0,
        # deficit-round-robin admission: slot credit added per tenant per
        # dequeue round (larger jobs accumulate credit across rounds, so
        # a many-small-jobs tenant cannot starve a few-big-jobs tenant)
        "drr-quantum": 1,
        # deterministic (no jitter) exponential backoff after a placement
        # rejection (node 409 / injected admission fault): the job re-
        # queues at the head of its tenant's queue but is ineligible for
        # base * 2^(k-1) seconds after its k-th consecutive rejection
        "requeue-backoff-base-s": 0.5,
        "requeue-backoff-max-s": 30.0,
        # per-job supervision-step budget (ControllerServer.tick): a job
        # whose step overruns it emits JOB_TICK_OVERRUN and is
        # deprioritized (skipped for up to `tick-penalty-max` ticks, then
        # always runs again — never starved). 0 disables the budget.
        "tick-budget-ms": 250,
        "tick-penalty-max": 4,
        "quota": {
            # per-tenant ceilings, applied to EVERY tenant individually
            # (0 = unlimited); override one tenant via
            # fleet.quota.tenants.<name>.max-slots / .max-jobs. A job
            # whose own demand exceeds max-slots is REJECTED (it could
            # never run); a job that merely pushes current usage past the
            # quota QUEUES until a peer finishes.
            "max-slots": 0,
            "max-jobs": 0,
        },
        "autoscale": {
            # fleet-level elasticity: sustained capacity-blocked queue
            # demand (or per-job scale-ups the pool could not place)
            # grows the pool toward demand through the scheduler's
            # provision hook; synthetic pools (embedded/process) apply
            # the new size directly, cluster pools surface it as the
            # arroyo_fleet_target_workers gauge for the node-pool
            # autoscaler to actuate. Same rails as the per-job loop:
            # hysteresis, cooldown, clamped bounds.
            "enabled": False,
            "max-slots": 64,
            "up-ticks": 3,
            "down-ticks": 20,
            "cooldown-s": 15.0,
            # free slots to keep above demand after a resize
            "headroom-slots": 0,
        },
    },
    "profile": {
        # runtime cost attribution (obs/profile.py): per-operator self-time
        # accounting in the task run loop, state-size gauges, and key-skew
        # sketches; cheap enough to stay on in production (the overhead
        # guard test holds the run-loop wrapping under 5% wall)
        "enabled": True,
        "sketch": {
            "capacity": 64,      # space-saving summary entries per subtask
            # count 1/N batches; 1 (default) is row-deterministic under
            # replay regardless of coalescing batch boundaries — sampling
            # >1 is cheaper but boundary-sensitive (see obs/sketch.py)
            "sample-every": 1,
            "topk": 5,           # hot keys exported per operator
        },
    },
    "health": {
        # controller-side health monitors (obs/health.py): rules evaluated
        # every supervision tick over the merged job metrics, with
        # hysteresis — fire after fire-ticks consecutive breaching ticks,
        # clear after clear-ticks healthy ones (no flapping on a metric
        # oscillating around its threshold)
        "enabled": True,
        "fire-ticks": 3,
        "clear-ticks": 5,
        "watermark-lag-max-s": 900.0,
        "backpressure-max": 0.9,
        "queue-transit-p99-max-ms": 1000.0,
        "sink-latency-p99-max-s": 600.0,
        "checkpoint-failure-streak": 2,
        # memory pressure: worst subtask's resident state bytes as a
        # fraction of state.spill.budget-bytes (spill keeps it below 1.0;
        # sustained breach means spill is off, failing, or falling behind)
        "memory-pressure-max": 0.9,
    },
    "autoscaler": {
        # elastic autoscaler (controller/autoscaler.py): closes the loop
        # from the health sensors to worker count through the coordinated
        # checkpoint/drain/restore rescale path. Off by default — turning
        # it on hands the parallelism knob to the control loop.
        "enabled": False,
        "min-parallelism": 1,
        "max-parallelism": 8,
        # hysteresis: consecutive pressured ticks before a scale-up /
        # consecutive proven-headroom ticks before a scale-down
        "up-ticks": 3,
        "down-ticks": 10,
        # step sizing: up multiplies (ceil), down halves (floor), always
        # at least one step and always clamped to the bounds above
        "up-factor": 2.0,
        "down-factor": 0.5,
        # scale-up pressure thresholds over the merged metrics snapshot
        "up-backpressure": 0.8,
        "up-queue-transit-p99-ms": 750.0,
        "up-watermark-lag-s": 30.0,
        "up-sink-latency-p99-s": 30.0,
        # scale-down headroom ceilings (worst-subtask busy%, backpressure)
        "down-busy-max-pct": 25.0,
        "down-backpressure-max": 0.1,
        # cooldown after any worker-set (re)start; exponential backoff
        # after a disrupted scale transition
        "cooldown-s": 30.0,
        "backoff-base-s": 10.0,
        "backoff-multiplier": 2.0,
        "backoff-max-s": 300.0,
    },
    "obs": {
        # structured job event log (obs/events.py): bounded per-job ring
        "events": {"max-per-job": 512},
    },
    "logging": {
        # reference [logging] section: console | json | logfmt
        "format": "console",
        "level": "INFO",
        # install the JobEvent bridge handler: stdlib log records carrying
        # job context (extra={"job_id": ...}) land in the job event feed
        "capture-events": False,
    },
    "api": {"http-port": 5115},
    "admin": {"http-port": 5114},
}


class Config:
    def __init__(self, data: dict[str, Any]):
        self._data = data

    def get(self, path: str, default=None):
        """Dotted-path lookup: config().get("worker.queue-size")."""
        cur: Any = self._data
        for part in path.split("."):
            if not isinstance(cur, dict) or part not in cur:
                return default
            cur = cur[part]
        return cur

    def section(self, name: str) -> dict:
        return self._data.get(name, {})

    def with_overrides(self, overrides: dict[str, Any]) -> "Config":
        data = copy.deepcopy(self._data)
        for path, value in overrides.items():
            _set_path(data, path, value)
        return Config(data)


def _set_path(data: dict, path: str, value):
    parts = path.split(".")
    cur = data
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def _merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def _load() -> Config:
    data = copy.deepcopy(_DEFAULTS)
    paths = ["/etc/arroyo-tpu/config.toml",
             os.path.expanduser("~/.config/arroyo-tpu/config.toml"),
             "arroyo-tpu.toml"]
    env_file = os.environ.get("ARROYO_TPU_CONFIG")
    if env_file:
        paths.append(env_file)
    for path in paths:
        if not os.path.exists(path):
            continue
        if tomllib is None:
            raise RuntimeError(
                f"config file {path} exists but no TOML parser is available "
                f"(need Python >= 3.11 or the tomli package)"
            )
        with open(path, "rb") as f:
            data = _merge(data, tomllib.load(f))
    # ARROYO_TPU__WORKER__QUEUE_SIZE=1024 -> worker.queue-size
    for key, val in os.environ.items():
        if not key.startswith("ARROYO_TPU__"):
            continue
        parts = [p.lower().replace("_", "-") for p in key[len("ARROYO_TPU__"):].split("__")]
        parsed: Any = val
        for conv in (int, float):
            try:
                parsed = conv(val)
                break
            except ValueError:
                continue
        if val.lower() in ("true", "false"):
            parsed = val.lower() == "true"
        _set_path(data, ".".join(parts), parsed)
    return Config(data)


_lock = threading.Lock()
_config: Config | None = None


def config() -> Config:
    global _config
    with _lock:
        if _config is None:
            _config = _load()
        return _config


def update(overrides: dict[str, Any]) -> None:
    """Live-update config (used by tests; reference smoke_tests.rs:46)."""
    global _config
    with _lock:
        base = _config if _config is not None else _load()
        _config = base.with_overrides(overrides)


def reset() -> None:
    global _config
    with _lock:
        _config = None
