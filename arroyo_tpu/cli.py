"""CLI: the single-binary entry point.

Reference: crates/arroyo/src/main.rs:83-123 (clap subcommands run / api /
cluster / worker / visualize). `python -m arroyo_tpu <cmd>`.

  run <file.sql>      embedded cluster: api + controller + worker in-process,
                      ^C checkpoints then stops (reference run.rs:84-118)
  cluster             api + controller, jobs submitted over REST
  api                 REST API only (external controller polls the same DB)
  worker ...          subprocess entry used by the process scheduler
  visualize <file.sql> print the dataflow graph as graphviz dot
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Optional


def _cmd_visualize(args) -> int:
    import arroyo_tpu
    from arroyo_tpu.sql import plan_query

    arroyo_tpu._load_operators()
    with open(args.sql_file) as f:
        pp = plan_query(f.read())
    print(pp.graph.dot())
    return 0


def _cmd_check(args) -> int:
    """Static analysis of a pipeline without running it: plan the SQL, run
    every analyzer pass (arroyo_tpu.analysis), print the full diagnostic
    report (--json: a machine-readable array for CI annotation). Exit 0 =
    clean (warnings allowed unless --strict), 1 = rejected."""
    import arroyo_tpu
    from arroyo_tpu.analysis import (Severity, check_sql, render_json,
                                     render_report, render_sarif)

    arroyo_tpu._load_operators()
    with open(args.sql_file) as f:
        sql = f.read()
    pp, diags = check_sql(sql, parallelism=args.parallelism)
    if args.sarif:
        print(render_sarif(diags))
    elif args.json:
        print(render_json(diags))
    elif diags:
        print(render_report(diags))
    if any(d.severity == Severity.ERROR for d in diags) or pp is None:
        return 1
    if pp is not None and not diags and not args.json and not args.sarif:
        print(f"ok: {len(pp.graph.nodes)} nodes, {len(pp.graph.edges)} edges, "
              "no findings")
    if args.strict and diags:
        return 1
    return 0


def _cmd_evolve(args) -> int:
    """Live pipeline evolution (versioned redeploy): POST the evolved SQL to
    /api/v1/pipelines/<id>/evolve, print the per-node plan-diff classification
    (carried / rebuilt / stateless / dropped), and exit 0 once the controller
    has accepted the drain + blue/green cutover. An incompatible change is
    rejected server-side with AR-series diagnostics and exits 1 — the running
    job is never touched."""
    from arroyo_tpu.api.client import ApiError, ArroyoClient

    with open(args.sql_file) as f:
        query = f.read()
    client = ArroyoClient(args.api)

    def render(payload: dict) -> None:
        cls = payload.get("classifications") or []
        if cls:
            width = max(len(c.get("node_id", "")) for c in cls)
            for c in cls:
                line = f"  {c.get('node_id', ''):<{width}}  {c.get('action', '')}"
                if c.get("from"):
                    line += f"  (from {c['from']})"
                if c.get("detail"):
                    line += f"  -- {c['detail']}"
                print(line)
        for d in payload.get("diagnostics") or []:
            print(f"  {d.get('severity')} {d.get('rule')}: {d.get('message')}")
            if d.get("hint"):
                print(f"    hint: {d['hint']}")

    try:
        resp = client.evolve_pipeline(args.pipeline_id, query)
    except ApiError as e:
        payload = e.payload if isinstance(e.payload, dict) else {}
        print(payload.get("error") or f"evolve failed: {e}", file=sys.stderr)
        render(payload)
        return 1
    if resp.get("noop"):
        print(f"pipeline {args.pipeline_id}: query unchanged, nothing to do")
        return 0
    print(f"evolution accepted: pipeline {args.pipeline_id} -> "
          f"version {resp.get('version')} (job {resp.get('job_id')})")
    render(resp)
    return 0


def _cmd_lint(args) -> int:
    """Repo lint + replay-soundness audit: AST checks over this codebase's
    own invariants (arroyo_tpu.analysis.repo_lint + state_audit; --json: a
    machine-readable array for CI annotation). Exit 1 on any unwaived
    finding."""
    import arroyo_tpu
    from arroyo_tpu.analysis import (lint_paths, render_json, render_report,
                                     render_sarif)

    pkg_dir = os.path.dirname(os.path.abspath(arroyo_tpu.__file__))
    root = os.path.dirname(pkg_dir)
    paths = args.paths or [pkg_dir]
    diags = lint_paths(paths, root=root)
    if args.sarif:
        print(render_sarif(diags))
        return 1 if diags else 0
    if args.json:
        print(render_json(diags))
        return 1 if diags else 0
    if diags:
        print(render_report(diags))
        return 1
    print("lint clean")
    return 0


def _cmd_fsck(args) -> int:
    """Offline checkpoint-chain verifier (disaster-recovery fsck): walk every
    epoch of the job under the checkpoint store — marker completeness and
    checksum, sidecar and table-file envelopes, spill-run liveness and
    footers, evolution-mapping pairing, orphans — and print the shared
    diagnostic report (--json / --sarif for CI). Exit 0 = the chain is
    restorable (warnings allowed), 1 = at least one artifact is corrupt,
    torn, or missing (FS-series ERROR)."""
    from arroyo_tpu.analysis import (Severity, render_json, render_report,
                                     render_sarif)
    from arroyo_tpu.config import config
    from arroyo_tpu.state.integrity import fsck_job

    storage_url = args.storage_url or str(config().get("checkpoint.storage-url"))
    diags = fsck_job(storage_url, args.job_id)
    if args.sarif:
        print(render_sarif(diags))
    elif args.json:
        print(render_json(diags))
    elif diags:
        print(render_report(diags))
    if any(d.severity == Severity.ERROR for d in diags):
        return 1
    if not diags and not args.json and not args.sarif:
        print(f"fsck clean: job {args.job_id} checkpoint chain verified")
    return 0


def _cmd_run(args) -> int:
    import arroyo_tpu
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import scheduler_for

    arroyo_tpu._load_operators()
    with open(args.sql_file) as f:
        sql = f.read()
    # plan (and static-analyze) up front: a rejected pipeline prints its
    # diagnostics here instead of spinning up a cluster that dies "Failed"
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.lexer import SqlError

    try:
        plan_query(sql)
    except SqlError as e:
        print(f"pipeline rejected at plan time:\n{e}", file=sys.stderr)
        return 2
    db = Database(args.db or ":memory:")
    api = ApiServer(db, port=args.api_port).start()
    controller = ControllerServer(db, scheduler_for(args.scheduler, db)).start()
    pid = db.create_pipeline(os.path.basename(args.sql_file), sql, args.parallelism)
    jid = db.create_job(pid)
    print(f"pipeline {pid} job {jid} (api on :{api.port})", file=sys.stderr)

    stopping = threading.Event()

    def on_sigint(_sig, _frm):
        if stopping.is_set():
            os._exit(130)
        stopping.set()
        print("stopping with a final checkpoint (^C again to force)", file=sys.stderr)
        db.update_job(jid, desired_stop="checkpoint")

    signal.signal(signal.SIGINT, on_sigint)
    try:
        state = controller.wait_for_state(
            jid, "Finished", "Stopped", "Failed", timeout=args.timeout
        )
        print(f"job {jid}: {state}", file=sys.stderr)
        return 0 if state in ("Finished", "Stopped") else 1
    finally:
        controller.stop()
        api.stop()


def _cmd_cluster(args) -> int:
    import arroyo_tpu
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import ControllerServer, Database
    from arroyo_tpu.controller.scheduler import scheduler_for
    from arroyo_tpu.server_common import AdminServer, init_logging

    init_logging()
    arroyo_tpu._load_operators()
    from arroyo_tpu.config import config as _cfg

    AdminServer("cluster", port=_cfg().get("admin.http-port", 0)).start()
    db = Database(args.db or ":memory:")
    api = ApiServer(db, port=args.api_port).start()
    controller = ControllerServer(db, scheduler_for(args.scheduler, db)).start()
    print(f"cluster up: api on :{api.port}", file=sys.stderr)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        controller.stop()
        api.stop()
        return 0


def _cmd_api(args) -> int:
    from arroyo_tpu.api import ApiServer
    from arroyo_tpu.controller import Database

    db = Database(args.db or ":memory:")
    api = ApiServer(db, port=args.api_port).start()
    print(f"api on :{api.port}", file=sys.stderr)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        api.stop()
        return 0


def _cmd_worker(args) -> int:
    """Worker subprocess (reference `arroyo worker` spawned by the process
    scheduler): runs the engine, speaks the JSON-lines protocol on
    stdin/stdout (scheduler.py docstring)."""
    import arroyo_tpu
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.sql import plan_query
    from arroyo_tpu.sql.planner import set_parallelism

    arroyo_tpu._load_operators()
    from arroyo_tpu.server_common import AdminServer

    # per-worker admin endpoint on an ephemeral port (reference: every
    # service runs one, arroyo-server-common lib.rs:280)
    AdminServer("worker", port=0).start()

    def emit(obj: dict) -> None:
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    if getattr(args, "udfs_file", None):
        from arroyo_tpu.compiler import activate_udf_specs

        with open(args.udfs_file) as f:
            activate_udf_specs(json.load(f))
    if not getattr(args, "graph_file", None) and not getattr(args, "sql_file", None):
        print("worker: one of --sql-file / --graph-file is required", file=sys.stderr)
        return 2
    if getattr(args, "graph_file", None):
        # pre-planned IR shipped by the control plane: no local re-planning
        from arroyo_tpu.graph import Graph

        with open(args.graph_file) as f:
            graph = Graph.loads(f.read())
    else:
        with open(args.sql_file) as f:
            sql = f.read()
        pp = plan_query(sql)
        if args.parallelism > 1:
            set_parallelism(pp.graph, args.parallelism)
        graph = pp.graph
    n_workers = int(getattr(args, "n_workers", None) or 1)
    network = None
    assignment = None
    started = threading.Event()
    if n_workers > 1:
        # one worker of a multi-worker set: bind the data plane now (the
        # port rides the "started" event), hold task startup until the
        # controller distributes the full peer table
        from arroyo_tpu.engine.network import NetworkManager

        with open(args.assignment_file) as f:
            assignment = {(nid, int(sub)): int(w) for nid, sub, w in json.load(f)}
        network = NetworkManager(host=args.dp_bind or "127.0.0.1")
    eng = Engine(
        graph, job_id=args.job_id,
        restore_epoch=args.restore_epoch,
        storage_url=args.storage_url or None,
        assignment=assignment,
        worker_index=int(getattr(args, "worker_index", None) or 0),
        network=network,
    )
    # relay epoch-lifecycle spans AND structured job events to the
    # controller so ITS recorders (behind /traces, /events, and the wedge
    # diagnostics) hold this worker's timelines and event feed too
    eng.relay_obs = True
    if n_workers > 1:
        emit({"event": "started", "dp_port": network.port,
              "worker_index": int(args.worker_index or 0)})
    else:
        eng.start()
        started.set()
        emit({"event": "started"})
    fatal: list[str] = []

    def read_commands() -> None:
        import traceback as _tb

        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                cmd = json.loads(line)
            except json.JSONDecodeError:
                continue
            if cmd.get("cmd") == "checkpoint":
                eng.trigger_checkpoint(int(cmd["epoch"]), then_stop=bool(cmd.get("then_stop")))
            elif cmd.get("cmd") == "stop":
                eng.stop()
            elif cmd.get("cmd") == "commit":
                # phase 2 of the controller's 2PC: the epoch's job-level
                # metadata is durable across ALL workers
                eng.deliver_commit(int(cmd["epoch"]))
            elif cmd.get("cmd") == "peers" and network is not None:
                network.set_peers({
                    int(k): (v[0], int(v[1]))
                    for k, v in (cmd.get("peers") or {}).items()
                })
                if not started.is_set():
                    try:
                        eng.start()
                    except Exception:  # noqa: BLE001 - surface as a failed event
                        # a build/restore error here would otherwise die with
                        # this thread while the main loop keeps heartbeating —
                        # an invisible wedge the controller can't diagnose
                        fatal.append(_tb.format_exc())
                        return
                    started.set()

    threading.Thread(target=read_commands, daemon=True).start()
    from arroyo_tpu.connectors.preview import take_preview_rows

    last_hb = 0.0
    while True:
        with eng._lock:
            done = (started.is_set() and eng._n_tasks
                    and len(eng._finished_tasks) + len(eng._failed) >= eng._n_tasks)
            failed = list(eng._failed)
        send_hb = time.monotonic() - last_hb > 1.0
        if send_hb:
            # chaos hook: dropping heartbeats (worker.heartbeat:drop) models
            # a hung-but-not-dead worker; the controller's heartbeat-timeout
            # detection must declare it lost and recover (metrics ride the
            # same cadence, so a "hung" worker goes silent on both)
            from arroyo_tpu.faults import fault_point

            last_hb = time.monotonic()
            if (fault_point("worker.heartbeat") or (None,))[0] == "drop":
                send_hb = False
        # ONE drain for every relay stream — spans, job events, throttled
        # metrics, coordinator acks / completed epochs. The ordering rules
        # (spans and events strictly before coordinator acks) live in
        # Engine.drain_relay, not in per-stream loops here.
        for ev in eng.drain_relay(include_metrics=send_hb):
            emit(ev)
        if send_hb:
            emit({"event": "heartbeat"})
        lines = take_preview_rows(args.job_id)
        if lines:
            emit({"event": "sink_data", "lines": lines})
        if fatal:
            emit({"event": "failed", "error": fatal[0][-2000:]})
            return 1
        if failed:
            emit({"event": "failed", "error": failed[0].error or "task failed"})
            return 1
        if done:
            emit({"event": "finished"})
            return 0
        time.sleep(0.05)


def _cmd_trace(args) -> int:
    """Export a job's epoch-lifecycle traces (obs.trace): Chrome
    trace-event JSON (open in chrome://tracing or Perfetto's legacy-UI
    importer) or, with --report, human-readable per-epoch timelines naming
    any stuck subtask. Reads the controller DB directly (--db) or the
    cluster API (--api)."""
    import urllib.request

    from arroyo_tpu.obs import trace as obs_trace

    job_events: list = []
    if args.db:
        from arroyo_tpu.controller import Database

        db = Database(args.db)
        rows = db.list_traces(args.job_id, epoch=args.epoch)
        by_epoch = {r["epoch"]: r["events"] for r in rows}
        job_events = db.list_events(args.job_id)
    else:
        url = (f"{args.api.rstrip('/')}/api/v1/jobs/{args.job_id}"
               "/traces?format=events")
        if args.epoch is not None:
            url += f"&epoch={args.epoch}"
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = json.load(r)
        by_epoch = {int(e): evs
                    for e, evs in (payload.get("epochs") or {}).items()}
        try:
            with urllib.request.urlopen(
                    f"{args.api.rstrip('/')}/api/v1/jobs/{args.job_id}"
                    "/events", timeout=10) as r:
                job_events = json.load(r).get("data") or []
        except OSError:
            job_events = []
    if not by_epoch:
        print(f"no trace events recorded for job {args.job_id}",
              file=sys.stderr)
        return 1
    if args.report:
        for e in sorted(by_epoch):
            print(obs_trace.timeline_report(args.job_id, e, by_epoch[e]))
        return 0
    chrome = obs_trace.chrome_trace(args.job_id, by_epoch,
                                    job_events=job_events)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(chrome, f)
        print(f"wrote {len(chrome['traceEvents'])} trace events to "
              f"{args.out}", file=sys.stderr)
    else:
        print(json.dumps(chrome))
    return 0


def _cmd_logs(args) -> int:
    """Per-job structured event feed (obs.events): operator panics, set
    restores, wedged epochs, commit re-deliveries, rescales, and health
    transitions, each with its {node, subtask, worker, epoch} scope. Reads
    the controller DB directly (--db) or the cluster API; --follow tails
    new events until the job reaches a terminal state."""
    import urllib.error
    import urllib.request

    from arroyo_tpu.obs.events import render_event

    db = None
    if args.db:
        from arroyo_tpu.controller import Database

        db = Database(args.db)

    # state is the job's FSM state, "missing" for a job id the DB/API does
    # not know (so --follow can error out instead of tailing a typo
    # forever), or None when the API state probe transiently failed
    def fetch(after_seq: int) -> tuple[list[dict], Optional[str]]:
        if db is not None:
            job = db.get_job(args.job_id)
            return (db.list_events(args.job_id, level=args.level,
                                   after_seq=after_seq),
                    job["state"] if job else "missing")
        base = args.api.rstrip("/")
        url = f"{base}/api/v1/jobs/{args.job_id}/events?after={after_seq}"
        if args.level:
            url += f"&level={args.level}"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                payload = json.load(r)
        except OSError:
            if not args.follow:
                raise  # one-shot read: surface the API failure
            return [], None  # tailing: keep polling through the blip
        try:
            with urllib.request.urlopen(
                    f"{base}/api/v1/jobs/{args.job_id}", timeout=10) as r:
                state = json.load(r).get("state")
        except urllib.error.HTTPError as e:
            state = "missing" if e.code == 404 else None
        except OSError:
            state = None
        return payload.get("data") or [], state

    last_seq = 0
    printed = 0
    while True:
        events, state = fetch(last_seq)
        for ev in events:
            print(render_event(ev))
            last_seq = max(last_seq, int(ev.get("seq") or 0))
            printed += 1
        if state == "missing" and not printed:
            print(f"no such job {args.job_id}", file=sys.stderr)
            return 1
        if not args.follow:
            if not printed:
                print(f"no events recorded for job {args.job_id}",
                      file=sys.stderr)
                return 1
            return 0
        if state in ("Failed", "Finished", "Stopped", "missing"):
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _cmd_explain(args) -> int:
    """EXPLAIN ANALYZE for a job: render the logical plan sink-first,
    annotated with the live runtime cost profile (per-operator busy%,
    rows/s, self-time by category, state rows/bytes, top-k hot keys,
    late-row drops) merged across every worker of the set. Reads the
    controller DB directly (--db) or the cluster API (--api)."""
    import urllib.error
    import urllib.request

    from arroyo_tpu.obs.profile import job_profile, render_explain

    def plan_nodes_edges(sql, parallelism):
        """Plan the pipeline the way the engine runs it (the shared
        executed_graph_view: parallelism + chaining applied) so plan node
        ids line up with runtime metrics; a plan failure (e.g.
        unregistered UDFs) degrades to a plain per-operator profile
        listing instead of erroring out."""
        try:
            import arroyo_tpu
            from arroyo_tpu.sql.planner import executed_graph_view

            arroyo_tpu._load_operators()
            return executed_graph_view(sql, parallelism)
        except Exception:  # noqa: BLE001 - plan is decoration, profile is data
            return [], []

    if args.db:
        from arroyo_tpu.controller import Database

        db = Database(args.db)
        job = db.get_job(args.job_id)
        if job is None:
            print(f"job {args.job_id} not found", file=sys.stderr)
            return 1
        profile = (db.get_profile(args.job_id)
                   or job_profile(db.get_metrics(args.job_id)))
        pipeline = db.get_pipeline(job["pipeline_id"]) or {}
        nodes, edges = plan_nodes_edges(
            pipeline.get("query", ""), int(pipeline.get("parallelism") or 1))
    else:
        base = args.api.rstrip("/")

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.load(r)

        try:
            job = get(f"/api/v1/jobs/{args.job_id}")
        except urllib.error.HTTPError:
            print(f"job {args.job_id} not found", file=sys.stderr)
            return 1
        profile = get(f"/api/v1/jobs/{args.job_id}/profile").get("data") or {}
        nodes, edges = [], []
        try:
            g = get(f"/api/v1/pipelines/{job['pipeline_id']}/graph")
            nodes, edges = g.get("nodes", []), g.get("edges", [])
        except (urllib.error.HTTPError, urllib.error.URLError, KeyError):
            pass
    if not profile:
        print(f"no profile snapshot recorded yet for {args.job_id} "
              "(workers report ~1/s once running)", file=sys.stderr)
    print(render_explain(nodes, edges, profile or {}, job))
    return 0


def _cmd_top(args) -> int:
    """Live per-operator job view from the controller DB: rows/s in/out,
    backpressure, queue-transit p99, watermark lag, and the last epoch's
    duration with its dominant checkpoint phase. Refreshes until the job
    reaches a terminal state (--once prints a single frame)."""
    import urllib.error
    import urllib.request

    from arroyo_tpu.obs import topview

    db = None
    if args.db:
        from arroyo_tpu.controller import Database

        db = Database(args.db)

    def fetch():
        if db is not None:
            job = db.get_job(args.job_id)
            if job is None:
                return None, None, None
            if job.get("state") == "Queued":
                # admission-queue position from the controller's persisted
                # fleet snapshot (the API path attaches it server-side)
                pos = db.fleet_queue_position(args.job_id)
                if pos is not None:
                    job["queue_position"] = pos
            return (job, db.get_metrics(args.job_id),
                    db.list_checkpoints(args.job_id))
        base = args.api.rstrip("/")

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.load(r)

        try:
            job = get(f"/api/v1/jobs/{args.job_id}")
        except urllib.error.HTTPError:
            return None, None, None
        metrics = get(f"/api/v1/jobs/{args.job_id}/metrics").get("data")
        ckpts = get(f"/api/v1/jobs/{args.job_id}/checkpoints").get("data")
        return job, metrics, ckpts

    while True:
        job, metrics, ckpts = fetch()
        if job is None or "state" not in job:
            print(f"job {args.job_id} not found", file=sys.stderr)
            return 1
        frame = topview.render(job, metrics, ckpts)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        if job["state"] in ("Failed", "Finished", "Stopped"):
            return 0
        time.sleep(args.interval)


def _cmd_node(args) -> int:
    """Per-machine node daemon (reference `arroyo node`): registers with the
    cluster API and launches worker processes the controller places here."""
    import arroyo_tpu
    from arroyo_tpu.controller.node import NodeServer

    arroyo_tpu._load_operators()
    node = NodeServer(args.controller, slots=args.slots, port=args.port,
                      host=args.host, advertise_host=args.advertise_host).start()
    print(f"node {node.node_id} on :{node.port} -> {args.controller}", file=sys.stderr)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        node.stop()
        return 0


def _cmd_compile_service(args) -> int:
    """Standalone UDF compile service (reference `arroyo-compiler-service`):
    builds cpp UDF sources into dylibs and publishes them to the artifact
    store; the API delegates here when compiler.endpoint is configured."""
    from arroyo_tpu.compiler import CompileServer

    srv = CompileServer(host=args.host, port=args.port,
                        artifacts_url=args.artifacts_url).start()
    print(f"compile service on :{srv.port} -> {srv.service.artifacts_url}",
          file=sys.stderr)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()
        return 0


def main(argv: Optional[list[str]] = None) -> int:
    # Honor JAX_PLATFORMS even where a site-level shim force-selects a
    # platform at interpreter startup (the axon TPU tunnel does this and is
    # single-client: worker subprocesses spawned by the test/process
    # scheduler must stay on the platform the parent chose for them, or a
    # killed worker wedges the tunnel grant for every later worker).
    if os.environ.get("JAX_PLATFORMS"):
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    p = argparse.ArgumentParser(prog="arroyo_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser("run", help="run a SQL pipeline with an embedded cluster")
    rp.add_argument("sql_file")
    rp.add_argument("--parallelism", type=int, default=1)
    rp.add_argument("--scheduler", default="embedded", choices=["embedded", "process"])
    rp.add_argument("--api-port", type=int, default=0)
    rp.add_argument("--db", default=None)
    rp.add_argument("--timeout", type=float, default=86400)
    rp.set_defaults(fn=_cmd_run)

    cp = sub.add_parser("cluster", help="api + controller, submit jobs over REST")
    cp.add_argument("--scheduler", default="process",
                    choices=["embedded", "process", "node", "kubernetes"])
    cp.add_argument("--api-port", type=int, default=5115)
    cp.add_argument("--db", default=None)
    cp.set_defaults(fn=_cmd_cluster)

    ap = sub.add_parser("api", help="REST API server only")
    ap.add_argument("--api-port", type=int, default=5115)
    ap.add_argument("--db", default=None)
    ap.set_defaults(fn=_cmd_api)

    wp = sub.add_parser("worker", help="worker subprocess (used by process scheduler)")
    wp.add_argument("--sql-file", default=None)
    wp.add_argument("--graph-file", default=None)
    wp.add_argument("--job-id", required=True)
    wp.add_argument("--parallelism", type=int, default=1)
    wp.add_argument("--restore-epoch", type=int, default=None)
    wp.add_argument("--storage-url", default=None)
    wp.add_argument("--udfs-file", default=None)
    wp.add_argument("--worker-index", type=int, default=None,
                    help="this worker's index within a multi-worker set")
    wp.add_argument("--n-workers", type=int, default=1,
                    help="size of the job's worker set")
    wp.add_argument("--assignment-file", default=None,
                    help="JSON [[node_id, subtask, worker], ...] placement")
    wp.add_argument("--dp-bind", default=None,
                    help="bind host for the cross-worker data plane")

    np_ = sub.add_parser("node", help="per-machine worker launcher daemon")
    np_.add_argument("--controller", required=True,
                     help="cluster API base url, e.g. http://host:5115")
    np_.add_argument("--slots", type=int, default=16)
    np_.add_argument("--port", type=int, default=0)
    np_.add_argument("--host", default="0.0.0.0",
                     help="bind address for the node's HTTP surface")
    np_.add_argument("--advertise-host", default=None,
                     help="routable hostname the controller should dial "
                          "(default: the bind host)")
    np_.set_defaults(fn=_cmd_node)
    wp.set_defaults(fn=_cmd_worker)

    vp = sub.add_parser("visualize", help="print the dataflow graph as dot")
    vp.add_argument("sql_file")
    vp.set_defaults(fn=_cmd_visualize)

    tp = sub.add_parser("trace", help="export a job's checkpoint-epoch "
                                      "traces (Chrome trace-event JSON)")
    tp.add_argument("job_id")
    tp.add_argument("--api", default="http://127.0.0.1:5115",
                    help="cluster API base url")
    tp.add_argument("--db", default=None,
                    help="read the controller DB file directly instead")
    tp.add_argument("--epoch", type=int, default=None,
                    help="restrict to one epoch")
    tp.add_argument("--out", "-o", default=None,
                    help="write the JSON here instead of stdout")
    tp.add_argument("--report", action="store_true",
                    help="print human-readable per-epoch timelines instead")
    tp.set_defaults(fn=_cmd_trace)

    op = sub.add_parser("top", help="live per-operator job view "
                                    "(throughput, backpressure, watermark "
                                    "lag, checkpoint phases)")
    op.add_argument("job_id")
    op.add_argument("--api", default="http://127.0.0.1:5115",
                    help="cluster API base url")
    op.add_argument("--db", default=None,
                    help="read the controller DB file directly instead")
    op.add_argument("--interval", type=float, default=2.0,
                    help="refresh period seconds")
    op.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clearing)")
    op.set_defaults(fn=_cmd_top)

    lg = sub.add_parser("logs", help="structured job event feed (operator "
                                     "panics, restores, wedged epochs, "
                                     "health transitions)")
    lg.add_argument("job_id")
    lg.add_argument("--api", default="http://127.0.0.1:5115",
                    help="cluster API base url")
    lg.add_argument("--db", default=None,
                    help="read the controller DB file directly instead")
    lg.add_argument("--level", default=None,
                    choices=["DEBUG", "INFO", "WARN", "ERROR"],
                    help="minimum level to show")
    lg.add_argument("--follow", "-f", action="store_true",
                    help="keep tailing new events until the job ends")
    lg.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll period seconds")
    lg.set_defaults(fn=_cmd_logs)

    ep = sub.add_parser("explain", help="EXPLAIN ANALYZE: the logical plan "
                                        "annotated with live per-operator "
                                        "busy%, rows/s, state sizes, and "
                                        "hot keys")
    ep.add_argument("job_id")
    ep.add_argument("--api", default="http://127.0.0.1:5115",
                    help="cluster API base url")
    ep.add_argument("--db", default=None,
                    help="read the controller DB file directly instead")
    ep.set_defaults(fn=_cmd_explain)

    ev = sub.add_parser("evolve", help="live pipeline evolution: plan-diff "
                                       "the new SQL, carry proven state, "
                                       "blue/green cutover at a barrier")
    ev.add_argument("pipeline_id")
    ev.add_argument("sql_file", help="file holding the evolved SQL")
    ev.add_argument("--api", default="http://127.0.0.1:5115",
                    help="cluster API base url")
    ev.set_defaults(fn=_cmd_evolve)

    kp = sub.add_parser("check", help="static analysis of a SQL pipeline "
                                      "(plan + dataflow validation, no run)")
    kp.add_argument("sql_file")
    kp.add_argument("--parallelism", type=int, default=1)
    kp.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    kp.add_argument("--json", action="store_true",
                    help="machine-readable diagnostics (rule, severity, "
                         "site, message, hint); exit codes unchanged")
    kp.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 diagnostics for CI inline "
                         "annotations; exit codes unchanged")
    kp.set_defaults(fn=_cmd_check)

    lp = sub.add_parser("lint", help="repo lint + replay-soundness audit: "
                                     "AST invariant checks over this "
                                     "codebase (tools/lint.sh entry)")
    lp.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the arroyo_tpu package)")
    lp.add_argument("--json", action="store_true",
                    help="machine-readable diagnostics (rule, severity, "
                         "site, message, hint); exit codes unchanged")
    lp.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 diagnostics for CI inline "
                         "annotations; exit codes unchanged")
    lp.set_defaults(fn=_cmd_lint)

    fp = sub.add_parser("fsck", help="offline checkpoint-chain verifier: "
                                     "checksums, completeness, spill-run "
                                     "liveness, orphans (FS-series rules)")
    fp.add_argument("job_id", help="job whose checkpoint chain to verify")
    fp.add_argument("--storage-url", default=None,
                    help="checkpoint store prefix (default: "
                         "checkpoint.storage-url from config)")
    fp.add_argument("--json", action="store_true",
                    help="machine-readable diagnostics (rule, severity, "
                         "site, message, hint); exit codes unchanged")
    fp.add_argument("--sarif", action="store_true",
                    help="SARIF 2.1.0 diagnostics for CI inline "
                         "annotations; exit codes unchanged")
    fp.set_defaults(fn=_cmd_fsck)

    cs = sub.add_parser("compile-service",
                        help="standalone native-UDF compile service")
    cs.add_argument("--port", type=int, default=5117)
    cs.add_argument("--host", default="0.0.0.0")
    cs.add_argument("--artifacts-url", default=None,
                    help="storage prefix for built dylibs (local or s3://)")
    cs.set_defaults(fn=_cmd_compile_service)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
