"""Service plumbing: logging init + admin HTTP server.

Reference: crates/arroyo-server-common/src/lib.rs — init_logging (:53,
json/logfmt/console formats from the [logging] config section) and the
per-service admin HTTP server (:280, default port 5114) exposing /metrics,
/status, /config (heap profiling is jemalloc-specific and has no analog
here).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_START_TIME = time.time()


# event-scope attributes a record may carry (via ``extra=``; the JobEvent
# bridge reads the same names) — emitted by BOTH structured formatters
_EVENT_FIELDS = ("job_id", "node", "subtask", "worker", "epoch")


def _record_fields(formatter: logging.Formatter,
                   record: logging.LogRecord) -> dict:
    """The shared field set both structured formatters render, in order.
    One extraction point means the json and logfmt views of a record can
    never disagree on names or values (unit-tested for parity)."""
    out = {
        "ts": formatter.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
        "level": record.levelname,
        "target": record.name,
        "message": record.getMessage(),
    }
    code = getattr(record, "event_code", None)
    if code is not None:
        out["code"] = str(code)
    for field in _EVENT_FIELDS:
        v = getattr(record, field, None)
        if v is not None:
            out[field] = v
    return out


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = _record_fields(self, record)
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"))


class _LogfmtFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        parts = []
        for k, v in _record_fields(self, record).items():
            if k == "level":
                v = str(v).lower()
            v = str(v)
            # '=' and '\' also force quoting (`msg=retries=3` would parse
            # ambiguously), and newlines must never split a record across
            # physical lines; backslashes escape before quotes do
            if v == "" or any(c in v for c in ' "=\\\n\r'):
                v = ('"' + v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n").replace("\r", "\\r") + '"')
            parts.append(f"{'msg' if k == 'message' else k}={v}")
        return " ".join(parts)


def init_logging(fmt: Optional[str] = None, level: Optional[str] = None,
                 capture_events: Optional[bool] = None) -> None:
    """fmt: console | json | logfmt (config [logging] section analog).

    ``logging.capture-events`` (or capture_events=True) additionally
    installs the JobEvent bridge handler: stdlib records carrying job
    context (``extra={"job_id": ...}``) land in the structured job event
    feed (obs/events.py) next to the engine's own events."""
    from .config import config

    fmt = fmt or config().get("logging.format", "console")
    level = level or config().get("logging.level", "INFO")
    if capture_events is None:
        capture_events = bool(config().get("logging.capture-events"))
    root = logging.getLogger()
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(_JsonFormatter())
    elif fmt == "logfmt":
        handler.setFormatter(_LogfmtFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s: %(message)s", "%H:%M:%S"
        ))
    root.addHandler(handler)
    if capture_events:
        from .obs.events import install_bridge

        install_bridge(root)


class AdminServer:
    """Per-process admin endpoint: /metrics (prometheus), /status, /config,
    /debug/pprof/heap (tracemalloc snapshot — the reference serves jemalloc
    heap profiles from the same path, arroyo-server-common/src/lib.rs:257)
    and /debug/threads (py-spy-style stack dump)."""

    def __init__(self, service: str, port: int = 0, host: str = "127.0.0.1"):
        self.service = service
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    from .metrics import registry

                    body = registry.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/status":
                    body = json.dumps({
                        "service": outer.service,
                        "uptime_s": round(time.time() - _START_TIME, 1),
                        "healthy": True,
                    }).encode()
                    ctype = "application/json"
                elif path == "/config":
                    from .config import config

                    body = json.dumps(config()._data, default=str).encode()
                    ctype = "application/json"
                elif path == "/debug/pprof/heap":
                    import tracemalloc

                    q = self.path.split("?", 1)[1] if "?" in self.path else ""
                    if q == "stop":
                        tracemalloc.stop()
                        body = json.dumps({"status": "tracing stopped"}).encode()
                    elif not tracemalloc.is_tracing():
                        # lineno statistics only render one frame, so one is
                        # all we pay for; ?stop disables tracing again
                        tracemalloc.start(1)
                        body = json.dumps({
                            "status": "tracing started; fetch again for a "
                                      "snapshot, ?stop to disable"
                        }).encode()
                    else:
                        snap = tracemalloc.take_snapshot()
                        stats = snap.statistics("lineno")
                        body = json.dumps({
                            "total_kb": round(sum(s_.size for s_ in stats) / 1024, 1),
                            "top": [
                                {"site": str(s_.traceback), "kb": round(s_.size / 1024, 1),
                                 "count": s_.count}
                                for s_ in stats[:50]
                            ],
                        }).encode()
                    ctype = "application/json"
                elif path == "/debug/threads":
                    import sys as _sys
                    import traceback as _tb

                    frames = _sys._current_frames()
                    dump = {}
                    for t in threading.enumerate():
                        f = frames.get(t.ident)
                        if f is not None:
                            # names collide (several admin/prefetch threads)
                            dump[f"{t.name}-{t.ident}"] = _tb.format_stack(f)
                    body = json.dumps(dump).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "AdminServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="admin-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
