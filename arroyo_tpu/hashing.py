"""Deterministic 64-bit key hashing (host, vectorized).

The reference hashes routing keys with ahash via DataFusion's
``hash_utils::create_hashes`` (crates/arroyo-operator/src/context.rs:512) and
maps the u64 hash space onto subtasks with ``server_for_hash``
(crates/arroyo-types/src/lib.rs:621). Here we use a splitmix64-based mix that
is (a) deterministic across runs/processes (ahash is seeded per-process; our
checkpoint-rescale story needs stability), (b) vectorizable with NumPy uint64
lanes, and (c) cheap to recompute on restore.

String columns are hashed via per-unique blake2b (uniques are few relative to
rows in keyed streams; the unique pass also provides dictionary encoding).
"""

from __future__ import annotations

import hashlib

import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xBF58476D1CE4E5B9)
_C3 = np.uint64(0x94D049BB133111EB)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 arrays."""
    z = x + _C1
    z = (z ^ (z >> np.uint64(30))) * _C2
    z = (z ^ (z >> np.uint64(27))) * _C3
    return z ^ (z >> np.uint64(31))


_NULL_HASH = np.uint64(0x6E756C6C6E756C6C)  # fixed hash for None entries


def _hash_string_array(col: np.ndarray) -> np.ndarray:
    # pandas.factorize is hash-based (no sort), so it tolerates None mixed
    # with str (np.unique would raise on the comparison)
    import pandas as pd

    codes, uniques = pd.factorize(col, use_na_sentinel=True)
    hashes = np.empty(len(uniques) + 1, dtype=np.uint64)
    for i, s in enumerate(uniques):
        b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
        hashes[i] = np.uint64(
            int.from_bytes(hashlib.blake2b(b, digest_size=8).digest(), "little")
        )
    hashes[-1] = _NULL_HASH  # codes of -1 (None) index the last slot
    return hashes[codes]


def hash_column(col: np.ndarray) -> np.ndarray:
    """64-bit hash of one column (native C++ path when available,
    arroyo_tpu.native — same splitmix64 mix, differentially tested)."""
    from . import native

    if col.dtype == object or col.dtype.kind in "US":
        # numpy unicode/bytes arrays (e.g. CASE over string literals) hash
        # like object string columns, not like integers
        return splitmix64(_hash_string_array(col))
    if col.dtype.kind == "f":
        out = native.hash_f64(col.astype(np.float64))
        if out is not None:
            return out
        # canonicalize -0.0 and hash the bit pattern
        col = np.where(col == 0.0, 0.0, col)
        col = col.astype(np.float64).view(np.uint64)
        return splitmix64(col)
    if col.dtype == np.bool_:
        col = col.astype(np.uint64)
    else:
        col = col.astype(np.int64).view(np.uint64)
    out = native.hash_u64(col)
    if out is not None:
        return out
    return splitmix64(col)


def hash_columns(cols: list[np.ndarray]) -> np.ndarray:
    """Combined 64-bit hash of several columns (row-wise)."""
    from . import native

    if not cols:
        raise ValueError("need at least one key column")
    h = hash_column(cols[0])
    for c in cols[1:]:
        h2 = hash_column(c)
        combined = native.hash_combine(h, h2)
        if combined is not None:
            h = combined
        else:
            h = splitmix64(h ^ (h2 + _C1))
    return h


def servers_for_hashes(hashes: np.ndarray, n: int) -> np.ndarray:
    """Vectorized server_for_hash (reference arroyo-types/src/lib.rs:621)."""
    if n == 1:
        return np.zeros(len(hashes), dtype=np.int64)
    size = np.uint64(((1 << 64) - 1) // n + 1)
    return np.minimum(hashes // size, np.uint64(n - 1)).astype(np.int64)
