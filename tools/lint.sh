#!/usr/bin/env bash
# Repo lint + pipeline static analysis — the tools entry point CI uses.
#
#   tools/lint.sh              lint the arroyo_tpu package (AST invariant
#                              checks; see README "Static analysis")
#   tools/lint.sh --check      additionally `check` every smoke query and
#                              assert every queries_bad catalog entry still
#                              produces its annotated diagnostic
#   tools/lint.sh --metrics-catalog
#                              assert every metric name emitted in code
#                              appears in the README "Observability"
#                              catalog (grep-based; keeps docs honest)
#
# Exit non-zero on any unwaived lint finding or unexpected check result.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

python -m arroyo_tpu lint arroyo_tpu

if [[ "${1:-}" == "--metrics-catalog" ]]; then
    python - <<'EOF'
import glob, re, sys

# every prometheus series family this codebase can emit (string literals
# in the package; _bucket/_sum/_count suffixes are format-time derived).
# state/late families (ISSUE 7) carry no worker_ prefix — they describe
# job-level facts, not worker-loop counters — so they match explicitly
NAME_RE = re.compile(r"arroyo_(?:worker|checkpoint)_[a-z0-9_]+"
                     r"|arroyo_state_(?:rows|bytes)"
                     r"|arroyo_late_rows_total")
code_names: set[str] = set()
for p in glob.glob("arroyo_tpu/**/*.py", recursive=True):
    with open(p) as f:
        code_names |= set(NAME_RE.findall(f.read()))
with open("README.md") as f:
    doc_names = set(NAME_RE.findall(f.read()))
missing = sorted(code_names - doc_names)
if missing:
    print("metrics-catalog: emitted in code but missing from the README "
          "'Observability' catalog:")
    for m in missing:
        print(f"  {m}")
    sys.exit(1)
print(f"metrics-catalog: ok ({len(code_names)} metric names documented)")
EOF
fi

if [[ "${1:-}" == "--check" ]]; then
    python - <<'EOF'
import glob, os, re, sys
sys.path.insert(0, "tests/smoke")
import arroyo_tpu
arroyo_tpu._load_operators()
import udfs  # noqa: F401 - registers the smoke suite's UDFs/UDAFs
from arroyo_tpu.analysis import Severity, check_sql

def load(p):
    sql = open(p).read()
    return sql.replace("$input_dir", "tests/smoke/inputs").replace(
        "$output_path", "/tmp/lint_check_out.json")

failed = 0
for p in sorted(glob.glob("tests/smoke/queries/*.sql")):
    _pp, diags = check_sql(load(p))
    errs = [d for d in diags if d.severity == Severity.ERROR]
    if errs:
        failed += 1
        print(f"FAIL {p}: unexpectedly rejected: {[d.rule_id for d in errs]}")
for p in sorted(glob.glob("tests/smoke/queries_bad/*.sql")):
    m = re.match(r"--\s*(reject|warn):\s*(\S+)", open(p).read())
    mode, rule = m.group(1), m.group(2)
    _pp, diags = check_sql(load(p))
    errs = {d.rule_id for d in diags if d.severity == Severity.ERROR}
    ids = {d.rule_id for d in diags}
    ok = (rule in errs) if mode == "reject" else (not errs and rule in ids)
    if not ok:
        failed += 1
        print(f"FAIL {p}: expected {mode}:{rule}, got {sorted(ids)}")
print(f"check: {'FAILED' if failed else 'ok'} "
      f"({len(glob.glob('tests/smoke/queries/*.sql'))} accepted, "
      f"{len(glob.glob('tests/smoke/queries_bad/*.sql'))} catalog)")
sys.exit(1 if failed else 0)
EOF
fi
