#!/usr/bin/env bash
# Repo lint + pipeline static analysis — the tools entry point CI uses.
#
#   tools/lint.sh              lint the arroyo_tpu package (AST invariant
#                              checks; see README "Static analysis")
#   tools/lint.sh --check      additionally `check` every smoke query and
#                              assert every queries_bad catalog entry still
#                              produces its annotated diagnostic
#   tools/lint.sh --metrics-catalog
#                              assert every metric name emitted in code
#                              appears in the README "Observability"
#                              catalog (grep-based; keeps docs honest)
#   tools/lint.sh --events-catalog
#                              assert every EventCode the package can emit
#                              (obs/events.py EVENT_CODES, cross-checked
#                              against code-site literals) is documented in
#                              the README "Events & health" table
#   tools/lint.sh --mesh-tests
#                              run the tier-1 `mesh`-marked pytest subset
#                              on 8 emulated host devices (the fused
#                              shard_map segment path; same flag CI uses)
#   tools/lint.sh --rules-catalog
#                              assert every LR/AR rule id registered in the
#                              analysis engines (repo_lint.RULES,
#                              state_audit.RULES, trace_audit.RULES,
#                              concurrency_audit.RULES, plan-pass AR
#                              literals) appears in the README rule tables
#
#   LINT_SARIF=findings.sarif tools/lint.sh
#                              additionally write the lint findings as a
#                              SARIF 2.1.0 document (CI renders them as
#                              inline annotations); exit codes unchanged —
#                              the plain lint run below still gates
#
# Exit non-zero on any unwaived lint finding or unexpected check result.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

if [[ -n "${LINT_SARIF:-}" ]]; then
    # ONE analysis run gates and emits the annotations (--sarif keeps the
    # lint exit code); the human-readable report is re-rendered only on
    # failure, when someone actually reads it
    rc=0
    python -m arroyo_tpu lint --sarif arroyo_tpu > "$LINT_SARIF" || rc=$?
    if [[ $rc -ne 0 ]]; then
        python -m arroyo_tpu lint arroyo_tpu || true
        exit "$rc"
    fi
    echo "lint clean (SARIF written to $LINT_SARIF)"
else
    python -m arroyo_tpu lint arroyo_tpu
fi

if [[ "${1:-}" == "--metrics-catalog" ]]; then
    python - <<'EOF'
import glob, re, sys

# every prometheus series family this codebase can emit (string literals
# in the package; _bucket/_sum/_count suffixes are format-time derived).
# state/late families (ISSUE 7) carry no worker_ prefix — they describe
# job-level facts, not worker-loop counters — so they match explicitly
NAME_RE = re.compile(r"arroyo_(?:worker|checkpoint)_[a-z0-9_]+"
                     r"|arroyo_state_(?:rows|bytes)"
                     r"|arroyo_late_rows_total"
                     r"|arroyo_job_health"
                     r"|arroyo_autoscaler_[a-z0-9_]+"
                     r"|arroyo_segment_[a-z0-9_]+"
                     r"|arroyo_spill_[a-z0-9_]+"
                     r"|arroyo_fleet_[a-z0-9_]+"
                     r"|arroyo_bad_records_total"
                     r"|arroyo_mesh_[a-z0-9_]+"
                     r"|arroyo_events_total")
code_names: set[str] = set()
for p in glob.glob("arroyo_tpu/**/*.py", recursive=True):
    with open(p) as f:
        code_names |= set(NAME_RE.findall(f.read()))
with open("README.md") as f:
    doc_names = set(NAME_RE.findall(f.read()))
missing = sorted(code_names - doc_names)
if missing:
    print("metrics-catalog: emitted in code but missing from the README "
          "'Observability' catalog:")
    for m in missing:
        print(f"  {m}")
    sys.exit(1)
print(f"metrics-catalog: ok ({len(code_names)} metric names documented)")
EOF
fi

if [[ "${1:-}" == "--events-catalog" ]]; then
    python - <<'EOF'
import ast, glob, re, sys

from arroyo_tpu.obs.events import EVENT_CODES, LEVELS

# every string literal used as an event code at a recorder.record()/
# JobController._event()/Autoscaler._emit() call site must be declared in
# EVENT_CODES, and every declared code must be documented in the README
# "Events & health" table (AST-walked so formatting can't hide a call site)
CODE_RE = re.compile(r"^[A-Z][A-Z_]+$")
EVENT_CALLS = ("record", "_event", "_emit")
code_sites: set[str] = set()
for p in glob.glob("arroyo_tpu/**/*.py", recursive=True):
    with open(p) as f:
        tree = ast.parse(f.read(), p)
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in EVENT_CALLS):
            continue
        recv = n.func.value
        recv_name = getattr(recv, "id", getattr(recv, "attr", ""))
        if n.func.attr == "record" and "event" not in recv_name.lower() \
                and recv_name != "recorder":
            continue  # trace/metric .record() calls are out of scope
        for a in n.args:
            if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                    and CODE_RE.match(a.value) and a.value not in LEVELS:
                code_sites.add(a.value)
undeclared = sorted(c for c in code_sites if c not in EVENT_CODES)
if undeclared:
    print("events-catalog: emitted codes missing from obs.events.EVENT_CODES:")
    for c in undeclared:
        print(f"  {c}")
    sys.exit(1)
with open("README.md") as f:
    readme = f.read()
missing = sorted(c for c in EVENT_CODES if f"`{c}`" not in readme)
if missing:
    print("events-catalog: EventCodes missing from the README "
          "'Events & health' table:")
    for c in missing:
        print(f"  {c}")
    sys.exit(1)
print(f"events-catalog: ok ({len(EVENT_CODES)} event codes documented, "
      f"{len(code_sites)} emitted in code)")
EOF
fi

if [[ "${1:-}" == "--rules-catalog" ]]; then
    python - <<'EOF'
import ast, re, sys

from arroyo_tpu.analysis import (AUDIT_RULES, CONCURRENCY_RULES, LINT_RULES,
                                 TRACE_RULES)

# every rule id an analysis engine can emit: the four registered rule
# tables, plus AR-series literals AST-walked out of the plan passes (they
# register by function, not id) and the FS-series fsck rules (emitted as
# literals in state/integrity.py) — each must appear in a README rule table
rule_ids = {rid for rid, _sev, _fn in LINT_RULES} | set(AUDIT_RULES) \
    | set(TRACE_RULES) | set(CONCURRENCY_RULES)
ID_RE = re.compile(r"^(AR|LR|FS)\d{3}$")
for p in ("arroyo_tpu/analysis/plan_passes.py",
          "arroyo_tpu/analysis/plan_diff.py",
          "arroyo_tpu/analysis/trace_audit.py",
          "arroyo_tpu/analysis/__init__.py",
          "arroyo_tpu/state/integrity.py"):
    with open(p) as f:
        tree = ast.parse(f.read(), p)
    for n in ast.walk(tree):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and ID_RE.match(n.value):
            rule_ids.add(n.value)
with open("README.md") as f:
    readme = f.read()
missing = sorted(r for r in rule_ids if f"`{r}`" not in readme)
if missing:
    print("rules-catalog: rule ids registered in code but missing from the "
          "README 'Static analysis' tables:")
    for r in missing:
        print(f"  {r}")
    sys.exit(1)
print(f"rules-catalog: ok ({len(rule_ids)} rule ids documented)")
EOF
fi

if [[ "${1:-}" == "--mesh-tests" ]]; then
    # tests/conftest.py forces the same flag before backend init, but
    # setting it here keeps the subset honest when invoked standalone
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        python -m pytest tests -q -m mesh -p no:cacheprovider
fi

if [[ "${1:-}" == "--check" ]]; then
    python - <<'EOF'
import glob, os, re, sys
sys.path.insert(0, "tests/smoke")
import arroyo_tpu
arroyo_tpu._load_operators()
import udfs  # noqa: F401 - registers the smoke suite's UDFs/UDAFs
from arroyo_tpu.analysis import Severity, check_sql

def load(p):
    sql = open(p).read()
    return sql.replace("$input_dir", "tests/smoke/inputs").replace(
        "$output_path", "/tmp/lint_check_out.json")

failed = 0
for p in sorted(glob.glob("tests/smoke/queries/*.sql")):
    _pp, diags = check_sql(load(p))
    errs = [d for d in diags if d.severity == Severity.ERROR]
    if errs:
        failed += 1
        print(f"FAIL {p}: unexpectedly rejected: {[d.rule_id for d in errs]}")
for p in sorted(glob.glob("tests/smoke/queries_bad/*.sql")):
    m = re.match(r"--\s*(reject|warn):\s*(\S+)", open(p).read())
    mode, rule = m.group(1), m.group(2)
    _pp, diags = check_sql(load(p))
    errs = {d.rule_id for d in diags if d.severity == Severity.ERROR}
    ids = {d.rule_id for d in diags}
    ok = (rule in errs) if mode == "reject" else (not errs and rule in ids)
    if not ok:
        failed += 1
        print(f"FAIL {p}: expected {mode}:{rule}, got {sorted(ids)}")
print(f"check: {'FAILED' if failed else 'ok'} "
      f"({len(glob.glob('tests/smoke/queries/*.sql'))} accepted, "
      f"{len(glob.glob('tests/smoke/queries_bad/*.sql'))} catalog)")
sys.exit(1 if failed else 0)
EOF
fi
