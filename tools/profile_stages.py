#!/usr/bin/env python
"""Per-operator cost profile of the q7 bench pipeline.

Runs bench.run_config with the runtime profiler (arroyo_tpu/obs/profile.py
— the same attribution `arroyo_tpu explain` renders for live jobs) and
prints the per-operator self-time / busy% / state / hot-key table, so a
perf win can be attributed to the operator that earned it.

`--stages` additionally monkey-patches timing wrappers around the
fine-grained hot-path stages (source generation, slot-aggregate update,
window close dispatch/fetch, emission) for intra-operator drill-down —
the methodology that found round 2's fetch-latency stall. Nested keys
overlap: agg_process_total includes agg_update_chunk, which includes
dir_lookup.

Usage:
    python tools/profile_stages.py [events] [batch_size] [--stages]
    ARROYO_BENCH_PLATFORM=cpu python tools/profile_stages.py 200000

Runs on the default platform (the real TPU chip under the driver tunnel)
unless ARROYO_BENCH_PLATFORM overrides it.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import arroyo_tpu
from arroyo_tpu import config as cfg


def print_profile(job_id: str) -> None:
    from arroyo_tpu.metrics import registry
    from arroyo_tpu.obs.profile import job_profile

    prof = job_profile(registry.job_metrics(job_id))
    print("\nper-operator cost profile (obs/profile.py):")
    for op, p in sorted(prof.items(),
                        key=lambda kv: -sum((kv[1]["self_time"] or {}).values())):
        st = p.get("self_time") or {}
        cats = "  ".join(f"{c} {v * 1000:9.1f}ms" for c, v in
                         sorted(st.items(), key=lambda kv: -kv[1]) if v)
        line = f"  {op:34s} busy {p.get('busy_pct') or 0:5.1f}%  {cats}"
        if p.get("self_us_per_row") is not None:
            line += f"  {p['self_us_per_row']:.2f}us/row"
        print(line)
        rows = p.get("state_rows") or {}
        if any(rows.values()):
            print("  " + " " * 34 + "state: " + "  ".join(
                f"{t}={rows[t]:,}r/{(p.get('state_bytes') or {}).get(t, 0):,}B"
                for t in sorted(rows)))
        hot = p.get("hot_keys") or []
        if hot:
            print("  " + " " * 34 + "hot:   " + "  ".join(
                f"{e['key'][:8]} {100 * e.get('share', 0):.1f}%"
                for e in hot[:5]))


def main() -> None:
    if os.environ.get("ARROYO_BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["ARROYO_BENCH_PLATFORM"])
    import bench

    args = [a for a in sys.argv[1:] if a != "--stages"]
    stages = "--stages" in sys.argv[1:]
    events = int(args[0]) if len(args) > 0 else 1_000_000
    batch = int(args[1]) if len(args) > 1 else 32_768

    arroyo_tpu._load_operators()
    cfg.update({
        "pipeline.source-batch-size": batch,
        "pipeline.chaining.enabled": True,
        "device.batch-capacity": batch,
        "device.table-capacity": 65536,
        "device.emit-capacity": 8192,
        "profile.enabled": True,
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
    })

    T: dict[str, float] = {}
    C: dict[str, int] = {}

    def wrap(obj, name, key):
        orig = getattr(obj, name)

        def timed(*a, **k):
            t0 = time.perf_counter()
            r = orig(*a, **k)
            T[key] = T.get(key, 0.0) + (time.perf_counter() - t0)
            C[key] = C.get(key, 0) + 1
            return r

        setattr(obj, name, timed)

    if stages:
        from arroyo_tpu.connectors import nexmark as nx
        from arroyo_tpu.operators import builtin as bi
        from arroyo_tpu.ops import slot_agg as sa
        from arroyo_tpu.windows import tumbling as tw

        wrap(nx.NexmarkSource, "_generate", "source_generate")
        wrap(bi.ValueOperator, "process_batch", "value_op_total")
        wrap(bi.KeyOperator, "process_batch", "key_op_total")
        wrap(tw.TumblingAggregate, "process_batch", "agg_process_total")
        wrap(sa.SlotAggregator, "_update_chunk", "agg_update_chunk")
        wrap(sa.BinSlotDirectory, "lookup_or_assign", "dir_lookup")
        wrap(sa.SlotAggregator, "extract_start", "close_dispatch")
        wrap(sa.SlotExtractHandle, "result", "close_fetch_materialize")
        wrap(tw.TumblingAggregate, "_emit_entries", "emit_entries")

    bench.run_config("q7", bench.build_q7, "jax", 50_000, batch)  # warmup
    T.clear()
    C.clear()
    wall, _rows, _lat, _walls = bench.run_config(
        "q7", bench.build_q7, "jax", events, batch)
    print(f"\n{events} events in {wall:.2f}s = {events / wall:,.0f} ev/s")
    print_profile("bench-q7-jax")
    if stages:
        print("\nfine-grained stage wraps (--stages; nested keys overlap):")
        for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
            print(f"  {k:26s} {v * 1000:8.1f} ms   x{C[k]}")


if __name__ == "__main__":
    main()
