#!/usr/bin/env python
"""Per-stage wall-clock profile of the q7 bench pipeline.

Monkey-patches timing wrappers around the hot-path stages (source
generation, value/key operators, slot-aggregate update, window close
dispatch/fetch, emission) and runs bench.run_config. Nested keys overlap:
agg_process_total includes agg_update_chunk, which includes dir_lookup.

Usage:
    python tools/profile_stages.py [events] [batch_size]
    ARROYO_BENCH_PLATFORM=cpu python tools/profile_stages.py 200000

Runs on the default platform (the real TPU chip under the driver tunnel)
unless ARROYO_BENCH_PLATFORM overrides it. This is the methodology that
found round 2's fetch-latency stall; keep it working as the bench evolves.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import arroyo_tpu
from arroyo_tpu import config as cfg


def main() -> None:
    if os.environ.get("ARROYO_BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["ARROYO_BENCH_PLATFORM"])
    import bench

    events = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 32_768

    arroyo_tpu._load_operators()
    cfg.update({
        "pipeline.source-batch-size": batch,
        "pipeline.chaining.enabled": True,
        "device.batch-capacity": batch,
        "device.table-capacity": 65536,
        "device.emit-capacity": 8192,
        "checkpoint.storage-url": "/tmp/arroyo-tpu-bench/checkpoints",
    })

    T: dict[str, float] = {}
    C: dict[str, int] = {}

    def wrap(obj, name, key):
        orig = getattr(obj, name)

        def timed(*a, **k):
            t0 = time.perf_counter()
            r = orig(*a, **k)
            T[key] = T.get(key, 0.0) + (time.perf_counter() - t0)
            C[key] = C.get(key, 0) + 1
            return r

        setattr(obj, name, timed)

    from arroyo_tpu.connectors import nexmark as nx
    from arroyo_tpu.operators import builtin as bi
    from arroyo_tpu.ops import slot_agg as sa
    from arroyo_tpu.windows import tumbling as tw

    wrap(nx.NexmarkSource, "_generate", "source_generate")
    wrap(bi.ValueOperator, "process_batch", "value_op_total")
    wrap(bi.KeyOperator, "process_batch", "key_op_total")
    wrap(tw.TumblingAggregate, "process_batch", "agg_process_total")
    wrap(sa.SlotAggregator, "_update_chunk", "agg_update_chunk")
    wrap(sa.BinSlotDirectory, "lookup_or_assign", "dir_lookup")
    wrap(sa.SlotAggregator, "extract_start", "close_dispatch")
    wrap(sa.SlotExtractHandle, "result", "close_fetch_materialize")
    wrap(tw.TumblingAggregate, "_emit_entries", "emit_entries")

    bench.run_config("q7", bench.build_q7, "jax", 50_000, batch)  # warmup
    T.clear()
    C.clear()
    wall, _rows, _lat, _walls = bench.run_config(
        "q7", bench.build_q7, "jax", events, batch)
    print(f"\n{events} events in {wall:.2f}s = {events / wall:,.0f} ev/s")
    for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
        print(f"  {k:26s} {v * 1000:8.1f} ms   x{C[k]}")


if __name__ == "__main__":
    main()
